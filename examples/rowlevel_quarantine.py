"""Streaming row-level egress: one pass over a large table splits it
into a CLEAN parquet file and a QUARANTINE parquet file — every row
annotated with per-constraint outcomes and provenance — while the same
scan computes the aggregate verification metrics (docs/EGRESS.md).

The table is autosized for the current host with the bench's probe
(bench.py: ``probe_host``/``autosize``): the nominal shape is 100M rows
and small CI hosts scale down instead of thrashing. The pipeline is
honest about passes — for a mask/predicate suite the split streams out
of the SAME single traversal the metrics ride (``engine.data_passes``
rises by exactly 1).

Run: python examples/rowlevel_quarantine.py
"""

import os
import sys
import tempfile

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import _sized, autosize, probe_host  # noqa: E402
from deequ_tpu import (  # noqa: E402
    Check,
    CheckLevel,
    Dataset,
    VerificationSuite,
    config,
)
from deequ_tpu.egress import RowLevelSink  # noqa: E402
from deequ_tpu.telemetry import get_telemetry  # noqa: E402

NOMINAL_ROWS = 100_000_000


def make_events(n: int) -> Dataset:
    """Synthetic event stream with realistic dirt: ~2% null emails,
    ~5% malformed addresses, ~1% negative amounts."""
    rng = np.random.default_rng(20260805)
    amount = rng.gamma(2.0, 40.0, n)
    amount[rng.random(n) < 0.01] *= -1.0
    user = rng.integers(0, max(1, n // 50), n)
    domain = np.where(rng.random(n) < 0.05, "bad address", "ex.com")
    email = np.char.add(
        np.char.add("u", user.astype("U12")), np.char.add("@", domain)
    ).astype(object)
    email[rng.random(n) < 0.02] = None
    return Dataset.from_arrow(
        pa.table(
            {
                "event_id": pa.array(np.arange(n, dtype=np.int64)),
                "amount": pa.array(amount),
                "email": pa.array(email, type=pa.string()),
            }
        )
    )


def main() -> None:
    sizing = autosize(probe_host())
    n = _sized(NOMINAL_ROWS, sizing, streamed=True)
    data = make_events(n)
    out_dir = tempfile.mkdtemp(prefix="deequ_tpu_egress_")

    checks = [
        Check(CheckLevel.ERROR, "event hygiene")
        .is_complete("email")
        .has_pattern("email", r"@ex\.com$")
        .satisfies("amount >= 0", "amount_non_negative")
    ]
    sink = RowLevelSink(out_dir, tenant="examples", run_id="quarantine-demo")

    tm = get_telemetry()
    passes_before = tm.counter("engine.data_passes").value
    # device cache off: the source streams through once, host memory
    # stays O(batch), and the split is written as the scan folds
    with config.configure(device_cache_bytes=0):
        result = (
            VerificationSuite()
            .on_data(data)
            .add_checks(checks)
            .with_row_level_sink(sink)
            .run()
        )
    passes = tm.counter("engine.data_passes").value - passes_before

    report = result.row_level_egress
    print(f"rows           : {n:,}")
    print(f"status         : {report.status}")
    print(f"clean          : {report.rows_clean:,} -> {report.clean_dir}")
    print(
        f"quarantined    : {report.rows_quarantined:,} -> "
        f"{report.quarantine_dir}"
    )
    print(
        f"wire           : {report.bytes_raw:,} raw -> "
        f"{report.bytes_encoded:,} encoded bytes"
    )
    print(f"data passes    : {passes}")

    # the partitioning invariant: clean + quarantined == input,
    # and a mask/predicate suite needed exactly ONE traversal
    assert report.status == "complete"
    assert report.rows_clean + report.rows_quarantined == n
    assert passes == 1, passes
    clean = pq.read_table(report.clean_dir)
    quarantine = pq.read_table(report.quarantine_dir)
    assert len(clean) + len(quarantine) == n
    # every quarantined row names what it failed
    assert all(quarantine.column("__failed_constraints__").to_pylist())
    print("clean + quarantined == input; one pass — OK")


if __name__ == "__main__":
    main()
