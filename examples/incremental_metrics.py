"""Incremental metrics: per-partition states merged without re-scanning.

Reference example: IncrementalMetrics example (SURVEY.md §2.5, §3.2):
compute mergeable states per dataset partition (e.g. per day), persist
them, and later combine metrics across partitions monoidally — no data
pass over old partitions.
"""

import os
import sys
import tempfile

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)  # allow running from a source checkout without installing

import numpy as np

from deequ_tpu import (
    ApproxCountDistinct,
    Completeness,
    Dataset,
    FileSystemStateProvider,
    Mean,
    Size,
)
from deequ_tpu.analyzers import AnalysisRunner


def main():
    analyzers = [Size(), Mean("amount"), Completeness("amount"),
                 ApproxCountDistinct("customer")]

    def day(seed, n):
        r = np.random.default_rng(seed)
        return Dataset.from_pydict(
            {
                "amount": r.gamma(2.0, 50.0, n),
                "customer": r.integers(0, 5000, n),
            }
        )

    with tempfile.TemporaryDirectory() as root:
        providers = []
        # each "day" computes and persists its own states
        for i, n in enumerate((30_000, 45_000, 25_000)):
            provider = FileSystemStateProvider(os.path.join(root, f"day{i}"))
            AnalysisRunner.do_analysis_run(
                day(i, n), analyzers, save_states_with=provider
            )
            providers.append(provider)
            print(f"day {i}: persisted states for {n} rows")

        # later: metrics across ALL days from states alone (no data scan)
        schema = day(0, 1).schema
        context = AnalysisRunner.run_on_aggregated_states(
            schema, analyzers, providers
        )
        print("metrics across all days (no re-scan):")
        for record in context.success_metrics_as_records():
            print(f"  {record['name']}({record['instance']}) = "
                  f"{record['value']:.3f}")
        assert context.metric(Size()).value.get() == 100_000.0


if __name__ == "__main__":
    main()
