"""Pluggable storage backends (io/storage.py): the HdfsStateProvider
URI analog (VERDICT r3 missing #5). mem:// exercises every remote
branch; plain paths keep the direct local layout (backward compatible
with pre-r4 state directories)."""

import numpy as np
import pytest

from deequ_tpu import Dataset
from deequ_tpu.analyzers import AnalysisRunner, Mean, Size
from deequ_tpu.io.state_provider import FileSystemStateProvider
from deequ_tpu.io.storage import (
    LocalStorage,
    MemoryStorage,
    register_storage_scheme,
    storage_for,
)
from deequ_tpu.repository.base import AnalysisResult, ResultKey
from deequ_tpu.repository.fs import FileSystemMetricsRepository


def test_storage_dispatch(tmp_path):
    assert isinstance(storage_for(str(tmp_path)), LocalStorage)
    assert isinstance(storage_for("mem://t1"), MemoryStorage)
    assert isinstance(
        storage_for(f"file://{tmp_path}"), LocalStorage
    )
    with pytest.raises(ValueError, match="register_storage_scheme"):
        storage_for("s3://bucket/prefix")


def test_local_storage_atomic_layout(tmp_path):
    s = storage_for(str(tmp_path))
    s.write_bytes("a/b.bin", b"payload")
    assert (tmp_path / "a" / "b.bin").read_bytes() == b"payload"
    assert s.read_bytes("a/b.bin") == b"payload"
    assert s.read_bytes("missing") is None
    assert s.list_keys() == ["a/b.bin"]


def test_memory_storage_shared_namespace():
    a, b = storage_for("mem://shared-x"), storage_for("mem://shared-x")
    a.write_bytes("k", b"v")
    assert b.read_bytes("k") == b"v"
    assert storage_for("mem://other").read_bytes("k") is None


def test_state_provider_over_memory_uri():
    ds1 = Dataset.from_pydict({"x": [1.0, 2.0, 3.0]})
    ds2 = Dataset.from_pydict({"x": [4.0, 5.0]})
    provider = FileSystemStateProvider("mem://states-test")
    AnalysisRunner.do_analysis_run(
        ds1, [Mean("x"), Size()], save_states_with=provider
    )
    # a second provider instance over the same URI sees the states
    reloaded = FileSystemStateProvider("mem://states-test")
    ctx = AnalysisRunner.do_analysis_run(
        ds2, [Mean("x"), Size()], aggregate_with=reloaded
    )
    assert ctx.metric(Mean("x")).value.get() == pytest.approx(3.0)
    assert ctx.metric(Size()).value.get() == 5.0


def test_metrics_repository_over_memory_uri():
    ds = Dataset.from_pydict({"x": [1.0, 2.0]})
    ctx = AnalysisRunner.do_analysis_run(ds, [Size()])
    repo = FileSystemMetricsRepository("mem://repo-test/metrics.json")
    repo.save(AnalysisResult(ResultKey.of(10, {"env": "t"}), ctx))
    again = FileSystemMetricsRepository("mem://repo-test/metrics.json")
    loaded = again.load_by_key(ResultKey.of(10, {"env": "t"}))
    assert loaded is not None
    assert loaded.analyzer_context.metric(Size()).value.get() == 2.0


def test_custom_scheme_registration(tmp_path):
    calls = []

    def factory(uri):
        calls.append(uri)
        return LocalStorage(str(tmp_path / "fake-remote"))

    register_storage_scheme("fakefs", factory)
    provider = FileSystemStateProvider("fakefs://bucket/x")
    ds = Dataset.from_pydict({"x": [1.0]})
    AnalysisRunner.do_analysis_run(
        ds, [Size()], save_states_with=provider
    )
    assert calls == ["fakefs://bucket/x"]
    assert (tmp_path / "fake-remote" / "index.json").exists()


def test_local_state_layout_backward_compatible(tmp_path):
    """Pre-r4 state dirs had state-<digest>.npz + index.json at the
    top level; the storage rewrite must keep that exact layout."""
    provider = FileSystemStateProvider(str(tmp_path))
    ds = Dataset.from_pydict({"x": [1.0, 2.0]})
    AnalysisRunner.do_analysis_run(
        ds, [Mean("x")], save_states_with=provider
    )
    names = sorted(p.name for p in tmp_path.iterdir())
    assert "index.json" in names
    assert any(
        n.startswith("state-") and n.endswith(".npz") for n in names
    )


def test_uri_repository_requires_root_segment():
    with pytest.raises(ValueError, match="scheme://root/key"):
        FileSystemMetricsRepository("mem://metrics.json")


def test_list_keys_skips_inflight_temps(tmp_path):
    s = storage_for(str(tmp_path))
    s.write_bytes("real.bin", b"x")
    (tmp_path / "real.bin.tmp.123.456").write_bytes(b"partial")
    (tmp_path / "stale.tmp").write_bytes(b"partial")
    assert s.list_keys() == ["real.bin"]
