"""Analyzer state types: fixed-shape pytrees forming commutative monoids.

Reference: the ``State[S]`` family in
``src/main/scala/com/amazon/deequ/analyzers/*.scala`` (SURVEY.md §2.2) —
``NumMatches``, ``NumMatchesAndCount``, ``MeanState``, ``MinState``,
``MaxState``, ``SumState``, ``StandardDeviationState`` (Welford),
``CorrelationState``. Each state here is a NamedTuple of scalars/arrays
(hence automatically a JAX pytree), with a dataset-independent ``merge``
so persisted states can be combined without touching data
(``runOnAggregatedStates``, SURVEY.md §3.2).

All merges are commutative and associative; identities are provided by
the analyzers' ``ScanOps.init``.
"""

from __future__ import annotations

from typing import Dict, NamedTuple, Type

import jax.numpy as jnp
import numpy as np


def _facc(value: float = 0.0):
    """Scalar in the configured accumulation float dtype (see
    deequ_tpu.config: f64 default; f32 avoids emulated f64 scalar ops on
    TPU at the cost of cross-batch rounding)."""
    from deequ_tpu import config

    return config.options().accumulation_float()(value)


def _iacc(value: int = 0):
    """Count scalar — always int64: counts are exact row-count semantics
    regardless of the float accumulation knob (i64 scalar adds are a few
    emulated ops per batch, never per element)."""
    return np.int64(value)


def nan_largest_min(a, b):
    """Min under Spark's ordering, where NaN ranks ABOVE every value
    including +inf (SURVEY.md §2.2 numeric semantics): NaN loses to any
    non-NaN operand; min(NaN, NaN) = NaN. A plain ``jnp.minimum``
    propagates NaN, which would let one all-NaN shard poison a merged
    Minimum. The MAX side needs no counterpart — NaN-propagating
    ``jnp.maximum`` IS Spark's max (NaN is the largest value)."""
    return jnp.where(
        jnp.isnan(a), b, jnp.where(jnp.isnan(b), a, jnp.minimum(a, b))
    )


class NumMatches(NamedTuple):
    num_matches: jnp.ndarray  # int64 scalar

    @staticmethod
    def identity() -> "NumMatches":
        return NumMatches(_iacc(0))

    @staticmethod
    def merge(a: "NumMatches", b: "NumMatches") -> "NumMatches":
        return NumMatches(a.num_matches + b.num_matches)


class NumMatchesAndCount(NamedTuple):
    num_matches: jnp.ndarray
    count: jnp.ndarray

    @staticmethod
    def identity() -> "NumMatchesAndCount":
        return NumMatchesAndCount(_iacc(0), _iacc(0))

    @staticmethod
    def merge(
        a: "NumMatchesAndCount", b: "NumMatchesAndCount"
    ) -> "NumMatchesAndCount":
        return NumMatchesAndCount(
            a.num_matches + b.num_matches, a.count + b.count
        )

    @property
    def metric_value(self):
        return self.num_matches / self.count


class SumState(NamedTuple):
    sum_value: jnp.ndarray  # float64
    count: jnp.ndarray  # int64; tracks emptiness

    @staticmethod
    def identity() -> "SumState":
        return SumState(_facc(0.0), _iacc(0))

    @staticmethod
    def merge(a: "SumState", b: "SumState") -> "SumState":
        return SumState(a.sum_value + b.sum_value, a.count + b.count)


class MeanState(NamedTuple):
    total: jnp.ndarray  # float64
    count: jnp.ndarray  # int64

    @staticmethod
    def identity() -> "MeanState":
        return MeanState(_facc(0.0), _iacc(0))

    @staticmethod
    def merge(a: "MeanState", b: "MeanState") -> "MeanState":
        return MeanState(a.total + b.total, a.count + b.count)


class MinState(NamedTuple):
    min_value: jnp.ndarray  # float64
    count: jnp.ndarray

    @staticmethod
    def identity() -> "MinState":
        # always f64: min/max carries no accumulation error (see
        # basic._mmin) and must not round large ints. NaN, not +inf:
        # under the Spark ordering NaN is nan_largest_min's identity —
        # +inf would beat an all-NaN column's NaN and surface as a
        # bogus min of inf. count==0 guards the truly-empty case.
        return MinState(np.float64(np.nan), _iacc(0))

    @staticmethod
    def merge(a: "MinState", b: "MinState") -> "MinState":
        return MinState(
            nan_largest_min(a.min_value, b.min_value), a.count + b.count
        )


class MaxState(NamedTuple):
    max_value: jnp.ndarray
    count: jnp.ndarray

    @staticmethod
    def identity() -> "MaxState":
        return MaxState(np.float64(-np.inf), _iacc(0))

    @staticmethod
    def merge(a: "MaxState", b: "MaxState") -> "MaxState":
        return MaxState(jnp.maximum(a.max_value, b.max_value), a.count + b.count)


class StandardDeviationState(NamedTuple):
    """Welford-style mergeable variance accumulator (n, avg, m2)."""

    n: jnp.ndarray  # float64
    avg: jnp.ndarray
    m2: jnp.ndarray

    @staticmethod
    def identity() -> "StandardDeviationState":
        # always f64: n carries an exact count (config.py promises the
        # accumulation knob never rounds counts) and the moments are
        # per-batch scalars — f64 here costs a few emulated ops per
        # batch, never per element
        z = np.float64(0.0)
        return StandardDeviationState(z, z, z)

    @staticmethod
    def merge(
        a: "StandardDeviationState", b: "StandardDeviationState"
    ) -> "StandardDeviationState":
        n = a.n + b.n
        safe_n = jnp.maximum(n, 1.0)
        delta = b.avg - a.avg
        avg = jnp.where(n > 0, a.avg + delta * b.n / safe_n, 0.0)
        m2 = a.m2 + b.m2 + delta * delta * a.n * b.n / safe_n
        return StandardDeviationState(n, avg, m2)


class CorrelationState(NamedTuple):
    """Mergeable Pearson correlation accumulator (Spark Corr-style)."""

    n: jnp.ndarray
    x_avg: jnp.ndarray
    y_avg: jnp.ndarray
    ck: jnp.ndarray  # co-moment
    x_mk: jnp.ndarray
    y_mk: jnp.ndarray

    @staticmethod
    def identity() -> "CorrelationState":
        z = np.float64(0.0)  # see StandardDeviationState.identity
        return CorrelationState(z, z, z, z, z, z)

    @staticmethod
    def merge(a: "CorrelationState", b: "CorrelationState") -> "CorrelationState":
        n = a.n + b.n
        safe_n = jnp.maximum(n, 1.0)
        dx = b.x_avg - a.x_avg
        dy = b.y_avg - a.y_avg
        frac = a.n * b.n / safe_n
        x_avg = jnp.where(n > 0, a.x_avg + dx * b.n / safe_n, 0.0)
        y_avg = jnp.where(n > 0, a.y_avg + dy * b.n / safe_n, 0.0)
        ck = a.ck + b.ck + dx * dy * frac
        x_mk = a.x_mk + b.x_mk + dx * dx * frac
        y_mk = a.y_mk + b.y_mk + dy * dy * frac
        return CorrelationState(n, x_avg, y_avg, ck, x_mk, y_mk)


class SumPairState(NamedTuple):
    """For RatioOfSums: two sums plus a row count."""

    sum_a: jnp.ndarray
    sum_b: jnp.ndarray
    count: jnp.ndarray

    @staticmethod
    def identity() -> "SumPairState":
        return SumPairState(_facc(0.0), _facc(0.0), _iacc(0))

    @staticmethod
    def merge(a: "SumPairState", b: "SumPairState") -> "SumPairState":
        return SumPairState(
            a.sum_a + b.sum_a, a.sum_b + b.sum_b, a.count + b.count
        )


class DataTypeHistogram(NamedTuple):
    """Counts per inferred type bucket, packed as one int64[6] vector:
    [null, fractional, integral, boolean, string, (reserved)].
    Merge = elementwise sum (a psum across the mesh)."""

    counts: jnp.ndarray  # int64[6]

    NULL = 0
    FRACTIONAL = 1
    INTEGRAL = 2
    BOOLEAN = 3
    STRING = 4

    @staticmethod
    def identity() -> "DataTypeHistogram":
        return DataTypeHistogram(np.zeros(6, dtype=np.int64))

    @staticmethod
    def merge(a: "DataTypeHistogram", b: "DataTypeHistogram") -> "DataTypeHistogram":
        return DataTypeHistogram(a.counts + b.counts)


class ApproxCountDistinctState(NamedTuple):
    """HLL registers (int8[m]; rho <= 33 — narrow dtype quarters the
    wire bytes when states cross the tunnel); merge = elementwise max
    (SURVEY.md §2.3: the reference's StatefulHyperloglogPlus merges
    register words by word-wise max — here the registers are a device
    vector and the merge is a ``lax.max`` all-reduce). States persisted
    as int32 by older builds promote cleanly on merge."""

    registers: jnp.ndarray  # int8[m]

    @staticmethod
    def merge(
        a: "ApproxCountDistinctState", b: "ApproxCountDistinctState"
    ) -> "ApproxCountDistinctState":
        return ApproxCountDistinctState(jnp.maximum(a.registers, b.registers))


# (The KLL sketch state is host-side — deequ_tpu.sketches.kll.KLLSketchState —
# because its compaction is data-dependent; its device-side per-batch
# pre-compaction output is transient and never persisted.)

# Persisted-state format versions: bump when a state's INTERPRETATION
# changes (not just its shape), so stale states are rejected instead of
# silently merged wrong. v2 of ApproxCountDistinctState: integral
# columns hash the raw int64 payload (v1 float-canonicalized, colliding
# above 2^53) — v1 registers place the same values in different
# registers, so a v1+v2 max-merge would double-count.
STATE_FORMAT_VERSIONS: Dict[str, int] = {
    "ApproxCountDistinctState": 2,
}


# Registry used by state serde (deequ_tpu.io.state_provider).
STATE_TYPES: Dict[str, Type] = {
    cls.__name__: cls
    for cls in (
        NumMatches,
        NumMatchesAndCount,
        SumState,
        MeanState,
        MinState,
        MaxState,
        StandardDeviationState,
        CorrelationState,
        SumPairState,
        DataTypeHistogram,
        ApproxCountDistinctState,
    )
}
