"""Basic verification: declare checks, run one fused scan, read results.

Reference example: the reference's basic-usage example
(``examples/`` — SURVEY.md §2.5): define a Check with several
constraints, run the suite, inspect constraint results.
"""

import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)  # allow running from a source checkout without installing

import numpy as np

from deequ_tpu import Check, CheckLevel, CheckStatus, Dataset, VerificationSuite


def main():
    rng = np.random.default_rng(0)
    data = Dataset.from_pydict(
        {
            "id": np.arange(10_000),
            "product": rng.choice(["thingA", "thingB", "thingC"], 10_000),
            "value": rng.normal(100.0, 15.0, 10_000),
            "priority": rng.choice(["high", "low", None], 10_000, p=[0.3, 0.6, 0.1]),
        }
    )

    result = (
        VerificationSuite()
        .on_data(data)
        .add_check(
            Check(CheckLevel.ERROR, "integrity checks")
            .has_size(lambda s: s == 10_000)
            .is_complete("id")
            .is_unique("id")
            .is_contained_in("product", ["thingA", "thingB", "thingC"])
            .is_non_negative("value")
        )
        .add_check(
            Check(CheckLevel.WARNING, "distribution checks")
            .has_completeness("priority", lambda c: c > 0.8)
            .has_mean("value", lambda m: 90 < m < 110)
            .has_standard_deviation("value", lambda s: 10 < s < 20)
        )
        .run()
    )

    print(f"verification status: {result.status}")
    for record in result.check_results_as_records():
        print(
            f"  [{record['check']}] {record['constraint']}: "
            f"{record['constraint_status']} {record['constraint_message']}"
        )
    if result.status != CheckStatus.SUCCESS:
        raise SystemExit(1)


if __name__ == "__main__":
    main()
