"""Frequency-based analyzer tests (reference shape: per-analyzer tests +
AnalysisRunnerTests' shared-groupBy assertions — SURVEY.md §4)."""

import math

import pytest

from deequ_tpu import Dataset
from deequ_tpu.analyzers import (
    AnalysisRunner,
    CountDistinct,
    Distinctness,
    Entropy,
    Histogram,
    MutualInformation,
    Uniqueness,
    UniqueValueRatio,
)
from deequ_tpu.analyzers.grouping import FrequenciesAndNumRows
from fixtures import df_full, df_missing, df_unique


def value(metric):
    assert metric.value.is_success, f"metric failed: {metric.value}"
    return metric.value.get()


class TestUniquenessFamily:
    def test_unique_column(self):
        assert value(Uniqueness("unique").calculate(df_unique())) == 1.0

    def test_non_unique(self):
        # non_unique: a,a,b,b,c -> only 'c' occurs once -> 1/5
        assert value(Uniqueness("non_unique").calculate(df_unique())) == 0.2

    def test_half(self):
        # half: a,a,b,c,d -> b,c,d unique -> 3/5
        assert value(Uniqueness("half").calculate(df_unique())) == 0.6

    def test_unique_value_ratio(self):
        # half: 4 distinct, 3 unique -> 3/4
        assert (
            value(UniqueValueRatio("half").calculate(df_unique())) == 0.75
        )

    def test_distinctness(self):
        assert value(Distinctness("non_unique").calculate(df_unique())) == 0.6

    def test_count_distinct(self):
        assert value(CountDistinct("non_unique").calculate(df_unique())) == 3.0

    def test_nulls_excluded_single_column(self):
        # att1 in df_missing: 10 non-null (7 a, 3 b), 2 null rows dropped
        assert value(Distinctness("att1").calculate(df_missing())) == 2 / 10

    def test_multi_column(self):
        # (att1, att2) pairs in df_full: (a,c),(b,d),(a,d),(b,d) -> 3 groups
        metric = CountDistinct(("att1", "att2")).calculate(df_full())
        assert value(metric) == 3.0


class TestEntropy:
    def test_entropy(self):
        # att1 in df_full: a:2, b:2 -> ln 2
        assert value(Entropy("att1").calculate(df_full())) == pytest.approx(
            math.log(2)
        )

    def test_entropy_skewed(self):
        # att2: c:1, d:3
        expected = -(0.25 * math.log(0.25) + 0.75 * math.log(0.75))
        assert value(Entropy("att2").calculate(df_full())) == pytest.approx(
            expected
        )


class TestMutualInformation:
    def test_identical_columns(self):
        # MI(X, X) == H(X)
        mi = value(
            MutualInformation(("att1", "att1")).calculate(df_full())
        )
        assert mi == pytest.approx(math.log(2))

    def test_independent(self):
        from deequ_tpu.data import Dataset

        ds = Dataset.from_pydict(
            {
                "a": ["x", "x", "y", "y"],
                "b": ["u", "v", "u", "v"],
            }
        )
        mi = value(MutualInformation(("a", "b")).calculate(ds))
        assert mi == pytest.approx(0.0, abs=1e-12)


class TestHistogram:
    def test_basic(self):
        dist = value(Histogram("att2").calculate(df_full()))
        assert dist.number_of_bins == 2
        assert dist["d"].absolute == 3
        assert dist["d"].ratio == 0.75

    def test_nulls_binned(self):
        dist = value(Histogram("att2").calculate(df_missing()))
        assert dist["NullValue"].absolute == 6
        assert dist["f"].absolute == 4
        assert dist["d"].absolute == 2

    def test_max_detail_bins(self):
        dist = value(
            Histogram("item", max_detail_bins=2).calculate(df_missing())
        )
        # detail capped at 2 bins but the true distinct count is reported
        assert len(dist.values) == 2
        assert dist.number_of_bins == 12

    def test_numeric_column(self):
        from fixtures import df_numeric

        dist = value(Histogram("att2").calculate(df_numeric()))
        assert dist["0"].absolute == 3


class TestHighCardinalityPaths:
    """The dense device path (budget-raised cap, i32 counts) and the
    streaming Arrow fallback must agree exactly, and merges of large
    sparse states stay vectorized."""

    @staticmethod
    def _ds(n=50_000, distinct=30_000, seed=3):
        import numpy as np

        rng = np.random.default_rng(seed)
        return Dataset.from_pydict(
            {"id": rng.integers(0, distinct, n), "pair": rng.integers(0, 50, n)}
        )

    def test_dense_equals_fallback(self):
        from deequ_tpu import config

        ds_dense = self._ds()
        ds_spill = self._ds()
        analyzers = lambda: [
            Uniqueness("id"),
            Distinctness("id"),
            CountDistinct("id"),
            Entropy("id"),
        ]
        with config.configure(dense_grouping_budget_bytes=1 << 30):
            dense_ctx = AnalysisRunner.do_analysis_run(ds_dense, analyzers())
        # a tiny budget (honored exactly) forces the Arrow fallback
        with config.configure(dense_grouping_budget_bytes=8):
            spill_ctx = AnalysisRunner.do_analysis_run(
                ds_spill, analyzers()
            )
        for a in analyzers():
            d = dense_ctx.metric(a).value.get()
            s = spill_ctx.metric(a).value.get()
            assert d == pytest.approx(s, rel=1e-12), a

    def test_large_sparse_merge_vectorized(self):
        import numpy as np

        k = 200_000
        keys = np.empty((k, 1), dtype=object)
        keys[:, 0] = np.arange(k)
        a = FrequenciesAndNumRows(("c",), keys, np.ones(k, dtype=np.int64), k)
        keys2 = np.empty((k, 1), dtype=object)
        keys2[:, 0] = np.arange(k // 2, k + k // 2)
        b = FrequenciesAndNumRows(
            ("c",), keys2, np.ones(k, dtype=np.int64), k
        )
        import time

        t0 = time.time()
        merged = FrequenciesAndNumRows.merge(a, b)
        assert time.time() - t0 < 5.0  # dict-loop took tens of seconds
        assert merged.num_groups == k + k // 2
        assert merged.counts.sum() == 2 * k
        assert merged.num_rows == 2 * k


def test_nan_payloads_group_together():
    """Different NaN BIT PATTERNS are one group on every path (Spark
    NaN==NaN; Arrow's group_by would otherwise split them — verified
    empirically in r4 review), and -0.0 groups with 0.0."""
    import numpy as np
    import pyarrow as pa

    from deequ_tpu import CountDistinct, Dataset
    from deequ_tpu.analyzers import AnalysisRunner

    bits = np.array(
        [0x7FF8000000000000, 0xFFF8000000000000, 0x7FF8000000000001],
        dtype=np.uint64,
    ).view(np.float64)
    values = np.concatenate([bits, np.array([-0.0, 0.0, 2.5])])
    ds = Dataset.from_arrow(pa.table({"x": pa.array(values)}))
    ctx = AnalysisRunner.do_analysis_run(ds, [CountDistinct(["x"])])
    # {NaN, 0.0, 2.5} = 3 groups
    assert ctx.metric(CountDistinct(["x"])).value.get() == 3.0
