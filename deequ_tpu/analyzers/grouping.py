"""Grouping (frequency-based) analyzers: CountDistinct, Distinctness,
Uniqueness, UniqueValueRatio, Entropy, MutualInformation, Histogram.

Reference: ``src/main/scala/com/amazon/deequ/analyzers/GroupingAnalyzers.scala``
and one file per analyzer (SURVEY.md §2.2): analyzers over value
frequencies share one ``groupBy().count()`` per distinct (grouping
columns, filter) — the shared state is ``FrequenciesAndNumRows``.

TPU design (SURVEY.md §7 hard part #1): the TPU has no shuffle. Grouping
columns are dictionary-encoded host-side by Arrow's C++ kernels (exact,
vectorized); the device pass is a masked scatter-add of joint codes into
a dense count vector — one fused pass per frequency group, batched the
same way as the scan analyzers. Cross-shard/cross-dataset merges operate
on (key, count) pairs host-side, exactly like the reference merges
frequency DataFrames with unionByName + groupBy.sum (SURVEY.md §3.2).
For joint-key spaces too large for a dense vector, computation falls
back to Arrow's multithreaded host group_by.

Row semantics follow the reference: rows where ALL grouping columns are
null are excluded (``atLeastOneNonNullGroupingColumn``); Histogram runs
its own frequency pass that keeps nulls as a ``NullValue`` bin.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
import pyarrow as pa

from deequ_tpu.analyzers.base import (
    Analyzer,
    EmptyStateException,
    GroupingAnalyzer,
    Precondition,
    has_column,
)
from deequ_tpu.data.table import ROW_MASK, ColumnRequest, Dataset
from deequ_tpu.engine.scan import AnalysisEngine
from deequ_tpu.metrics.distribution import HistogramMetric
from deequ_tpu.metrics.metric import DoubleMetric, Entity, Metric
from deequ_tpu.sql.predicate import compile_predicate

NULL_VALUE = "NullValue"  # reference: Histogram's bin name for nulls
MAX_DENSE_JOINT = 1 << 24  # dense device count-vector cap


# --------------------------------------------------------------------------
# Shared state
# --------------------------------------------------------------------------


class FrequenciesAndNumRows:
    """(value combination -> count) plus the number of contributing rows.

    Host-side object (the reference's equivalent holds a DataFrame):
    ``keys`` is an object ndarray of shape (K, n_cols) whose entries are
    Python values (None encodes SQL NULL), ``counts`` an int64 (K,).
    Merge is a host dictionary union with summed counts — the incremental
    path across datasets/days (SURVEY.md §3.2).
    """

    def __init__(
        self,
        columns: Tuple[str, ...],
        keys: np.ndarray,
        counts: np.ndarray,
        num_rows: int,
    ):
        self.columns = tuple(columns)
        self.keys = keys
        self.counts = np.asarray(counts, dtype=np.int64)
        self.num_rows = int(num_rows)

    @property
    def num_groups(self) -> int:
        return len(self.counts)

    @staticmethod
    def merge(
        a: "FrequenciesAndNumRows", b: "FrequenciesAndNumRows"
    ) -> "FrequenciesAndNumRows":
        if a.columns != b.columns:
            raise ValueError(
                f"cannot merge frequencies over {a.columns} with {b.columns}"
            )
        combined: Dict[Tuple, int] = {}
        for keys, counts in ((a.keys, a.counts), (b.keys, b.counts)):
            for row, count in zip(keys, counts):
                key = tuple(row)
                combined[key] = combined.get(key, 0) + int(count)
        if combined:
            key_arr = np.empty((len(combined), len(a.columns)), dtype=object)
            for i, key in enumerate(combined):
                key_arr[i, :] = key
            count_arr = np.fromiter(
                combined.values(), dtype=np.int64, count=len(combined)
            )
        else:
            key_arr = np.empty((0, len(a.columns)), dtype=object)
            count_arr = np.zeros(0, dtype=np.int64)
        return FrequenciesAndNumRows(
            a.columns, key_arr, count_arr, a.num_rows + b.num_rows
        )


# --------------------------------------------------------------------------
# Frequency computation (the "groupBy" pass)
# --------------------------------------------------------------------------


@dataclass(frozen=True)
class FrequencyPlan:
    """Identity of one shared frequency pass."""

    columns: Tuple[str, ...]
    where: Optional[str]
    include_nulls: bool  # Histogram keeps nulls as their own bin


def compute_frequencies(
    dataset: Dataset,
    plan: FrequencyPlan,
    engine: Optional[AnalysisEngine] = None,
) -> FrequenciesAndNumRows:
    return compute_many_frequencies(dataset, [plan], engine)[plan]


def compute_many_frequencies(
    dataset: Dataset,
    plans: Sequence[FrequencyPlan],
    engine: Optional[AnalysisEngine] = None,
) -> Dict[FrequencyPlan, FrequenciesAndNumRows]:
    """ALL dense frequency plans ride ONE fused scan (each plan is just a
    scatter-add over different codes, so K plans still cost one data
    pass — the profiler's pass-3 histogram explosion collapses into a
    single job, SURVEY.md §7 hard part #6). Plans whose joint key space
    exceeds the dense cap fall back to Arrow's host group_by."""
    engine = engine or AnalysisEngine()
    dense: List[Tuple[FrequencyPlan, List[np.ndarray], List[int]]] = []
    results: Dict[FrequencyPlan, FrequenciesAndNumRows] = {}
    for plan in plans:
        dictionaries = [dataset.dictionary(c) for c in plan.columns]
        sizes = [len(d) + 1 for d in dictionaries]  # +1: the null slot
        joint = 1
        for s in sizes:
            joint *= s
        if joint <= MAX_DENSE_JOINT:
            dense.append((plan, dictionaries, sizes))
        else:
            results[plan] = _arrow_frequencies(dataset, plan)
    if dense:
        results.update(_device_frequencies_shared(dataset, dense, engine))
    return results


def _where_mask_full(dataset: Dataset, where: Optional[str]) -> Optional[np.ndarray]:
    """Evaluate a where-filter over the whole table (used by the host
    fallback); returns bool ndarray or None."""
    if where is None:
        return None
    pred = compile_predicate(where, dataset)
    batch = {r.key: dataset.materialize(r) for r in pred.requests}
    batch[ROW_MASK] = np.ones(dataset.num_rows, dtype=bool)
    return np.asarray(jax.device_get(pred.complies(batch))).astype(bool)


def _make_dense_ops(
    dataset: Dataset,
    plan: FrequencyPlan,
    sizes: List[int],
):
    """(requests, ScanOps) for one dense frequency plan; the ops' state is
    (dense int64 count vector, kept-row count)."""
    from deequ_tpu.analyzers.base import ScanOps

    columns = list(plan.columns)
    where_fn = None
    requests = [ColumnRequest(c, "codes") for c in columns] + [
        ColumnRequest(c, "mask") for c in columns
    ]
    if plan.where is not None:
        pred = compile_predicate(plan.where, dataset)
        where_fn = pred.complies
        requests += list(pred.requests)

    joint = 1
    for s in sizes:
        joint *= s

    def init():
        return (
            np.zeros(joint, dtype=np.int64),
            np.int64(0),
        )

    def update(state, batch):
        counts, num_rows = state
        rows = batch[ROW_MASK]
        if where_fn is not None:
            rows = rows & where_fn(batch)
        if plan.include_nulls:
            keep = rows
        else:
            any_non_null = jnp.zeros_like(rows)
            for c in columns:
                any_non_null = any_non_null | batch[f"{c}::mask"]
            keep = rows & any_non_null
        code = jnp.zeros_like(batch[f"{columns[0]}::codes"])
        for c, size in zip(columns, sizes):
            shifted = batch[f"{c}::codes"] + 1  # null (-1) -> slot 0
            code = code * size + shifted
        # masked scatter-add; rejected rows go to an overflow slot
        code = jnp.where(keep, code, joint)
        counts = counts + jnp.bincount(
            code, length=joint + 1
        )[:joint].astype(jnp.int64)
        return counts, num_rows + jnp.sum(keep, dtype=jnp.int64)

    ops = ScanOps(init, update, lambda a, b: (a[0] + b[0], a[1] + b[1]))
    return requests, ops


def _decode_dense(
    plan: FrequencyPlan,
    dictionaries: List[np.ndarray],
    sizes: List[int],
    counts: np.ndarray,
    num_rows: int,
) -> FrequenciesAndNumRows:
    columns = list(plan.columns)
    observed = np.nonzero(counts)[0]
    key_arr = np.empty((len(observed), len(columns)), dtype=object)
    remaining = observed.copy()
    for j in range(len(columns) - 1, -1, -1):
        slot = remaining % sizes[j]
        remaining = remaining // sizes[j]
        dictionary = dictionaries[j]
        decoded = np.empty(len(slot), dtype=object)
        non_null = slot > 0
        if non_null.any():
            decoded[non_null] = dictionary[slot[non_null] - 1]
        decoded[~non_null] = None
        key_arr[:, j] = decoded
    return FrequenciesAndNumRows(
        tuple(columns), key_arr, counts[observed], num_rows
    )


def _device_frequencies_shared(
    dataset: Dataset,
    dense: List[Tuple[FrequencyPlan, List[np.ndarray], List[int]]],
    engine: AnalysisEngine,
) -> Dict[FrequencyPlan, FrequenciesAndNumRows]:
    class _FreqAnalyzer:
        """Adapter so frequency passes ride the shared scan engine."""

        def __init__(self, requests):
            self._requests = requests

        def device_requests(self, ds):
            return self._requests

    planned = []
    for plan, dictionaries, sizes in dense:
        requests, ops = _make_dense_ops(dataset, plan, sizes)
        planned.append((_FreqAnalyzer(requests), ops))
    states = engine.run_scan(dataset, planned)  # type: ignore[arg-type]
    out: Dict[FrequencyPlan, FrequenciesAndNumRows] = {}
    for (plan, dictionaries, sizes), (counts, num_rows) in zip(dense, states):
        out[plan] = _decode_dense(
            plan, dictionaries, sizes, np.asarray(counts), int(num_rows)
        )
    return out


def _arrow_frequencies(
    dataset: Dataset, plan: FrequencyPlan
) -> FrequenciesAndNumRows:
    """Host fallback for huge joint key spaces: Arrow's multithreaded
    C++ group_by (the 'spill' strategy of SURVEY.md §7 hard part #1)."""
    columns = list(plan.columns)
    table = dataset.table.select(columns)
    mask = _where_mask_full(dataset, plan.where)
    if not plan.include_nulls:
        non_null = np.zeros(dataset.num_rows, dtype=bool)
        for c in columns:
            non_null |= dataset.materialize(ColumnRequest(c, "mask"))
        mask = non_null if mask is None else (mask & non_null)
    if mask is not None:
        table = table.filter(pa.array(mask))
    grouped = table.group_by(columns).aggregate([([], "count_all")])
    counts = grouped.column("count_all").to_numpy(zero_copy_only=False)
    key_arr = np.empty((len(counts), len(columns)), dtype=object)
    for j, c in enumerate(columns):
        key_arr[:, j] = np.asarray(grouped.column(c).to_pylist(), dtype=object)
    return FrequenciesAndNumRows(
        tuple(columns), key_arr, counts.astype(np.int64), int(table.num_rows)
    )


def run_grouping_analyzers(
    dataset: Dataset,
    analyzers: Sequence[GroupingAnalyzer],
    engine: Optional[AnalysisEngine],
    aggregate_with,
    save_states_with,
) -> Dict[Analyzer, Metric]:
    """Group analyzers by their frequency plan; ONE pass per plan, shared
    by every analyzer in the group (SURVEY.md §2.4 step 5)."""
    metrics: Dict[Analyzer, Metric] = {}
    by_plan: Dict[FrequencyPlan, List[GroupingAnalyzer]] = {}
    for analyzer in analyzers:
        plan = FrequencyPlan(
            tuple(analyzer.grouping_columns()),
            analyzer.filter_condition,
            getattr(analyzer, "include_nulls", False),
        )
        by_plan.setdefault(plan, []).append(analyzer)

    try:
        all_frequencies = compute_many_frequencies(
            dataset, list(by_plan.keys()), engine
        )
    except Exception as exc:  # noqa: BLE001
        return {
            analyzer: analyzer.to_failure_metric(exc)
            for group in by_plan.values()
            for analyzer in group
        }

    for plan, group in by_plan.items():
        frequencies = all_frequencies[plan]
        for analyzer in group:
            try:
                state = frequencies
                if aggregate_with is not None:
                    prior = aggregate_with.load(analyzer)
                    if prior is not None:
                        state = FrequenciesAndNumRows.merge(state, prior)
                if save_states_with is not None:
                    save_states_with.persist(analyzer, state)
                metrics[analyzer] = analyzer.compute_metric_from_state(state)
            except Exception as exc:  # noqa: BLE001
                metrics[analyzer] = analyzer.to_failure_metric(exc)
    return metrics


# --------------------------------------------------------------------------
# Concrete grouping analyzers
# --------------------------------------------------------------------------


def _normalize_columns(columns: Union[str, Sequence[str]]) -> Tuple[str, ...]:
    if isinstance(columns, str):
        return (columns,)
    return tuple(columns)


@dataclass(frozen=True)
class _FrequencyAnalyzer(GroupingAnalyzer):
    columns: Tuple[str, ...] = ()
    where: Optional[str] = None

    def __init__(
        self, columns: Union[str, Sequence[str]], where: Optional[str] = None
    ):
        object.__setattr__(self, "columns", _normalize_columns(columns))
        object.__setattr__(self, "where", where)

    def grouping_columns(self) -> List[str]:
        return list(self.columns)

    @property
    def filter_condition(self) -> Optional[str]:
        return self.where

    @property
    def entity(self) -> Entity:
        return Entity.COLUMN if len(self.columns) == 1 else Entity.MULTICOLUMN

    @property
    def instance(self) -> str:
        return ",".join(self.columns)

    def compute_metric_from_state(self, state) -> Metric:
        if state is None or state.num_rows == 0:
            return self.to_failure_metric(
                EmptyStateException(
                    f"Empty state for analyzer {self.name}."
                )
            )
        return DoubleMetric.success(
            self.entity, self.name, self.instance, self._value(state)
        )

    def _value(self, state: FrequenciesAndNumRows) -> float:
        raise NotImplementedError


class CountDistinct(_FrequencyAnalyzer):
    """Exact distinct count (reference: analyzers/CountDistinct.scala)."""

    def _value(self, state: FrequenciesAndNumRows) -> float:
        return float(state.num_groups)


class Distinctness(_FrequencyAnalyzer):
    """#distinct / #rows (reference: analyzers/Distinctness.scala)."""

    def _value(self, state: FrequenciesAndNumRows) -> float:
        return state.num_groups / state.num_rows


class Uniqueness(_FrequencyAnalyzer):
    """Fraction of values occurring exactly once (reference:
    analyzers/Uniqueness.scala)."""

    def _value(self, state: FrequenciesAndNumRows) -> float:
        return float(np.sum(state.counts == 1)) / state.num_rows


class UniqueValueRatio(_FrequencyAnalyzer):
    """#unique / #distinct (reference: analyzers/UniqueValueRatio.scala)."""

    def _value(self, state: FrequenciesAndNumRows) -> float:
        return float(np.sum(state.counts == 1)) / state.num_groups


class Entropy(_FrequencyAnalyzer):
    """Shannon entropy of the value distribution (reference:
    analyzers/Entropy.scala); computed over non-null groups."""

    def _value(self, state: FrequenciesAndNumRows) -> float:
        non_null = np.array(
            [all(v is not None for v in row) for row in state.keys], dtype=bool
        )
        counts = state.counts[non_null].astype(np.float64)
        total = counts.sum()
        if total == 0:
            raise EmptyStateException("Entropy over empty distribution.")
        p = counts / total
        return float(-(p * np.log(p)).sum())


class MutualInformation(_FrequencyAnalyzer):
    """Mutual information of two columns (reference:
    analyzers/MutualInformation.scala) — derived from the joint frequency
    table; rows with any null in the pair are excluded."""

    def preconditions(self) -> List[Precondition]:
        from deequ_tpu.analyzers.base import exactly_n_columns

        return [exactly_n_columns(self.columns, 2)] + super().preconditions()

    @property
    def entity(self) -> Entity:
        return Entity.MULTICOLUMN

    def _value(self, state: FrequenciesAndNumRows) -> float:
        keep = np.array(
            [all(v is not None for v in row) for row in state.keys], dtype=bool
        )
        keys = state.keys[keep]
        counts = state.counts[keep].astype(np.float64)
        total = counts.sum()
        if total == 0:
            raise EmptyStateException("MutualInformation over empty state.")
        p_joint = counts / total
        left: Dict[object, float] = {}
        right: Dict[object, float] = {}
        for row, p in zip(keys, p_joint):
            left[row[0]] = left.get(row[0], 0.0) + p
            right[row[1]] = right.get(row[1], 0.0) + p
        mi = 0.0
        for row, p in zip(keys, p_joint):
            mi += p * math.log(p / (left[row[0]] * right[row[1]]))
        return float(mi)


@dataclass(frozen=True)
class Histogram(GroupingAnalyzer):
    """Full value distribution, null values kept as a ``NullValue`` bin,
    detail capped at ``max_detail_bins`` (reference:
    analyzers/Histogram.scala — runs its own groupBy; SURVEY.md §2.2)."""

    column: str = ""
    max_detail_bins: int = 1000
    where: Optional[str] = None

    def __init__(
        self,
        column: str,
        max_detail_bins: int = 1000,
        where: Optional[str] = None,
    ):
        object.__setattr__(self, "column", column)
        object.__setattr__(self, "max_detail_bins", max_detail_bins)
        object.__setattr__(self, "where", where)

    include_nulls = True

    def grouping_columns(self) -> List[str]:
        return [self.column]

    @property
    def filter_condition(self) -> Optional[str]:
        return self.where

    @property
    def instance(self) -> str:
        return self.column

    def preconditions(self) -> List[Precondition]:
        return [has_column(self.column)]

    def compute_metric_from_state(self, state) -> Metric:
        if state is None:
            return self.to_failure_metric(
                EmptyStateException("Empty state for analyzer Histogram.")
            )
        order = np.argsort(-state.counts, kind="stable")
        top = order[: self.max_detail_bins]
        counts: Dict[str, int] = {}
        for i in top:
            value = state.keys[i, 0]
            label = NULL_VALUE if value is None else str(value)
            counts[label] = int(state.counts[i])
        metric = HistogramMetric.from_counts(
            "Histogram", self.instance, counts, state.num_rows
        )
        # number_of_bins reflects the FULL distinct count even when the
        # detail is capped (reference behavior)
        from deequ_tpu.metrics.distribution import Distribution

        full = Distribution(metric.value.get().values, state.num_groups)
        return HistogramMetric(
            Entity.COLUMN, "Histogram", self.instance, type(metric.value)(full)
        )
