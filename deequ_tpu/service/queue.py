"""Run queue: priority classes, per-tenant quotas, deadline-aware pop.

The queue is the service's ONLY ordering authority. It layers three
policies on top of the engine's FIFO ``AdmissionController`` (which
still gates device admission underneath, per run):

- **priority classes** — INTERACTIVE < STANDARD < BATCH; ``pop``
  always serves the best class first, FIFO by submission sequence
  within a class (no starvation re-ordering inside a class);
- **per-tenant quotas** — a tenant over its pending quota is refused
  at ``push`` (``QuotaExceeded``); a tenant at its active quota is
  SKIPPED at ``pop`` (its tickets stay queued, other tenants' work
  proceeds — one noisy tenant cannot wedge the queue);
- **envelope checks at pop** — a ticket whose deadline expired or
  whose cancel token fired while queued is rejected/cancelled CLEANLY
  at dequeue time (the terminal state lands on the handle; the
  executor never sees it).

Timing discipline: the queue never reads wall time itself — deadline
expiry is asked of each ticket's ``RunBudget`` (which carries its own
injectable clock), and queue-wait measurements use the ``clock`` handed
to the queue. ``time.time``/``time.sleep`` are banned in this package
(tools/telemetry_lint.py).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

from deequ_tpu.engine.deadline import (
    CancelToken,
    DeadlineExceeded,
    MonotonicClock,
    RunBudget,
    RunCancelled,
)
from deequ_tpu.telemetry import TraceContext, get_telemetry


def finish_ticket_trace(ticket: "RunTicket", status: str,
                        queue_wait_s: Optional[float] = None) -> None:
    """Emit the ticket's synthetic root span (span_id reserved at mint)
    once its handle is terminal — EVERY terminal path routes here so a
    traced run always has exactly one root. ``queue_wait_s`` adds the
    queue-wait child for tickets that died without ever starting (the
    scheduler emits it itself for tickets it started)."""
    ctx = ticket.trace
    if ctx is None:
        return
    tm = get_telemetry()
    handle = ticket.handle
    submitted = ticket.submitted_at or 0.0
    finished = handle.finished_at
    wall = max(0.0, (finished - submitted)) if finished is not None else 0.0
    if queue_wait_s is not None:
        tm.emit_span(
            "queue_wait",
            queue_wait_s,
            trace=ctx,
            parent_id=ctx.span_id,
            priority=Priority.name(handle.priority),
        )
    tm.emit_span(
        "ticket",
        wall,
        trace=ctx,
        span_id=ctx.span_id,
        parent_id=None,
        run_id=handle.run_id,
        tenant=handle.tenant,
        priority=Priority.name(handle.priority),
        status=status,
    )


class Priority:
    """Scheduling classes, best first. Integers (not an Enum) so
    tickets order as plain tuples; anything in between is allowed but
    these three are the service's vocabulary."""

    INTERACTIVE = 0
    STANDARD = 1
    BATCH = 2

    _NAMES = {0: "interactive", 1: "standard", 2: "batch"}

    @staticmethod
    def name(priority: int) -> str:
        return Priority._NAMES.get(priority, str(priority))


class QuotaExceeded(RuntimeError):
    """A tenant tried to queue past its pending quota."""


class RunState:
    """Terminal + transitional states of a submitted run."""

    QUEUED = "queued"
    RUNNING = "running"
    DONE = "done"
    FAILED = "failed"
    CANCELLED = "cancelled"
    REJECTED = "rejected"

    TERMINAL = frozenset({DONE, FAILED, CANCELLED, REJECTED})


class RunHandle:
    """The client's thread-safe view of one submitted run: poll
    ``status``, block on ``result()``/``wait()``, ``cancel()`` at any
    point. Exactly one terminal transition ever happens; ``result()``
    re-raises the run's error for FAILED/REJECTED and ``RunCancelled``
    for a run cancelled while still queued (a run cancelled while
    RUNNING still returns its partial ``VerificationResult`` with
    ``interruption`` set — same contract as a direct bounded run)."""

    def __init__(self, run_id: str, tenant: str, priority: int):
        self.run_id = run_id
        self.tenant = tenant
        self.priority = priority
        self._state = RunState.QUEUED
        self._result: Any = None
        self._error: Optional[BaseException] = None
        self._done = threading.Event()
        self._lock = threading.Lock()
        self.cancel_token = CancelToken()
        # scheduling timeline (service clock timestamps; filled by the
        # queue/scheduler, surfaced in telemetry events)
        self.submitted_at: Optional[float] = None
        self.started_at: Optional[float] = None
        self.finished_at: Optional[float] = None
        # elastic placement record, filled by the scheduler when a
        # placer is wired: {"ndev", "device_ids", "lease_wait_s"}
        self.placement: Optional[dict] = None
        # fired exactly once, after the terminal transition publishes
        # (the service's journal hook rides here, so EVERY terminal
        # path — scheduler finish, queued-state rejection, drain —
        # reaches the write-ahead log)
        self.on_terminal: Optional[Callable[["RunHandle"], None]] = None

    @property
    def status(self) -> str:
        # lint-ok: lock-discipline: monitoring snapshot — _state moves
        # monotonically to a terminal value; a stale read is benign
        return self._state

    @property
    def done(self) -> bool:
        # lint-ok: lock-discipline: monotonic state machine — once a
        # terminal state is visible it never changes
        return self._state in RunState.TERMINAL

    def cancel(self, reason: str = "cancelled by client") -> None:
        """Cooperative cancel: while queued the ticket is dropped at
        the next pop; while running the engine exits through its
        checkpoint path and the handle completes with a partial
        result."""
        self.cancel_token.cancel(reason)

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._done.wait(timeout)

    def result(self, timeout: Optional[float] = None):
        if not self._done.wait(timeout):
            raise TimeoutError(
                # lint-ok: lock-discipline: best-effort status in an
                # error message; may lag the transition that races the
                # timeout
                f"run {self.run_id} not finished (status={self._state})"
            )
        # lint-ok: lock-discipline: _done.wait() returned True — the
        # Event.set() in _finish publishes _error/_result (terminal
        # state never changes after that)
        if self._error is not None:
            # lint-ok: lock-discipline: post-Event read, see above
            raise self._error
        # lint-ok: lock-discipline: post-Event read, see above
        return self._result

    def terminal_info(self):
        """(state, error) once terminal, ``(None, None)`` before — the
        journal hook's read API (no private attribute pokes)."""
        with self._lock:
            if self._state not in RunState.TERMINAL:
                return None, None
            return self._state, self._error

    # -- transitions (scheduler/queue internal) -------------------------

    def _mark_running(self) -> None:
        with self._lock:
            if self._state == RunState.QUEUED:
                self._state = RunState.RUNNING

    def _requeue(self) -> None:
        """Preemption transition: RUNNING back to QUEUED. A no-op once
        terminal (a client cancel that raced the preemption wins — the
        scheduler never requeues a handle whose own token fired)."""
        with self._lock:
            if self._state == RunState.RUNNING:
                self._state = RunState.QUEUED

    def _finish(
        self,
        state: str,
        result: Any = None,
        error: Optional[BaseException] = None,
    ) -> None:
        with self._lock:
            if self._state in RunState.TERMINAL:
                return
            self._state = state
            self._result = result
            self._error = error
        self._done.set()
        hook = self.on_terminal
        if hook is not None:
            try:
                hook(self)
            except Exception:  # noqa: BLE001 — journaling must never
                pass  # turn a finished run into a crashed worker

    def __repr__(self) -> str:
        return (
            f"RunHandle({self.run_id}, tenant={self.tenant!r}, "
            # lint-ok: lock-discipline: debug snapshot of a monotonic
            # state machine
            f"{Priority.name(self.priority)}, {self._state})"
        )


@dataclass
class RunTicket:
    """One queued unit of work: the handle the client holds, the
    payload the executor needs, and the envelope (budget started at
    SUBMIT — queue wait burns the deadline, matching the admission
    controller's semantics)."""

    seq: int
    handle: RunHandle
    payload: Any
    budget: Optional[RunBudget] = None
    estimated_bytes: int = 0
    dataset_key: Optional[str] = None
    submitted_at: float = 0.0
    # config-derived plan-key surface captured at submit
    # (engine.scan.coalesce_key_surface): the coalescer only groups
    # tickets with EQUAL surfaces, so a config change between two
    # submissions can't smuggle differently-planned runs into one scan
    coalesce_surface: Optional[tuple] = None
    # placement lease granted by the scheduler's ElasticPlacer just
    # before execution (service/placement.py); None when elastic
    # placement is off. A coalesced group shares ONE lease object.
    lease: Optional[Any] = None
    # trace identity minted at push when the queue runs with
    # trace_enabled (config.service_trace): the span tree of everything
    # that happens to this run hangs off trace.span_id
    trace: Optional[TraceContext] = None
    # last clock reading at which the coalesce policy held this ticket
    # back as a host (waiting for peers) — the scheduler turns the
    # difference from submitted_at into the coalesce_window span
    coalesce_held_until: float = 0.0
    # checkpoint-conserving preemption (service/preempt.py): the
    # per-attempt cancel token (child of the handle token) armed just
    # before execution, whether this attempt was asked to yield, the
    # checkpoint-bearing interruption that licensed the requeue, and
    # how many preemptions this run has absorbed so far (the livelock
    # bound). All None/0 when service_preemption is off.
    preempt_token: Optional[CancelToken] = None
    preempt_requested: bool = False
    preempt_evidence: Optional[Any] = None
    preemptions: int = 0

    @property
    def sort_key(self):
        return (self.handle.priority, self.seq)


class RunQueue:
    """Thread-safe priority queue with tenant quotas. ``push`` from any
    client thread; ``pop`` from executor workers (optionally restricted
    to a maximum priority class — the interactive reserve). ``pop``
    resolves queued-state terminations (deadline expired, cancelled)
    as it scans, so dead tickets never reach an executor."""

    def __init__(
        self,
        clock: Any = None,
        tenant_max_pending: int = 0,
        tenant_max_active: int = 0,
        trace_enabled: bool = False,
        process_label: str = "",
    ):
        self.clock = clock or MonotonicClock()
        self.tenant_max_pending = int(tenant_max_pending)
        self.tenant_max_active = int(tenant_max_active)
        self.trace_enabled = bool(trace_enabled)
        self.process_label = process_label
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._seq = 0
        self._queued: List[RunTicket] = []
        self._pending_by_tenant: Dict[str, int] = {}
        self._active_by_tenant: Dict[str, int] = {}
        self._closed = False

    # -- producer side --------------------------------------------------

    def push(self, ticket: RunTicket) -> None:
        tm = get_telemetry()
        tenant = ticket.handle.tenant
        with self._cond:
            if self._closed:
                raise RuntimeError("run queue is closed")
            pending = self._pending_by_tenant.get(tenant, 0)
            active = self._active_by_tenant.get(tenant, 0)
            if (
                self.tenant_max_pending > 0
                and pending + active >= self.tenant_max_pending
            ):
                tm.counter("service.quota_rejections").inc()
                raise QuotaExceeded(
                    f"tenant {tenant!r} at pending quota "
                    f"({self.tenant_max_pending})"
                )
            self._seq += 1
            ticket.seq = self._seq
            ticket.submitted_at = self.clock.now()
            ticket.handle.submitted_at = ticket.submitted_at
            if self.trace_enabled and ticket.trace is None:
                ticket.trace = TraceContext.mint(
                    ticket.handle.run_id, process=self.process_label
                )
            if ticket.budget is not None:
                ticket.budget.start()  # queue wait burns the deadline
            self._queued.append(ticket)
            self._pending_by_tenant[tenant] = pending + 1
            self._cond.notify_all()
        tm.metrics.gauge("service.queue_depth").set(self.depth())

    def requeue(self, ticket: RunTicket) -> bool:
        """Return a PREEMPTED ticket to the queue (the scheduler's
        cancel→checkpoint→requeue path; docs/SERVICE.md "Preemption and
        autoscaling"). The original ``seq`` is preserved, so within the
        BATCH class the victim resumes ahead of anything submitted
        after it — preemption changes WHEN it runs, never its place in
        line. ``submitted_at`` is re-stamped so the resume leg measures
        its own queue wait (the autoscaler reads those histograms); the
        budget is NOT restarted (``RunBudget.start`` pinned the
        deadline at the original submit — preemption does not extend a
        deadline). Returns False when the queue already closed: there
        is nothing to resume into, and the caller applies normal
        terminal semantics instead."""
        tenant = ticket.handle.tenant
        with self._cond:
            if self._closed:
                return False
            ticket.lease = None
            ticket.coalesce_held_until = 0.0
            ticket.submitted_at = self.clock.now()
            ticket.handle._requeue()
            self._queued.append(ticket)
            self._pending_by_tenant[tenant] = (
                self._pending_by_tenant.get(tenant, 0) + 1
            )
            self._cond.notify_all()
        get_telemetry().metrics.gauge("service.queue_depth").set(
            self.depth()
        )
        return True

    # -- consumer side --------------------------------------------------

    def _resolve_dead(self, ticket: RunTicket) -> bool:
        """Terminate a queued ticket whose envelope already closed.
        Returns True when the ticket was consumed (dropped)."""
        handle = ticket.handle
        tm = get_telemetry()
        if handle.cancel_token.cancelled:
            handle.finished_at = self.clock.now()
            handle._finish(
                RunState.CANCELLED,
                error=RunCancelled(
                    handle.cancel_token.reason or "cancelled"
                ),
            )
            tm.counter("service.cancelled_queued").inc()
            tm.event(
                "service_run_rejected",
                run_id=handle.run_id,
                tenant=handle.tenant,
                reason="cancelled while queued",
            )
            finish_ticket_trace(
                ticket,
                RunState.CANCELLED,
                queue_wait_s=handle.finished_at - ticket.submitted_at,
            )
            return True
        if ticket.budget is not None and ticket.budget.expired():
            handle.finished_at = self.clock.now()
            handle._finish(
                RunState.REJECTED,
                error=DeadlineExceeded(
                    f"deadline of {ticket.budget.deadline_s}s expired "
                    "while queued"
                ),
            )
            tm.counter("service.deadline_rejections").inc()
            tm.event(
                "service_run_rejected",
                run_id=handle.run_id,
                tenant=handle.tenant,
                reason="deadline expired while queued",
            )
            finish_ticket_trace(
                ticket,
                RunState.REJECTED,
                queue_wait_s=handle.finished_at - ticket.submitted_at,
            )
            return True
        return False

    def _take_locked(self, max_priority: Optional[int]) -> Optional[RunTicket]:
        """Best live ticket this worker may take, or None. Must hold
        the lock. Scans in (priority, seq) order; resolves dead tickets
        and skips tenants at their active quota."""
        group = self._take_group_locked(max_priority, None)
        return group[0] if group else None

    def _at_active_quota_locked(
        self, tenant: str, taking: Dict[str, int]
    ) -> bool:
        """Would taking one more ticket for ``tenant`` (on top of the
        ``taking`` counts already claimed by this group) breach the
        active quota? Must hold the lock."""
        if self.tenant_max_active <= 0:
            return False
        active = self._active_by_tenant.get(tenant, 0)
        return active + taking.get(tenant, 0) >= self.tenant_max_active

    def _take_group_locked(
        self,
        max_priority: Optional[int],
        policy: Optional[Any],
        defer_batch: Optional[Callable[[], bool]] = None,
    ) -> Optional[List[RunTicket]]:
        """Best live ticket this worker may take PLUS every compatible
        queued ticket the coalesce policy lets it absorb — one critical
        section, so concurrent idle workers can never each grab one
        member of a would-be group (with workers >= tenants nothing
        would ever coalesce otherwise). ``policy=None`` (or disabled)
        degrades to plain single-ticket selection. Must hold the lock.

        Held-back tickets (BATCH inside its coalesce window) are
        skipped as HOSTS but remain absorbable as MEMBERS: a peer
        popping first collects them; otherwise the window expires and
        the next scan takes them normally."""
        coalescing = policy is not None and getattr(
            policy, "enabled", False
        )
        # preemption-aware pop: while an INTERACTIVE group is waiting
        # for capacity, queued/window-held BATCH tickets yield by SKIP
        # — they stay queued at their seq, untouched, rather than
        # racing the interactive into the pool only to be
        # cancel-preempted moments later (docs/SERVICE.md "Preemption
        # and autoscaling"). Evaluated once per scan.
        deferring = defer_batch is not None and defer_batch()
        now = self.clock.now() if coalescing else 0.0
        live: List[RunTicket] = []
        dead: List[RunTicket] = []
        for ticket in self._queued:
            if self._resolve_dead(ticket):
                dead.append(ticket)
            else:
                live.append(ticket)
        for ticket in dead:
            self._remove_locked(ticket)
        taking: Dict[str, int] = {}
        best: Optional[RunTicket] = None
        for ticket in live:
            if max_priority is not None and (
                ticket.handle.priority > max_priority
            ):
                continue
            if self._at_active_quota_locked(ticket.handle.tenant, taking):
                continue
            if deferring and ticket.handle.priority >= Priority.BATCH:
                continue
            if coalescing and policy.may_coalesce(ticket):
                peers = sum(
                    1
                    for other in live
                    if other is not ticket
                    and policy.compatible(ticket, other) is None
                )
                if policy.should_wait(ticket, now, peers):
                    # remember how long the coalesce window held this
                    # ticket back — the scheduler splits the eventual
                    # queue wait into queue_wait + coalesce_window spans
                    ticket.coalesce_held_until = now
                    continue
            if best is None or ticket.sort_key < best.sort_key:
                best = ticket
        if best is None:
            return None
        group = [best]
        taking[best.handle.tenant] = 1
        if coalescing and policy.may_coalesce(best):
            for ticket in sorted(
                (t for t in live if t is not best),
                key=lambda t: t.sort_key,
            ):
                if len(group) >= max(1, int(policy.max_members)):
                    break
                if not policy.may_coalesce(ticket):
                    continue
                if deferring and ticket.handle.priority >= Priority.BATCH:
                    continue
                if self._at_active_quota_locked(
                    ticket.handle.tenant, taking
                ):
                    continue
                if policy.compatible(best, ticket) is None:
                    group.append(ticket)
                    taking[ticket.handle.tenant] = (
                        taking.get(ticket.handle.tenant, 0) + 1
                    )
        for ticket in group:
            self._queued.remove(ticket)
            tenant = ticket.handle.tenant
            self._pending_by_tenant[tenant] = max(
                0, self._pending_by_tenant.get(tenant, 0) - 1
            )
            self._active_by_tenant[tenant] = (
                self._active_by_tenant.get(tenant, 0) + 1
            )
        return group

    def _remove_locked(self, ticket: RunTicket) -> None:
        if ticket in self._queued:
            self._queued.remove(ticket)
        tenant = ticket.handle.tenant
        self._pending_by_tenant[tenant] = max(
            0, self._pending_by_tenant.get(tenant, 0) - 1
        )

    def pop(
        self,
        max_priority: Optional[int] = None,
        should_stop: Optional[Callable[[], bool]] = None,
    ) -> Optional[RunTicket]:
        """Block until a ticket this worker may run is available (or
        ``should_stop()``/close). The wait polls at the clock's
        ``queue_poll_s`` cadence so fake-clock tests resolve deadline
        expiry promptly and shutdown is noticed without a wakeup."""
        while True:
            with self._cond:
                ticket = self._take_locked(max_priority)
                if ticket is not None:
                    get_telemetry().metrics.gauge(
                        "service.queue_depth"
                    ).set(len(self._queued))
                    return ticket
                if self._closed or (
                    should_stop is not None and should_stop()
                ):
                    return None
                self._cond.wait(timeout=self.clock.queue_poll_s())

    def pop_group(
        self,
        max_priority: Optional[int] = None,
        should_stop: Optional[Callable[[], bool]] = None,
        policy: Optional[Any] = None,
        defer_batch: Optional[Callable[[], bool]] = None,
    ) -> Optional[List[RunTicket]]:
        """Like :meth:`pop`, but returns the best live ticket TOGETHER
        with every compatible queued ticket the ``policy``
        (service.coalesce.CoalescePolicy) lets it absorb — the group
        that will share one superset scan. The caller owes one
        :meth:`task_done` per returned ticket. ``policy=None`` behaves
        exactly like ``pop`` wrapped in a one-element list.
        ``defer_batch`` (preemption wiring) skips BATCH-class tickets
        while it returns True — queued batch work yields to an
        interactive ticket waiting on capacity without being started
        and cancelled."""
        while True:
            with self._cond:
                group = self._take_group_locked(
                    max_priority, policy, defer_batch
                )
                if group:
                    get_telemetry().metrics.gauge(
                        "service.queue_depth"
                    ).set(len(self._queued))
                    return group
                if self._closed or (
                    should_stop is not None and should_stop()
                ):
                    return None
                self._cond.wait(timeout=self.clock.queue_poll_s())

    def task_done(self, ticket: RunTicket) -> None:
        """Executor finished (or abandoned) a popped ticket: release
        the tenant's active slot."""
        with self._cond:
            tenant = ticket.handle.tenant
            self._active_by_tenant[tenant] = max(
                0, self._active_by_tenant.get(tenant, 0) - 1
            )
            self._cond.notify_all()

    # -- lifecycle / introspection --------------------------------------

    def close(self) -> List[RunTicket]:
        """Refuse new pushes and wake every waiting worker. Returns the
        tickets still queued (the service terminates them on drain)."""
        with self._cond:
            self._closed = True
            remaining = list(self._queued)
            self._cond.notify_all()
        return remaining

    def drain_queued(self, reason: str) -> int:
        """Cancel every still-queued ticket (shutdown semantics: running
        work finishes and checkpoints; queued work terminates cleanly
        with the shutdown reason). Returns how many were drained."""
        with self._cond:
            drained = list(self._queued)
            self._queued.clear()
            for ticket in drained:
                self._remove_locked(ticket)  # fixes pending counters
            self._cond.notify_all()
        tm = get_telemetry()
        for ticket in drained:
            ticket.handle.finished_at = self.clock.now()
            ticket.handle._finish(
                RunState.CANCELLED, error=RunCancelled(reason)
            )
            tm.event(
                "service_run_rejected",
                run_id=ticket.handle.run_id,
                tenant=ticket.handle.tenant,
                reason=reason,
            )
            finish_ticket_trace(
                ticket,
                RunState.CANCELLED,
                queue_wait_s=(
                    ticket.handle.finished_at - ticket.submitted_at
                ),
            )
        if drained:
            tm.counter("service.drained_queued").inc(len(drained))
        tm.metrics.gauge("service.queue_depth").set(self.depth())
        return len(drained)

    def depth(self) -> int:
        with self._lock:
            return len(self._queued)

    def wait_event(self, timeout: float) -> None:
        """Block until queue state MAY have changed (bounded by
        ``timeout`` seconds) — the building block for idle waits."""
        with self._cond:
            self._cond.wait(timeout=timeout)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "depth": len(self._queued),
                "pending_by_tenant": dict(self._pending_by_tenant),
                "active_by_tenant": dict(self._active_by_tenant),
                "closed": self._closed,
            }
