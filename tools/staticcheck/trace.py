"""Trace-hazard analyzer: host semantics inside traced scopes.

Code that runs under a ``jax.jit`` / ``shard_map`` / ``lax.scan``
trace must stay in graph land: a ``float()``/``int()``/``bool()``/
``.item()`` coercion forces a device sync (ConcretizationTypeError at
best, a silent per-batch tunnel round-trip at worst), an ``np.*`` call
on a traced value falls out of the graph, and a Python ``if``/
``while`` on a traced operand raises at trace time. This analyzer
infers the traced-function set per module and flags those constructs
inside it.

Traced-set inference (flow-insensitive, same-module):

1. seeds — functions decorated with / passed to a trace entry point
   (``jit``, ``shard_map``, ``pallas_call``, ``vmap``, ``pmap``,
   ``lax.scan``/``fori_loop``/``while_loop``/``cond``/``switch``,
   ``custom_vjp``/``custom_jvp``);
2. nesting — a ``def`` inside a traced function is traced;
3. closure — a function a traced function calls by bare name (or
   ``self.<method>``) in the same module is traced;
4. usage heuristic — a function whose body calls ``jnp.*``/``lax.*``/
   ``pl.*`` is treated as traced even when the trace entry point is a
   dynamic dispatch the call graph can't see (the op-protocol
   ``apply_update`` methods jitted via the fused-scan step builder).

The heuristic deliberately over-approximates: host-side glue that
builds arrays with ``jnp`` gets marked, and its deliberate syncs take
a ``# lint-ok: trace-hazard`` waiver saying WHY the value is host-side
there (post-``device_get`` fold, one-time probe, metadata-only).
``np.*`` metadata accessors (dtype/shape arithmetic) are allowed.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from tools.staticcheck.core import (
    Analyzer,
    Finding,
    SourceFile,
    dotted_name,
    register,
)

SCOPE_PREFIXES = ("deequ_tpu/engine/", "deequ_tpu/sketches/")

# call targets whose function arguments (or decorated function) run
# under a trace
TRACE_ENTRY_TAILS = frozenset(
    {
        "jit",
        "shard_map",
        "pallas_call",
        "vmap",
        "pmap",
        "scan",
        "fori_loop",
        "while_loop",
        "cond",
        "switch",
        "custom_vjp",
        "custom_jvp",
        "checkpoint",
        "remat",
    }
)

TRACED_MODULE_HEADS = frozenset({"jnp", "lax", "pl", "pltpu"})

# np.* attributes that are metadata/static-shape arithmetic, legal in
# a traced function (they never touch traced values)
NP_ALLOWED = frozenset(
    {
        "dtype",
        "iinfo",
        "finfo",
        "float16",
        "float32",
        "float64",
        "int8",
        "int16",
        "int32",
        "int64",
        "uint8",
        "uint16",
        "uint32",
        "uint64",
        "bool_",
        "ceil",
        "floor",
        "log2",
        "log",
        "sqrt",
        "prod",
        "ndarray",
        "generic",
        "pi",
        "inf",
        "nan",
        "e",
        "errstate",
    }
)

COERCIONS = frozenset({"float", "int", "bool", "complex"})


def _func_key(stack: Tuple[str, ...]) -> str:
    return ".".join(stack)


class _FunctionIndex(ast.NodeVisitor):
    """Qualified-name index of every function in a module, plus the
    raw data the traced-set inference needs: decorators, call edges,
    and whether the body touches jnp/lax/pl."""

    def __init__(self) -> None:
        self.functions: Dict[str, ast.AST] = {}
        self.class_of: Dict[str, Optional[str]] = {}
        self.decorators: Dict[str, List[str]] = {}
        self.calls: Dict[str, Set[str]] = {}
        self.uses_traced_module: Dict[str, bool] = {}
        self._stack: List[str] = []
        self._class_stack: List[str] = []

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self._class_stack.append(node.name)
        self._stack.append(node.name)
        self.generic_visit(node)
        self._stack.pop()
        self._class_stack.pop()

    def _visit_function(self, node) -> None:
        self._stack.append(node.name)
        key = _func_key(tuple(self._stack))
        self.functions[key] = node
        self.class_of[key] = (
            self._class_stack[-1] if self._class_stack else None
        )
        self.decorators[key] = [
            d for d in (
                dotted_name(dec.func if isinstance(dec, ast.Call) else dec)
                for dec in node.decorator_list
            ) if d
        ]
        self.calls.setdefault(key, set())
        self.uses_traced_module.setdefault(key, False)
        self.generic_visit(node)
        self._stack.pop()

    visit_FunctionDef = _visit_function
    visit_AsyncFunctionDef = _visit_function

    def visit_Call(self, node: ast.Call) -> None:
        if self._stack:
            key = _func_key(tuple(self._stack))
            if key in self.calls:
                name = dotted_name(node.func)
                if name:
                    self.calls[key].add(name)
                    head = name.split(".")[0]
                    if head in TRACED_MODULE_HEADS:
                        self.uses_traced_module[key] = True
        self.generic_visit(node)


def _entry_point_args(tree: ast.AST) -> Set[str]:
    """Bare function names passed to a trace entry point anywhere in
    the module (``lax.scan(step, ...)`` marks ``step``)."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = dotted_name(node.func)
        if not name or name.split(".")[-1] not in TRACE_ENTRY_TAILS:
            continue
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            if isinstance(arg, ast.Name):
                out.add(arg.id)
    return out


def _traced_functions(index: _FunctionIndex, tree: ast.AST) -> Set[str]:
    traced: Set[str] = set()
    entry_args = _entry_point_args(tree)
    for key, _node in index.functions.items():
        short = key.split(".")[-1]
        if short in entry_args:
            traced.add(key)
        if any(
            d.split(".")[-1] in TRACE_ENTRY_TAILS
            for d in index.decorators[key]
        ):
            traced.add(key)
        if index.uses_traced_module[key]:
            traced.add(key)
    # nesting: inner defs of traced functions are traced
    for key in list(index.functions):
        for t in list(traced):
            if key.startswith(t + ".") :
                traced.add(key)
    # closure: propagate through same-module calls until fixed point
    short_to_keys: Dict[str, List[str]] = {}
    for key in index.functions:
        short_to_keys.setdefault(key.split(".")[-1], []).append(key)
    changed = True
    while changed:
        changed = False
        for key in traced.copy():
            for callee in index.calls.get(key, ()):
                tail = callee.split(".")[-1]
                head = callee.split(".")[0]
                if head not in ("self", "cls") and "." in callee:
                    continue  # external module call
                for ckey in short_to_keys.get(tail, ()):
                    # self.<m> resolves only within the same class
                    if head in ("self", "cls") and index.class_of[
                        ckey
                    ] != index.class_of.get(key):
                        continue
                    if ckey not in traced:
                        traced.add(ckey)
                        changed = True
    return traced


#: jnp/lax functions that compute dtype METADATA, static under
#: tracing — a Python `if` on them is the sanctioned way to dispatch
#: (``if jnp.issubdtype(x.dtype, jnp.floating):``)
STATIC_JNP_TAILS = frozenset(
    {"issubdtype", "isdtype", "result_type", "promote_types", "dtype"}
)


def _test_is_traced_operand(test: ast.AST) -> bool:
    """Heuristic: the if/while test itself manufactures or reduces a
    traced value (jnp call, .any()/.all()/.item() reduction)."""
    for node in ast.walk(test):
        if isinstance(node, ast.Call):
            name = dotted_name(node.func)
            if (
                name
                and name.split(".")[0] in TRACED_MODULE_HEADS
                and name.split(".")[-1] not in STATIC_JNP_TAILS
            ):
                return True
            if isinstance(node.func, ast.Attribute) and node.func.attr in (
                "any",
                "all",
                "item",
            ):
                return True
    return False


#: calls whose results are host-side static values even in a trace
STATIC_CALLS = frozenset({"len", "range", "min", "max", "abs", "round"})
#: metadata attributes that are static under tracing
STATIC_ATTRS = frozenset({"shape", "ndim", "size", "dtype", "itemsize"})


def _is_static_arg(node: ast.AST) -> bool:
    """True when a coercion argument is demonstrably static under
    tracing: built from literals, bare names (could be Python scalars
    — the analyzer gives the benefit of the doubt ONLY when no array
    operation appears), shape/dtype metadata, and len()/math.* calls.
    Any jnp/lax call, ``.sum()``-style reduction, or subscript of a
    call result makes it non-static."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            name = dotted_name(sub.func)
            if name is None:
                return False
            tail = name.split(".")[-1]
            head = name.split(".")[0]
            if name in STATIC_CALLS or head == "math":
                continue
            if isinstance(sub.func, ast.Attribute) and tail in STATIC_ATTRS:
                continue
            return False
        if isinstance(sub, ast.Attribute):
            continue
        if isinstance(
            sub,
            (
                ast.Constant, ast.Name, ast.BinOp, ast.UnaryOp, ast.Compare,
                ast.BoolOp, ast.IfExp, ast.Subscript, ast.Tuple, ast.List,
                ast.Load, ast.operator, ast.unaryop, ast.cmpop, ast.boolop,
                ast.expr_context, ast.Slice, ast.keyword, ast.Starred,
            ),
        ):
            continue
        return False
    return True


def _walk_skipping_nested_defs(func: ast.AST) -> Iterable[ast.AST]:
    """Walk a function body without descending into nested ``def``s —
    those are traced entries of their own and report separately."""
    stack: List[ast.AST] = list(ast.iter_child_nodes(func))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


class TraceHazardAnalyzer(Analyzer):
    name = "trace"
    rules = ("trace-hazard",)
    description = (
        "host-sync coercions, np.* calls, and Python control flow on "
        "traced values inside jit/shard_map/scan scopes"
    )

    def analyze(
        self, files: Sequence[SourceFile], root: str
    ) -> Iterable[Finding]:
        for sf in files:
            if sf.tree is None or not any(
                sf.rel.startswith(p) for p in SCOPE_PREFIXES
            ):
                continue
            index = _FunctionIndex()
            index.visit(sf.tree)
            traced = _traced_functions(index, sf.tree)
            for key in sorted(traced):
                yield from self._hazards_in(sf, key, index.functions[key])

    def _hazards_in(
        self, sf: SourceFile, key: str, func: ast.AST
    ) -> Iterable[Finding]:
        short = key.split(".")[-1]
        for node in _walk_skipping_nested_defs(func):
            if isinstance(node, ast.Call):
                name = dotted_name(node.func)
                if (
                    name in COERCIONS
                    and node.args
                    and not all(
                        isinstance(a, ast.Constant) for a in node.args
                    )
                    and not all(_is_static_arg(a) for a in node.args)
                ):
                    yield Finding(
                        rule="trace-hazard",
                        path=sf.rel,
                        line=node.lineno,
                        message=(
                            f"host coercion {name}(...) inside traced "
                            f"scope '{short}' forces a device sync"
                        ),
                        symbol=name,
                    )
                elif (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr == "item"
                    and not node.args
                ):
                    yield Finding(
                        rule="trace-hazard",
                        path=sf.rel,
                        line=node.lineno,
                        message=(
                            f".item() inside traced scope '{short}' "
                            "forces a device sync"
                        ),
                        symbol="item",
                    )
                elif (
                    name
                    and name.startswith("np.")
                    and name.split(".")[1] not in NP_ALLOWED
                ):
                    yield Finding(
                        rule="trace-hazard",
                        path=sf.rel,
                        line=node.lineno,
                        message=(
                            f"{name}(...) inside traced scope '{short}' "
                            "falls out of the graph (use jnp)"
                        ),
                        symbol=name,
                    )
            elif isinstance(node, (ast.If, ast.While)):
                if _test_is_traced_operand(node.test):
                    kw = "if" if isinstance(node, ast.If) else "while"
                    yield Finding(
                        rule="trace-hazard",
                        path=sf.rel,
                        line=node.lineno,
                        message=(
                            f"Python '{kw}' on a traced operand inside "
                            f"'{short}' — use lax.cond/jnp.where"
                        ),
                        symbol=kw,
                    )


register(TraceHazardAnalyzer())
