"""Memory-pressure resilience (docs/RESILIENCE.md "Memory pressure"):
OOM classification, adaptive batch backoff, spill downgrade chain, and
watermark admission.

The load-bearing differential: a scan that backs off to a smaller
effective batch size after an (injected) allocation failure must
produce BIT-IDENTICAL metrics to a run natively configured at that
batch size — same jit specializations, same update/fold sequence. All
faults fire through the ``oom_probe`` protocol (testing/faults.py) with
zero real allocation pressure, and no test here sleeps wall-clock time.
"""

import os
import threading
import time

import numpy as np
import pytest

from deequ_tpu import config
from deequ_tpu.analyzers import (
    AnalysisRunner,
    ApproxQuantile,
    Completeness,
    Mean,
    Size,
    Uniqueness,
)
from deequ_tpu.checks import Check, CheckLevel, CheckStatus
from deequ_tpu.data import Dataset
from deequ_tpu.engine.deadline import AdmissionController, admission_controller
from deequ_tpu.engine.memory import (
    AdaptiveBatchBackoff,
    BackoffExhausted,
    MemoryPressureError,
    SimulatedResourceExhausted,
    classify_memory_pressure,
    make_backoff,
    simulated_device_oom,
)
from deequ_tpu.engine.resilience import RetryPolicy, ScanKilled, is_transient
from deequ_tpu.engine.scan import AnalysisEngine
from deequ_tpu.io.state_provider import ScanCheckpointer
from deequ_tpu.telemetry import get_telemetry
from deequ_tpu.testing.faults import FaultInjectingDataset
from deequ_tpu.verification.suite import VerificationSuite


def _no_sleep(_s: float) -> None:
    pass


FAST_RETRY = RetryPolicy(max_attempts=3, sleep=_no_sleep)

# protection on, aggressive floor, healing off — the deterministic
# setting for the differential tests (heal would change the partition)
BACKOFF_OPTS = dict(min_batch_rows=8, memory_heal_after_batches=0)


def _table_data(n=1000, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "a": rng.normal(size=n).tolist(),
        "g": (np.arange(n) % 7).tolist(),
    }


ANALYZERS = [
    Size(),
    Completeness("a"),
    Mean("a"),
    ApproxQuantile("a", 0.5),
    Uniqueness(["g"]),
]


def _metric_values(ctx, analyzers=ANALYZERS):
    out = []
    for a in analyzers:
        value = ctx.metric(a).value
        assert value.is_success, (a, value)
        out.append((str(a), value.get()))
    return out


# mode -> (engine factory, config overrides) at a given batch size. Mesh
# batch sizes round up to a multiple of the 8 virtual devices, so mesh
# geometries below stick to multiples of 8.
def _mode_setup(mode, cpu_mesh, batch_size):
    if mode == "resident":
        return (lambda **kw: AnalysisEngine(**kw)), dict(
            device_cache_bytes=1 << 30, batch_size=batch_size
        )
    if mode == "streaming":
        return (lambda **kw: AnalysisEngine(**kw)), dict(
            device_cache_bytes=0, batch_size=batch_size
        )
    assert mode == "mesh"
    return (lambda **kw: AnalysisEngine(mesh=cpu_mesh, **kw)), dict(
        device_cache_bytes=0, batch_size=batch_size
    )


MODES = ["resident", "streaming", "mesh"]


# per-mode geometry: full batch size, the injected device's row limit,
# and the size backoff settles at (one halving; mesh aligns to the
# 8-device dp extent, so 128 -> 64 instead of 104 -> 52)
def _geometry(mode):
    if mode == "mesh":
        return dict(n=1000, full=128, over=80, settled=64)
    return dict(n=1000, full=104, over=60, settled=52)


# two-level geometry: n chosen so the settled size divides both the
# full batch and the total row count (no partial sub-slice at the tail,
# keeping the sub-batch partition identical to the native run's)
def _geometry2(mode):
    if mode == "mesh":
        return dict(n=1024, full=128, over=40, settled=32)
    return dict(n=1040, full=104, over=30, settled=26)


def _spin_until(predicate, what, timeout_s=5.0):
    deadline = time.monotonic() + timeout_s
    while not predicate():
        assert time.monotonic() < deadline, f"timed out waiting for {what}"
        time.sleep(0.001)


def _memory_events(cap):
    return [
        e for e in cap.final["events"]
        if e.get("event") == "scan_memory_pressure"
    ]


# --------------------------------------------------------------------------
# Classification (engine/memory.py)
# --------------------------------------------------------------------------


class TestClassification:
    def test_host_memory_error_classifies(self):
        pressure = classify_memory_pressure(MemoryError("malloc"))
        assert isinstance(pressure, MemoryPressureError)
        assert pressure.origin == "host"

    def test_simulated_device_oom_classifies(self):
        pressure = classify_memory_pressure(simulated_device_oom(104, "d@3"))
        assert pressure is not None and pressure.origin == "device"

    def test_runtime_error_with_marker_classifies(self):
        # jaxlib's XlaRuntimeError subclasses RuntimeError; matched by
        # type NAME + message marker, no jaxlib import needed
        exc = RuntimeError(
            "RESOURCE_EXHAUSTED: Out of memory while trying to "
            "allocate 1073741824 bytes"
        )
        pressure = classify_memory_pressure(exc)
        assert pressure is not None and pressure.origin == "device"
        assert pressure.__cause__ is exc

    def test_value_error_mentioning_memory_does_not_classify(self):
        # conservative: only runtime-shaped exception types are
        # message-matched — a data error MENTIONING memory stays a
        # deterministic failure (quarantine path, not backoff)
        assert classify_memory_pressure(ValueError("out of memory")) is None

    def test_plain_runtime_error_does_not_classify(self):
        assert classify_memory_pressure(RuntimeError("segfault")) is None

    def test_memory_pressure_error_passes_through(self):
        original = BackoffExhausted("floor hit")
        assert classify_memory_pressure(original) is original
        assert isinstance(original, MemoryPressureError)

    def test_memory_pressure_is_not_transient(self):
        # same-size retry re-OOMs: the retry driver must never treat
        # the family as transient
        assert not is_transient(MemoryPressureError("x"))
        assert not is_transient(SimulatedResourceExhausted("x"))

    def test_simulated_message_carries_byte_count(self):
        exc = simulated_device_oom(104, "dispatch@3")
        assert f"{104 * 8} bytes" in str(exc)
        assert "dispatch@3" in str(exc)


# --------------------------------------------------------------------------
# AdaptiveBatchBackoff state machine (unit level)
# --------------------------------------------------------------------------


class TestBackoffController:
    def test_shrink_halves_to_floor_then_exhausts(self):
        b = AdaptiveBatchBackoff(1024, 100)
        sizes = []
        while b.shrink("dispatch", 0):
            sizes.append(b.effective)
        assert sizes == [512, 256, 128, 100]
        assert b.shrink("dispatch", 1) is False  # stays exhausted
        assert b.effective == 100

    def test_align_keeps_multiples(self):
        b = AdaptiveBatchBackoff(104, 8, align=8)
        sizes = []
        while b.shrink("dispatch", 0):
            sizes.append(b.effective)
        assert sizes == [48, 24, 8]
        assert all(s % 8 == 0 for s in sizes)

    def test_min_rows_clamped_to_full(self):
        b = AdaptiveBatchBackoff(100, 10_000)
        assert b.min_rows == 100
        assert b.shrink("dispatch", 0) is False  # floor == full

    def test_active_property(self):
        b = AdaptiveBatchBackoff(104, 8)
        assert not b.active
        b.shrink("dispatch", 0)
        assert b.active

    def test_heal_after_consecutive_cleans(self):
        b = AdaptiveBatchBackoff(104, 8, heal_after=2)
        b.shrink("dispatch", 0)
        b.shrink("dispatch", 0)
        assert b.effective == 26
        assert b.note_clean() is False
        assert b.note_clean() is True  # second consecutive clean heals
        assert b.effective == 52
        assert b.note_clean() is False
        assert b.note_clean() is True
        assert b.effective == 104
        assert b.note_clean() is False  # at full: nothing to heal

    def test_heal_disabled_by_default(self):
        b = AdaptiveBatchBackoff(104, 8)
        b.shrink("dispatch", 0)
        for _ in range(50):
            assert b.note_clean() is False
        assert b.effective == 52

    def test_shrink_resets_clean_streak(self):
        b = AdaptiveBatchBackoff(104, 8, heal_after=2)
        b.shrink("dispatch", 0)
        assert b.note_clean() is False  # streak 1
        b.shrink("dispatch", 1)  # OOM: streak resets
        assert b.note_clean() is False
        assert b.note_clean() is True  # needs 2 NEW consecutive cleans

    def test_make_backoff_uses_config(self):
        with config.configure(
            min_batch_rows=16, memory_heal_after_batches=5
        ):
            b = make_backoff(1024, align=4)
        assert (b.full, b.min_rows, b.heal_after, b.align) == (
            1024, 16, 5, 4
        )
        with config.configure(memory_backoff=False):
            assert make_backoff(1024) is None

    def test_inactive_controller_emits_no_telemetry(self):
        tm = get_telemetry()
        before = tm.counter("engine.batch_size_backoffs").value
        b = AdaptiveBatchBackoff(104, 8, heal_after=2)
        for _ in range(100):
            b.note_clean()  # no-op while at full size
        assert tm.counter("engine.batch_size_backoffs").value == before


# --------------------------------------------------------------------------
# Engine-level backoff: the differential oracle, all scan paths
# --------------------------------------------------------------------------


@pytest.mark.parametrize("mode", MODES)
class TestEngineBackoff:
    def test_backoff_settles_bit_identical(self, mode, cpu_mesh):
        """A device that fits only `over` rows: the scan shrinks once
        and finishes — with metrics EXACTLY equal to a run natively
        configured at the settled batch size."""
        g = _geometry(mode)
        data = _table_data(g["n"])
        make_engine, opts = _mode_setup(mode, cpu_mesh, g["full"])
        _, ref_opts = _mode_setup(mode, cpu_mesh, g["settled"])
        with config.configure(**BACKOFF_OPTS, **ref_opts):
            ref = _metric_values(
                AnalysisRunner.do_analysis_run(
                    Dataset.from_pydict(data), ANALYZERS,
                    engine=make_engine(),
                )
            )
        tm = get_telemetry()
        oom_before = tm.counter("engine.oom_events").value
        backoffs_before = tm.counter("engine.batch_size_backoffs").value
        ds = FaultInjectingDataset(
            Dataset.from_pydict(data), oom_rows_over=g["over"]
        )
        with config.configure(**BACKOFF_OPTS, **opts):
            ctx = AnalysisRunner.do_analysis_run(
                ds, ANALYZERS, engine=make_engine()
            )
        assert _metric_values(ctx) == ref
        assert any(f[0] == "oom" for f in ds.faults_fired)
        assert tm.counter("engine.oom_events").value > oom_before
        assert (
            tm.counter("engine.batch_size_backoffs").value
            - backoffs_before
            == 1
        )
        assert ctx.degradation is None or not ctx.degradation.is_degraded

    def test_two_level_backoff_bit_identical(self, mode, cpu_mesh):
        """Two geometric halvings (full -> half -> quarter) still land
        exactly on the native quarter-size run."""
        g = _geometry2(mode)
        data = _table_data(g["n"])
        make_engine, opts = _mode_setup(mode, cpu_mesh, g["full"])
        _, ref_opts = _mode_setup(mode, cpu_mesh, g["settled"])
        with config.configure(**BACKOFF_OPTS, **ref_opts):
            ref = _metric_values(
                AnalysisRunner.do_analysis_run(
                    Dataset.from_pydict(data), ANALYZERS,
                    engine=make_engine(),
                )
            )
        tm = get_telemetry()
        backoffs_before = tm.counter("engine.batch_size_backoffs").value
        ds = FaultInjectingDataset(
            Dataset.from_pydict(data), oom_rows_over=g["over"]
        )
        with config.configure(**BACKOFF_OPTS, **opts):
            ctx = AnalysisRunner.do_analysis_run(
                ds, ANALYZERS, engine=make_engine()
            )
        assert _metric_values(ctx) == ref
        assert (
            tm.counter("engine.batch_size_backoffs").value
            - backoffs_before
            == 2
        )

    def test_exhausted_backoff_quarantines(self, mode, cpu_mesh):
        """With the floor AT the full batch size there is nothing to
        shrink: a persistent OOM at one unit quarantines that unit
        (PR 3's path) and the scan completes on the rest."""
        g = _geometry(mode)
        make_engine, opts = _mode_setup(mode, cpu_mesh, g["full"])
        tm = get_telemetry()
        q_before = tm.counter("engine.batches_quarantined").value
        ds = FaultInjectingDataset(
            Dataset.from_pydict(_table_data(g["n"])),
            oom_at_batch={2: 99},
        )
        with config.configure(
            min_batch_rows=g["full"], scan_retry=FAST_RETRY, **opts
        ):
            ctx = AnalysisRunner.do_analysis_run(
                ds, ANALYZERS, engine=make_engine()
            )
        degr = ctx.degradation
        assert degr is not None and degr.is_degraded
        assert degr.batches_quarantined == 1
        assert degr.rows_skipped == g["full"]
        assert degr.error_classes == ["BackoffExhausted"]
        assert (
            tm.counter("engine.batches_quarantined").value - q_before == 1
        )
        assert ctx.metric(Size()).value.get() == g["n"] - g["full"]

    def test_heal_restores_full_batch(self, mode, cpu_mesh):
        """One transient OOM shrinks the batch; after the configured
        number of clean units the size heals back to full — visible as
        the oom -> backoff -> heal event sequence and the gauge."""
        g = _geometry(mode)
        make_engine, opts = _mode_setup(mode, cpu_mesh, g["full"])
        tm = get_telemetry()
        ds = FaultInjectingDataset(
            Dataset.from_pydict(_table_data(g["n"])),
            oom_at_batch={0: 1},
        )
        with config.configure(
            min_batch_rows=8, memory_heal_after_batches=2, **opts
        ):
            with tm.run("heal") as cap:
                ctx = AnalysisRunner.do_analysis_run(
                    ds, ANALYZERS, engine=make_engine()
                )
        actions = [e["action"] for e in _memory_events(cap)]
        assert actions == ["oom", "backoff", "heal"]
        assert (
            tm.metrics.gauge("engine.batch_rows_effective").value
            == g["full"]
        )
        assert ctx.metric(Size()).value.get() == g["n"]
        assert ctx.degradation is None or not ctx.degradation.is_degraded


class TestBackoffDisabled:
    def test_dispatch_oom_fails_scan_when_disabled(self):
        """memory_backoff=False restores the pre-backoff contract: a
        dispatch allocation failure aborts the scan (failure metrics),
        is never counted as an OOM event, and never shrinks anything."""
        tm = get_telemetry()
        oom_before = tm.counter("engine.oom_events").value
        ds = FaultInjectingDataset(
            Dataset.from_pydict(_table_data()), oom_at_batch={1: 1}
        )
        with config.configure(
            device_cache_bytes=0,
            batch_size=104,
            memory_backoff=False,
            scan_retry=FAST_RETRY,
        ):
            ctx = AnalysisRunner.do_analysis_run(
                ds, ANALYZERS, engine=AnalysisEngine()
            )
        assert not ctx.metric(Size()).value.is_success
        assert tm.counter("engine.oom_events").value == oom_before

    def test_transfer_stage_oom_backs_off(self):
        """Streaming's host->device transfer is its own guarded stage:
        an OOM there records stage="transfer" and re-feeds the SAME
        rows through the sub-batch path — no rows lost."""
        tm = get_telemetry()
        ds = FaultInjectingDataset(
            Dataset.from_pydict(_table_data()), oom_transfer_at={1: 1}
        )
        with config.configure(
            device_cache_bytes=0, batch_size=104, **BACKOFF_OPTS
        ):
            with tm.run("transfer-oom") as cap:
                ctx = AnalysisRunner.do_analysis_run(
                    ds, ANALYZERS, engine=AnalysisEngine()
                )
        events = _memory_events(cap)
        assert [e["action"] for e in events] == ["oom", "backoff"]
        assert events[0]["stage"] == "transfer"
        assert ds.faults_fired == [("oom", "transfer", 1, 104)]
        assert ctx.metric(Size()).value.get() == 1000
        assert ctx.degradation is None or not ctx.degradation.is_degraded


# --------------------------------------------------------------------------
# Checkpoint/resume across an OOM-backoff boundary
# --------------------------------------------------------------------------


@pytest.mark.parametrize("mode", ["resident", "streaming"])
class TestCheckpointAcrossBackoff:
    def test_kill_resume_with_backoff_bit_identical(
        self, mode, cpu_mesh, tmp_path
    ):
        """Checkpoint cursors keep the NOMINAL batch size (backoff is
        internal to a dispatch), so a run killed while backed off
        resumes cleanly — and the resumed total still equals the native
        settled-size run bit-for-bit."""
        g = _geometry(mode)
        data = _table_data(g["n"])
        make_engine, opts = _mode_setup(mode, cpu_mesh, g["full"])
        _, ref_opts = _mode_setup(mode, cpu_mesh, g["settled"])
        tm = get_telemetry()
        with config.configure(
            scan_retry=FAST_RETRY, checkpoint_every_batches=3,
            **BACKOFF_OPTS, **ref_opts,
        ):
            ref = _metric_values(
                AnalysisRunner.do_analysis_run(
                    Dataset.from_pydict(data), ANALYZERS,
                    engine=make_engine(),
                )
            )
        with config.configure(
            scan_retry=FAST_RETRY, checkpoint_every_batches=3,
            **BACKOFF_OPTS, **opts,
        ):
            ckpt = ScanCheckpointer(str(tmp_path))
            engine = make_engine(checkpointer=ckpt)
            ds = FaultInjectingDataset(
                Dataset.from_pydict(data),
                oom_rows_over=g["over"],
                kill_at_batch=7,
            )
            resumes_before = tm.counter("engine.resumes").value
            with pytest.raises(ScanKilled):
                AnalysisRunner.do_analysis_run(ds, ANALYZERS, engine=engine)
            assert ckpt._storage.list_keys("scan-ckpt-")
            ctx = AnalysisRunner.do_analysis_run(ds, ANALYZERS, engine=engine)
            assert tm.counter("engine.resumes").value - resumes_before == 1
        assert _metric_values(ctx) == ref
        assert ckpt._storage.list_keys("scan-ckpt-") == []


# --------------------------------------------------------------------------
# Spill/collector downgrade chain: collector -> deferred -> host Arrow
# --------------------------------------------------------------------------


class TestSpillDowngrade:
    N = 4096

    @pytest.fixture
    def spill_data(self):
        rng = np.random.default_rng(42)
        return {
            "v": rng.normal(size=self.N).tolist(),
            "dense_g": (np.arange(self.N) % 5).tolist(),
            "id": rng.integers(0, 2**40, self.N).tolist(),
        }

    ANALYZERS = None  # set in _analyzers to keep instances fresh

    def _analyzers(self):
        return [
            Size(),
            Mean("v"),
            Uniqueness(["dense_g"]),  # forced onto the spill path
            Uniqueness(["id"]),  # high-cardinality spill plan
        ]

    def _overrides(self, **extra):
        base = dict(
            # resident cache: device_spill_eligible needs it — the
            # chunked sort path is what the downgrade chain protects
            device_cache_bytes=1 << 30,
            batch_size=512,
            scan_retry=FAST_RETRY,
            one_pass_spill=True,
            dense_grouping_budget_bytes=4 * 1024,
            **BACKOFF_OPTS,
        )
        base.update(extra)
        return base

    def _ref(self, cpu_mesh, spill_data, **extra):
        analyzers = self._analyzers()
        with config.configure(**self._overrides(**extra)):
            return _metric_values(
                AnalysisRunner.do_analysis_run(
                    Dataset.from_pydict(spill_data), analyzers,
                    engine=AnalysisEngine(mesh=cpu_mesh),
                ),
                analyzers,
            )

    def test_finalize_oom_downgrades_to_deferred(self, cpu_mesh, spill_data):
        ref = self._ref(cpu_mesh, spill_data)
        tm = get_telemetry()
        before = tm.counter("engine.spill_downgrades").value
        ds = FaultInjectingDataset(
            Dataset.from_pydict(spill_data), oom_finalize=1
        )
        analyzers = self._analyzers()
        with config.configure(**self._overrides()):
            with tm.run("finalize-oom") as cap:
                ctx = AnalysisRunner.do_analysis_run(
                    ds, analyzers, engine=AnalysisEngine(mesh=cpu_mesh)
                )
        assert _metric_values(ctx, analyzers) == ref
        assert tm.counter("engine.spill_downgrades").value - before == 1
        downgrades = [
            e for e in _memory_events(cap)
            if e["action"] == "spill-downgrade"
        ]
        assert len(downgrades) == 1
        assert downgrades[0]["stage"] == "finalize"
        assert downgrades[0]["path"] == "deferred"
        assert ("oom", "finalize", 0, 0) in ds.faults_fired

    def test_finalize_then_deferred_oom_falls_to_arrow(
        self, cpu_mesh, spill_data
    ):
        """Both rungs under pressure: collector finalize OOMs into the
        deferred re-scan, whose own device sort OOMs into Arrow's host
        group_by — results still exact."""
        ref = self._ref(cpu_mesh, spill_data)
        tm = get_telemetry()
        before = tm.counter("engine.spill_downgrades").value
        arrow_before = tm.counter("grouping.spill.host-arrow-oom").value
        ds = FaultInjectingDataset(
            Dataset.from_pydict(spill_data),
            oom_finalize=1,
            oom_deferred=1,
        )
        analyzers = self._analyzers()
        with config.configure(**self._overrides()):
            ctx = AnalysisRunner.do_analysis_run(
                ds, analyzers, engine=AnalysisEngine(mesh=cpu_mesh)
            )
        assert _metric_values(ctx, analyzers) == ref
        assert tm.counter("engine.spill_downgrades").value - before == 2
        assert (
            tm.counter("grouping.spill.host-arrow-oom").value
            - arrow_before
            == 1
        )

    def test_deferred_path_oom_without_collectors(
        self, cpu_mesh, spill_data
    ):
        """one_pass_spill=False takes the per-plan deferred scans
        directly; a device-sort OOM there downgrades to Arrow."""
        ref = self._ref(cpu_mesh, spill_data)
        tm = get_telemetry()
        before = tm.counter("engine.spill_downgrades").value
        ds = FaultInjectingDataset(
            Dataset.from_pydict(spill_data), oom_deferred=1
        )
        analyzers = self._analyzers()
        with config.configure(**self._overrides(one_pass_spill=False)):
            ctx = AnalysisRunner.do_analysis_run(
                ds, analyzers, engine=AnalysisEngine(mesh=cpu_mesh)
            )
        assert _metric_values(ctx, analyzers) == ref
        assert tm.counter("engine.spill_downgrades").value - before == 1

    def test_spill_suite_backoff_differential(self, spill_data):
        """Batch backoff under a mixed suite (scalars + dense grouping
        + one-pass spill collectors): the collector key buffers fill
        through the sub-batch path and still match the native
        settled-size run exactly. Single-device engine: a resident
        MESH chunk is device_put once with the nominal-batch sharding,
        so a row sub-slice would inherit a different per-device
        partition (different reduction grouping, ~1 ULP) than a
        natively-sized run — value-equal, not bit-equal."""
        analyzers = self._analyzers()
        with config.configure(**self._overrides(batch_size=256)):
            ref = _metric_values(
                AnalysisRunner.do_analysis_run(
                    Dataset.from_pydict(spill_data), analyzers,
                    engine=AnalysisEngine(),
                ),
                analyzers,
            )
        ds = FaultInjectingDataset(
            Dataset.from_pydict(spill_data), oom_rows_over=300
        )
        analyzers = self._analyzers()
        with config.configure(**self._overrides(batch_size=512)):
            ctx = AnalysisRunner.do_analysis_run(
                ds, analyzers, engine=AnalysisEngine()
            )
        assert _metric_values(ctx, analyzers) == ref
        assert any(f[0] == "oom" for f in ds.faults_fired)


# --------------------------------------------------------------------------
# Watermark admission (engine/deadline.py gate + runner plumbing)
# --------------------------------------------------------------------------


class TestWatermarkAdmission:
    def test_second_run_queues_past_watermark(self):
        ctl = AdmissionController()
        ctl.acquire(0, estimated_bytes=600, watermark_bytes=1000)
        assert ctl.snapshot() == {
            "active": 1, "queued": 0, "active_bytes": 600,
        }
        admitted = []

        def worker():
            ctl.acquire(0, estimated_bytes=600, watermark_bytes=1000)
            admitted.append(True)
            ctl.release(600)

        t = threading.Thread(target=worker)
        t.start()
        _spin_until(lambda: ctl.snapshot()["queued"] == 1, "worker queued")
        assert admitted == []  # 600 + 600 > 1000: held back
        ctl.release(600)
        t.join(timeout=5)
        assert admitted == [True]
        assert ctl.snapshot() == {
            "active": 0, "queued": 0, "active_bytes": 0,
        }

    def test_oversized_single_run_admits_when_idle(self):
        # a run bigger than the watermark must not deadlock an idle
        # controller: alone, it always admits (it may still OOM and
        # back off — that is the scan layer's job)
        ctl = AdmissionController()
        ctl.acquire(0, estimated_bytes=10_000, watermark_bytes=100)
        assert ctl.snapshot()["active"] == 1
        assert ctl.snapshot()["active_bytes"] == 10_000
        ctl.release(10_000)
        assert ctl.snapshot()["active_bytes"] == 0

    def test_no_estimate_no_gate(self):
        # unsized sources contribute nothing to the watermark sum and
        # are never held back by it
        ctl = AdmissionController()
        ctl.acquire(0, estimated_bytes=0, watermark_bytes=100)
        ctl.acquire(0, estimated_bytes=0, watermark_bytes=100)
        assert ctl.snapshot() == {
            "active": 2, "queued": 0, "active_bytes": 0,
        }
        ctl.release()
        ctl.release()

    def test_estimated_run_bytes_scales_with_columns(self):
        engine = AnalysisEngine()
        one = Dataset.from_pydict({"a": [1.0] * 100})
        two = Dataset.from_pydict({"a": [1.0] * 100, "b": [2.0] * 100})
        est_one = engine.estimated_run_bytes(one)
        est_two = engine.estimated_run_bytes(two)
        assert 0 < est_one < est_two

    def test_runner_watermark_end_to_end(self):
        tm = get_telemetry()
        queued_before = tm.counter("engine.runs_queued").value
        with config.configure(
            device_cache_bytes=0,
            batch_size=104,
            memory_watermark_bytes=1 << 40,
        ):
            ctx = AnalysisRunner.do_analysis_run(
                Dataset.from_pydict(_table_data()), [Size()]
            )
        assert ctx.metric(Size()).value.get() == 1000
        # uncontended: admitted without queueing, bytes released after
        assert tm.counter("engine.runs_queued").value == queued_before
        snap = admission_controller().snapshot()
        assert snap["active"] == 0
        assert snap["active_bytes"] == 0


# --------------------------------------------------------------------------
# Degradation policy: exhausted backoff floors verification status
# --------------------------------------------------------------------------


class TestDegradationPolicy:
    def _degraded_result(self, policy):
        # the check PASSES on the partial data — status movement below
        # comes from the degradation floor alone
        check = Check(CheckLevel.ERROR, "mem").has_size(lambda s: s > 0)
        ds = FaultInjectingDataset(
            Dataset.from_pydict(_table_data()), oom_at_batch={2: 99}
        )
        with config.configure(
            device_cache_bytes=0,
            batch_size=104,
            min_batch_rows=104,  # floor == full: backoff exhausts at once
            scan_retry=FAST_RETRY,
            degradation_policy=policy,
        ):
            return VerificationSuite.do_verification_run(ds, [check])

    def test_fail_policy_floors_to_error(self):
        result = self._degraded_result("fail")
        assert result.status == CheckStatus.ERROR
        assert result.degradation.batches_quarantined == 1
        assert result.degradation.error_classes == ["BackoffExhausted"]

    def test_warn_policy_floors_to_warning(self):
        result = self._degraded_result("warn")
        assert result.status == CheckStatus.WARNING

    def test_tolerate_policy_keeps_check_status(self):
        result = self._degraded_result("tolerate")
        assert result.status == CheckStatus.SUCCESS
        assert result.degradation.rows_skipped == 104


# --------------------------------------------------------------------------
# Row-level export degrade (verification/rowlevel.py satellite)
# --------------------------------------------------------------------------


class TestRowLevelDegrade:
    def test_one_bad_one_good_predicate_export(self):
        """An unplannable predicate drops ITS row-level column only —
        the plannable constraint still exports."""
        ds = Dataset.from_pydict({"a": [1.0, -2.0, 3.0]})
        check = (
            Check(CheckLevel.ERROR, "rl")
            .satisfies("a >= 0", "a-non-negative", lambda v: v == 1.0)
            .satisfies("nosuchcol >= 0", "phantom-column", lambda v: v == 1.0)
        )
        result = VerificationSuite().on_data(ds).add_check(check).run()
        # aggregate path: the bad constraint reported a FAILURE result
        assert result.status == CheckStatus.ERROR
        rl = result.row_level_results_as_dataset().table
        good = [n for n in rl.schema.names if "a-non-negative" in n]
        assert good, rl.schema.names
        assert rl.column(good[0]).to_pylist() == [True, False, True]
        assert not [n for n in rl.schema.names if "phantom-column" in n]

    def test_bad_where_filter_drops_only_its_column(self):
        ds = Dataset.from_pydict({"a": [1.0, 2.0, 3.0]})
        check = (
            Check(CheckLevel.ERROR, "rl")
            .has_min("a", lambda v: v <= 10)
            .where("nosuchcol > 0")  # unplannable filter
            .has_completeness("a", lambda v: v == 1.0)
        )
        result = VerificationSuite().on_data(ds).add_check(check).run()
        rl = result.row_level_results_as_dataset().table
        names = rl.schema.names
        complete = [n for n in names if "Completeness" in n]
        assert complete, names
        assert rl.column(complete[0]).to_pylist() == [True, True, True]
        assert not [n for n in names if "Minimum" in n]


# --------------------------------------------------------------------------
# Observability: obs_report rendering + run captures
# --------------------------------------------------------------------------


class TestObsReport:
    def test_renders_memory_pressure_lines(self):
        from tools.obs_report import render_run

        summary = {
            "run_id": 1,
            "name": "memory",
            "wall_s": 1.0,
            "counters": {
                "engine.oom_events": 2,
                "engine.batch_size_backoffs": 1,
                "engine.spill_downgrades": 1,
            },
            "events": [
                {
                    "event": "scan_memory_pressure", "action": "oom",
                    "stage": "dispatch", "batch_index": 3, "rows": 104,
                    "origin": "device",
                },
                {
                    "event": "scan_memory_pressure", "action": "backoff",
                    "from_rows": 104, "effective_rows": 52,
                },
                {
                    "event": "scan_memory_pressure", "action": "heal",
                    "from_rows": 52, "effective_rows": 104,
                },
                {
                    "event": "scan_memory_pressure", "action": "exhausted",
                    "batch_index": 5, "effective_rows": 8,
                },
                {
                    "event": "scan_memory_pressure",
                    "action": "spill-downgrade", "stage": "finalize",
                    "columns": ["id"], "path": "deferred",
                },
            ],
        }
        text = render_run(summary)
        assert "engine.oom_events" in text
        assert "engine.batch_size_backoffs" in text
        assert "engine.spill_downgrades" in text
        assert "memory pressure (device) at dispatch batch 3" in text
        assert "batch size backoff: 104 -> 52 rows" in text
        assert "batch size heal: 52 -> 104 rows" in text
        assert "backoff exhausted at batch 5 (floor=8 rows)" in text
        assert "spill downgrade (id): finalize -> deferred" in text

    def test_capture_end_to_end(self):
        from tools.obs_report import render_run

        tm = get_telemetry()
        ds = FaultInjectingDataset(
            Dataset.from_pydict(_table_data()), oom_rows_over=60
        )
        with config.configure(
            device_cache_bytes=0, batch_size=104, **BACKOFF_OPTS
        ):
            with tm.run("memory-report") as cap:
                AnalysisRunner.do_analysis_run(
                    ds, ANALYZERS, engine=AnalysisEngine()
                )
        text = render_run(cap.final)
        assert "engine.oom_events" in text
        assert "memory pressure (device)" in text
        assert "batch size backoff: 104 -> 52 rows" in text


# --------------------------------------------------------------------------
# Zero-cost default
# --------------------------------------------------------------------------


class TestZeroCostDefault:
    def test_clean_run_emits_no_memory_telemetry(self):
        tm = get_telemetry()
        names = (
            "engine.oom_events",
            "engine.batch_size_backoffs",
            "engine.spill_downgrades",
        )
        before = [tm.counter(n).value for n in names]
        with config.configure(device_cache_bytes=0, batch_size=104):
            with tm.run("zero-cost") as cap:
                AnalysisRunner.do_analysis_run(
                    Dataset.from_pydict(_table_data()), ANALYZERS
                )
        assert _memory_events(cap) == []
        assert [tm.counter(n).value for n in names] == before

    def test_protection_off_equals_on_for_clean_data(self):
        data = _table_data()
        with config.configure(device_cache_bytes=0, batch_size=104):
            on = _metric_values(
                AnalysisRunner.do_analysis_run(
                    Dataset.from_pydict(data), ANALYZERS
                )
            )
        with config.configure(
            device_cache_bytes=0, batch_size=104, memory_backoff=False
        ):
            off = _metric_values(
                AnalysisRunner.do_analysis_run(
                    Dataset.from_pydict(data), ANALYZERS
                )
            )
        assert on == off


# --------------------------------------------------------------------------
# telemetry_lint: no ad-hoc OOM classification in the hot path
# --------------------------------------------------------------------------


class TestLintOOMRule:
    def test_repo_hot_paths_are_clean(self):
        from tools.telemetry_lint import find_violations

        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        assert find_violations(root) == []

    def test_adhoc_oom_handling_flagged(self, tmp_path):
        from tools.telemetry_lint import find_violations

        mod = tmp_path / "deequ_tpu" / "engine"
        mod.mkdir(parents=True)
        (mod / "bad.py").write_text(
            "try:\n"
            "    pass\n"
            "except MemoryError:\n"
            "    pass\n"
            "MARKER = 'RESOURCE_EXHAUSTED: boom'\n"
        )
        tokens = {t for _rel, _line, t in find_violations(str(tmp_path))}
        assert "MemoryError" in tokens
        assert "<oom marker string>" in tokens

    def test_memory_module_is_exempt(self, tmp_path):
        from tools.telemetry_lint import find_violations

        mod = tmp_path / "deequ_tpu" / "engine"
        mod.mkdir(parents=True)
        (mod / "memory.py").write_text(
            "MARKERS = ('RESOURCE_EXHAUSTED', 'out of memory')\n"
            "def classify(exc):\n"
            "    if isinstance(exc, MemoryError):\n"
            "        return 'host'\n"
        )
        assert find_violations(str(tmp_path)) == []
