"""Engine configuration.

The reference has no global config — everything is per-run builder
options (SURVEY.md §5.6) — but the north star asks for an engine
selection flag (the ``deequ.engine=tpu`` analog) and the TPU build needs
a handful of hardware-shaping knobs that have no Spark equivalent:

- ``accumulation_dtype`` — dtype of scalar *float* state accumulators.
  On TPU, float64 is software-emulated; the hot path therefore does
  per-element work in the column's native dtype and only casts the
  per-batch *scalar* reduction results into the accumulation dtype, so
  "float64" costs a few emulated scalar ops per batch instead of an
  emulated elementwise pass (VERDICT.md weak #4). Counts are ALWAYS
  exact int64, and integral columns always widen per element to f64 —
  the knob never changes integer semantics.
- ``device_cache_bytes`` — budget for keeping device-resident columns.
  Host->device bandwidth is the bottleneck (on this image the chip sits
  behind a ~100 MB/s tunnel); the multi-pass profiler re-reads the same
  columns, so columns are transferred once and cached on device.
- ``synthesize_all_true_masks`` — columns with no nulls get their
  validity mask created ON device (jnp.ones) instead of shipping
  num_rows bytes over the wire.
- ``compilation_cache_dir`` — persistent XLA compilation cache; the
  fused scan re-traces per run (ops are per-dataset closures) but XLA
  compilation — the dominant cost — is reused across runs/processes.
- ``engine`` — "tpu" (default: whatever jax.devices() provides) or
  "cpu" (force host platform); the engine-selection flag.

Configuration may be set via ``deequ_tpu.config.set_option``, the
``configure(...)`` context manager, or ``DEEQU_TPU_*`` environment
variables read at import.
"""

from __future__ import annotations

import contextlib
import os
import threading
from dataclasses import dataclass, field, replace
from typing import Iterator, Optional


@dataclass
class Options:
    # dtype for scalar state accumulators ("float64" | "float32")
    accumulation_dtype: str = "float64"
    # device-resident column cache budget (bytes); 0 disables
    device_cache_bytes: int = int(
        os.environ.get("DEEQU_TPU_DEVICE_CACHE_BYTES", 8 << 30)
    )
    # synthesize masks of all-valid columns on device (skip transfer)
    synthesize_all_true_masks: bool = True
    # device budget for dense grouping count vectors (bytes); caps the
    # joint key space the frequency pass keeps on device (~2^28 keys/GB
    # at i32 counts) before spilling to the host Arrow group_by
    dense_grouping_budget_bytes: int = int(
        os.environ.get("DEEQU_TPU_DENSE_GROUPING_BYTES", 1 << 30)
    )
    # device sort+segment path for high-cardinality single-numeric-column
    # grouping (analyzers/spill.py); False forces the host Arrow fallback
    device_spill_grouping: bool = True
    # fold spill key extraction into the shared fused scan (ONE source
    # traversal for scalars + dense + spill plans, with the per-plan
    # sort finalizes overlapped); False restores the per-plan deferred
    # re-scan path — kept for differential testing and as an escape
    # hatch
    one_pass_spill: bool = True
    # route the HLL register scatter-max through the measured unroll-16
    # Pallas SMEM kernel (tools/scatter_probe.py: 1.1-1.15x over the XLA
    # scatter at (2^21, M=2^14)) when the backend supports it; falls
    # back to the XLA scatter automatically when Pallas/Mosaic is
    # unavailable (CPU, old jax). Registers are bit-identical either
    # way (tests/test_fastpath_differential.py). Off by default until
    # the production-shape probe artifact justifies flipping it
    # (docs/PERF.md "Pallas scatter")
    pallas_scatter: bool = (
        os.environ.get("DEEQU_TPU_PALLAS_SCATTER", "0") == "1"
    )
    # widened sorted-dedup HLL gate (sketches/hll.py): integer columns
    # whose O(1) range probe FAILS (unknown or wide declared range) may
    # still ride the shared KLL sort's sorted-dedup register builder
    # when their carried-register cardinality estimate says
    # mid-cardinality AND the batch's values fit the f32 mantissa
    # (both checked in-kernel; a mispredicted estimate falls back to
    # the full scatter inside the branch). False restores the
    # range-probe-only gate — kept as the differential reference
    hll_dedup_widening: bool = (
        os.environ.get("DEEQU_TPU_HLL_DEDUP_WIDENING", "1") != "0"
    )
    # per-column wire codecs on the streamed packed wire
    # (engine/wire.py, docs/PERF.md "Wire diet"): each column's wire
    # dtype is resolved ONCE per run from parquet statistics or a
    # first-batch probe (int64 -> i32/i16/i8 by range, f64 -> f32 when
    # values provably round-trip bit-exactly, codes/lengths by observed
    # magnitude) and decoded back to the canonical dtype inside the
    # fused wire_unpack, so device programs are bit-identical either
    # way. False ships today's canonical-width wire — kept as the
    # differential oracle (tests/test_wire_codecs.py)
    wire_codecs: bool = (
        os.environ.get("DEEQU_TPU_WIRE_CODECS", "1") != "0"
    )
    # one-pass dictionary deltas for streamed string codes
    # (data/parquet.py, engine/vectorize.py): dictionaries build
    # incrementally per batch and ship only the NEW uniques (delta
    # payloads applied to LUT-carrying op states), killing the
    # _dict_value_set streaming pre-pass — string-code suites traverse
    # the source exactly once. False restores the pre-pass path
    dict_deltas: bool = (
        os.environ.get("DEEQU_TPU_DICT_DELTAS", "1") != "0"
    )
    # static LUT capacity (entries) carried by delta-aware op states; a
    # dictionary growing past it is a deterministic error (raise the
    # cap or set dict_deltas=False for that source)
    dict_delta_capacity: int = int(
        os.environ.get("DEEQU_TPU_DICT_DELTA_CAPACITY", 1 << 16)
    )
    # parallel host ingest (engine/ingest.py, docs/PERF.md "r10"):
    # decode/encode worker threads feeding the streaming scan through
    # the ordered reassembly stage. 0 = auto (min(4, cpu count));
    # 1 = the single-prefetch-thread path, bit-identical to the
    # pre-pool engine (the differential oracle). Host-pipeline only:
    # never part of the plan fingerprint — flipping it must not
    # retrace or recompile anything
    ingest_workers: int = int(
        os.environ.get("DEEQU_TPU_INGEST_WORKERS", 0) or 0
    )
    # bounded prefetch queue depth for the single-worker path (the
    # old hard-coded depth=2 of engine/scan._prefetched); host-pipeline
    # only, plan-fingerprint-neutral like ingest_workers
    ingest_depth: int = int(
        os.environ.get("DEEQU_TPU_INGEST_DEPTH", 2) or 2
    )
    # max batches in flight inside the ingest pool (queued + decoding
    # + decoded-awaiting-ordered-release); bounds host memory under
    # the PR 5 admission watermark. 0 = auto (2 * workers)
    ingest_lookahead: int = int(
        os.environ.get("DEEQU_TPU_INGEST_LOOKAHEAD", 0) or 0
    )
    # process-sharded ingest on the mesh streaming path: each process
    # reads only its own row-group shard (ParquetDataset.shard_view)
    # and feeds ONE global array per batch leaf via
    # jax.make_array_from_process_local_data (SNIPPETS.md [2]
    # partitioner pattern). With a single process this is exactly the
    # plain device_put feed; multi-process runs also perform the r5
    # uniform compile-failure exchange so no host strands its peers
    process_sharded_ingest: bool = (
        os.environ.get("DEEQU_TPU_PROCESS_SHARDED_INGEST", "1") != "0"
    )
    # persistent XLA compilation cache directory ("" disables)
    compilation_cache_dir: str = os.environ.get(
        "DEEQU_TPU_COMPILE_CACHE", os.path.expanduser("~/.cache/deequ_tpu_xla")
    )
    # engine selection: "tpu" (default jax backend) | "cpu"
    engine: str = os.environ.get("DEEQU_TPU_ENGINE", "tpu")
    # rows per fused-scan batch when streaming (None = engine default)
    batch_size: Optional[int] = None
    # per-batch retry policy for the scan's read/decode/transfer stages
    # (engine/resilience.RetryPolicy; None = the engine's default
    # policy — 3 attempts, exponential backoff, deterministic jitter).
    # Set max_attempts=1 to disable retries entirely.
    scan_retry: Optional[object] = None
    # how a degraded run (quarantined batches in the fused scan) maps
    # onto VerificationSuite status: "fail" (the run is Error), "warn"
    # (at least Warning), "tolerate" (status unchanged; the
    # degradation record still rides the result)
    degradation_policy: str = os.environ.get(
        "DEEQU_TPU_DEGRADATION_POLICY", "fail"
    )
    # batches between scan checkpoints when the engine has a
    # ScanCheckpointer attached (io/state_provider.py); <= 0 disables
    checkpoint_every_batches: int = int(
        os.environ.get("DEEQU_TPU_CHECKPOINT_EVERY", 64)
    )
    # deadlines & cancellation (engine/deadline.py, docs/RESILIENCE.md):
    # wall-clock budget for a whole analysis/verification run — on
    # exhaustion the scan exits cleanly with partial metrics and a
    # final checkpoint cursor; <= 0 disables
    run_deadline_seconds: float = float(
        os.environ.get("DEEQU_TPU_RUN_DEADLINE", 0) or 0
    )
    # per-batch stall limit: a batch taking longer than this raises
    # ScanStalled (transient -> retry -> quarantine); <= 0 disables
    batch_stall_seconds: float = float(
        os.environ.get("DEEQU_TPU_BATCH_STALL", 0) or 0
    )
    # bounded admission: at most this many concurrent analysis runs in
    # the process, the rest queue FIFO under their own deadline;
    # 0 = unlimited
    max_concurrent_runs: int = int(
        os.environ.get("DEEQU_TPU_MAX_CONCURRENT_RUNS", 0) or 0
    )
    # memory-pressure resilience (engine/memory.py,
    # docs/RESILIENCE.md "Memory pressure"): adaptive batch backoff —
    # a batch whose dispatch/transfer OOMs is re-fed through a chunked
    # path at a geometrically halved effective batch size; False
    # restores the pre-backoff behavior (a device OOM aborts the scan)
    memory_backoff: bool = (
        os.environ.get("DEEQU_TPU_MEMORY_BACKOFF", "1") != "0"
    )
    # floor for the backed-off effective batch size; an allocation
    # that still fails here quarantines the remaining rows instead
    min_batch_rows: int = int(
        os.environ.get("DEEQU_TPU_MIN_BATCH_ROWS", 4096)
    )
    # consecutive clean batches at a reduced size before the effective
    # size heals back up (doubles); <= 0 disables healing (the scan
    # stays at the reduced size until it ends)
    memory_heal_after_batches: int = int(
        os.environ.get("DEEQU_TPU_MEMORY_HEAL_AFTER", 8)
    )
    # admission high-watermark (bytes): concurrent runs queue once the
    # sum of their estimated device footprints
    # (engine.estimated_run_bytes, from scan_row_capacity geometry)
    # would exceed this — queueing instead of co-OOMing; 0 disables
    memory_watermark_bytes: int = int(
        os.environ.get("DEEQU_TPU_MEMORY_WATERMARK_BYTES", 0) or 0
    )
    # multi-tenant verification service (deequ_tpu/service/,
    # docs/SERVICE.md): executor worker threads draining the run queue
    service_workers: int = int(
        os.environ.get("DEEQU_TPU_SERVICE_WORKERS", 2)
    )
    # of those, how many only ever take INTERACTIVE-class runs — the
    # anti-starvation reserve (a long BATCH run can never occupy every
    # worker); clamped to service_workers - 1 so batch work always has
    # at least one worker
    service_interactive_reserve: int = int(
        os.environ.get("DEEQU_TPU_SERVICE_INTERACTIVE_RESERVE", 1)
    )
    # bytes watermark for the service's shared resident-dataset
    # registry (service/caches.py DatasetCache): registered handles are
    # evicted LRU-first once the sum of their estimated run bytes
    # exceeds this; 0 = fall back to device_cache_bytes
    service_dataset_watermark_bytes: int = int(
        os.environ.get("DEEQU_TPU_SERVICE_DATASET_WATERMARK", 0) or 0
    )
    # per-tenant quotas: max runs a tenant may have queued+active at
    # once (submit raises QuotaExceeded beyond it), and max
    # simultaneously ACTIVE; 0 = unlimited
    service_tenant_max_pending: int = int(
        os.environ.get("DEEQU_TPU_SERVICE_TENANT_MAX_PENDING", 0) or 0
    )
    service_tenant_max_active: int = int(
        os.environ.get("DEEQU_TPU_SERVICE_TENANT_MAX_ACTIVE", 0) or 0
    )
    # crash isolation (engine/subproc.py, docs/RESILIENCE.md "Crash
    # isolation and recovery"): run service executions in a
    # spawn-started child process so a hard crash (SIGSEGV/OOM-kill)
    # costs one checkpoint window, not the daemon
    isolated_execution: bool = (
        os.environ.get("DEEQU_TPU_ISOLATED_EXECUTION", "0") == "1"
    )
    # child relaunches WITHOUT checkpoint progress before the run is
    # declared a crash loop (the poison-batch bound); each relaunch
    # that advanced the cursor resets the count
    crash_max_relaunches: int = int(
        os.environ.get("DEEQU_TPU_CRASH_MAX_RELAUNCHES", 3)
    )
    # per-plan crash-loop circuit breaker: seconds the breaker stays
    # OPEN (rejecting launches fast) before one half-open probe launch
    # is allowed through; <= 0 disables the breaker entirely
    crash_breaker_cooldown_s: float = float(
        os.environ.get("DEEQU_TPU_CRASH_BREAKER_COOLDOWN", 30.0)
    )
    # durable write-ahead run journal directory (service/journal.py);
    # "" disables journaling (and with it restart recovery)
    service_journal_dir: str = os.environ.get(
        "DEEQU_TPU_SERVICE_JOURNAL_DIR", ""
    )
    # load shedding at the submission edge: BATCH-priority submits are
    # rejected fast (ServiceOverloaded, with a retry-after hint) once
    # the queue holds this many runs; 0 disables
    service_shed_queue_depth: int = int(
        os.environ.get("DEEQU_TPU_SERVICE_SHED_QUEUE_DEPTH", 0) or 0
    )
    # ... and once this many child crashes landed inside the sliding
    # crash-rate window (service-wide, any plan); 0 disables
    service_shed_crash_rate: int = int(
        os.environ.get("DEEQU_TPU_SERVICE_SHED_CRASH_RATE", 0) or 0
    )
    # sliding-window length (seconds) for the crash-rate shed signal
    service_shed_crash_window_s: float = float(
        os.environ.get("DEEQU_TPU_SERVICE_SHED_CRASH_WINDOW", 60.0)
    )
    # scan coalescing (docs/SERVICE.md "Scan coalescing"): compatible
    # queued runs targeting the same dataset_key share ONE superset
    # scan, each tenant's AnalyzerContext sliced back out. Opt-in (like
    # pallas_scatter/isolated_execution): default-off keeps existing
    # solo-run latency/ordering semantics untouched
    service_coalesce: bool = (
        os.environ.get("DEEQU_TPU_SERVICE_COALESCE", "0") == "1"
    )
    # how long a BATCH-priority run may wait past submit for coalesce
    # peers to arrive (seconds, measured on the service's injected
    # clock); INTERACTIVE and STANDARD never wait. 0 = group only with
    # what is already queued
    service_coalesce_window_s: float = float(
        os.environ.get("DEEQU_TPU_SERVICE_COALESCE_WINDOW", 0) or 0
    )
    # ceiling on runs per superset scan (bounds merged-plan op count
    # and one failed group's blast radius)
    service_coalesce_max_members: int = int(
        os.environ.get("DEEQU_TPU_SERVICE_COALESCE_MAX_MEMBERS", 8) or 8
    )
    # elastic device placement (service/placement.py, docs/SERVICE.md
    # "Elastic placement"): bin-pack concurrent runs onto disjoint
    # power-of-two mesh sub-slices instead of serializing whole-mesh.
    # Opt-in like coalescing: default-off keeps today's host/whole-mesh
    # engine construction untouched
    service_elastic_placement: bool = (
        os.environ.get("DEEQU_TPU_SERVICE_ELASTIC_PLACEMENT", "0") == "1"
    )
    # placement policy: one device per this many estimated run bytes
    # (the admission watermark's estimate), rounded up to a power of two
    service_placement_bytes_per_device: int = int(
        os.environ.get(
            "DEEQU_TPU_SERVICE_PLACEMENT_BYTES_PER_DEVICE", 512 << 20
        )
        or (512 << 20)
    )
    # ceiling on a single run's slice (0 = the whole pool)
    service_placement_max_devices: int = int(
        os.environ.get("DEEQU_TPU_SERVICE_PLACEMENT_MAX_DEVICES", 0) or 0
    )
    # slice size for runs with no byte estimate (factory datasets)
    service_placement_default_devices: int = int(
        os.environ.get("DEEQU_TPU_SERVICE_PLACEMENT_DEFAULT_DEVICES", 1)
        or 1
    )
    # LRU cap on cached Mesh objects (one per distinct device slice)
    service_placement_mesh_cache_slices: int = int(
        os.environ.get("DEEQU_TPU_SERVICE_PLACEMENT_MESH_SLICES", 8) or 8
    )
    # end-to-end run tracing (docs/OBSERVABILITY.md "Tracing"): every
    # submission is minted a TraceContext at enqueue and the span tree
    # follows it across workers, coalesced groups, placement leases,
    # and the spawn boundary. Opt-in: default-off emits not one extra
    # span and adds no per-batch work above the existing PhaseClock
    service_trace: bool = (
        os.environ.get("DEEQU_TPU_SERVICE_TRACE", "0") == "1"
    )
    # live observability plane (telemetry/export.py serve_metrics):
    # port for the stdlib HTTP endpoint exposing /metrics (Prometheus
    # text) and /healthz (JSON health snapshot); 0 = no endpoint thread
    service_metrics_port: int = int(
        os.environ.get("DEEQU_TPU_SERVICE_METRICS_PORT", 0) or 0
    )
    # per-class queue-wait latency objectives for the SloTracker, as
    # "class=seconds" pairs ("interactive=1.0,batch=30"); "" disables
    # SLO tracking (no tracker allocated, no oprecords persisted)
    service_slo_objectives: str = os.environ.get(
        "DEEQU_TPU_SERVICE_SLO_OBJECTIVES", ""
    )
    # checkpoint-conserving preemption (service/preempt.py,
    # docs/SERVICE.md "Preemption and autoscaling"): an INTERACTIVE
    # ticket that finds the pool/workers saturated preempts the
    # youngest solo BATCH run — cancel-with-checkpoint at the next
    # batch boundary, lease revoked, ticket requeued carrying its
    # cursor — and the victim later resumes with zero recompute and
    # zero recompile. Opt-in: default-off allocates no controller, no
    # per-attempt tokens, and changes no pop/finish semantics
    service_preemption: bool = (
        os.environ.get("DEEQU_TPU_SERVICE_PREEMPTION", "0") == "1"
    )
    # livelock bound: preemption requests a single run may absorb
    # before it becomes ineligible as a victim (it then runs to
    # completion however long interactive pressure lasts)
    service_preempt_max_per_run: int = int(
        os.environ.get("DEEQU_TPU_SERVICE_PREEMPT_MAX_PER_RUN", 3) or 3
    )
    # queue-driven autoscaling (service/autoscale.py): a control loop
    # adjusting worker count, interactive_reserve, and the coalesce
    # window from the per-class service.queue_wait_s.* histograms and
    # SLO burn. Opt-in; requires an explicit decision cadence
    service_autoscale: bool = (
        os.environ.get("DEEQU_TPU_SERVICE_AUTOSCALE", "0") == "1"
    )
    service_autoscale_interval_s: float = float(
        os.environ.get("DEEQU_TPU_SERVICE_AUTOSCALE_INTERVAL", 10.0)
        or 10.0
    )
    service_autoscale_min_workers: int = int(
        os.environ.get("DEEQU_TPU_SERVICE_AUTOSCALE_MIN_WORKERS", 1) or 1
    )
    service_autoscale_max_workers: int = int(
        os.environ.get("DEEQU_TPU_SERVICE_AUTOSCALE_MAX_WORKERS", 8) or 8
    )
    # queue-wait the interactive class should stay under (seconds);
    # the controller scales up / widens the reserve while the observed
    # p99 since the last decision exceeds it
    service_autoscale_target_interactive_p99_s: float = float(
        os.environ.get(
            "DEEQU_TPU_SERVICE_AUTOSCALE_TARGET_INTERACTIVE_P99", 1.0
        )
        or 1.0
    )
    # fleet failover (service/fleet.py, docs/SERVICE.md "Fleet
    # failover"): a non-empty shared fleet dir turns each journaling
    # replica into a fleet member — heartbeat lease + peer watch +
    # orphan adoption + epoch fencing. "" (default) = solo replica,
    # every fleet path byte-identical to the pre-fleet service.
    service_fleet_dir: str = os.environ.get(
        "DEEQU_TPU_SERVICE_FLEET_DIR", ""
    )
    # replica identity in the fleet dir's lease namespace; "" derives
    # replica-<pid> (fine for single-host loopback fleets, set it
    # explicitly for real deployments so adoption provenance is stable)
    service_fleet_replica: str = os.environ.get(
        "DEEQU_TPU_SERVICE_FLEET_REPLICA", ""
    )
    service_fleet_heartbeat_s: float = float(
        os.environ.get("DEEQU_TPU_SERVICE_FLEET_HEARTBEAT", 2.0) or 2.0
    )
    # how long a peer's (epoch, stamp) pair may sit unchanged on the
    # OBSERVER's clock before the lease is declared dead and adoption
    # races begin; must comfortably exceed heartbeat_s (the default
    # survives ~5 missed beats)
    service_fleet_lease_timeout_s: float = float(
        os.environ.get("DEEQU_TPU_SERVICE_FLEET_LEASE_TIMEOUT", 10.0)
        or 10.0
    )
    # distinct replicas a plan key must crash-loop before the shared
    # breaker ledger quarantines it fleet-wide at adoption time
    service_fleet_poison_replicas: int = int(
        os.environ.get("DEEQU_TPU_SERVICE_FLEET_POISON_REPLICAS", 2) or 2
    )

    def accumulation_float(self):
        import jax.numpy as jnp

        return jnp.float64 if self.accumulation_dtype == "float64" else jnp.float32


_lock = threading.Lock()
_options = Options()
_compile_cache_installed = False


def options() -> Options:
    return _options


def set_option(**kwargs) -> None:
    global _options
    with _lock:
        _options = replace(_options, **kwargs)


@contextlib.contextmanager
def configure(**kwargs) -> Iterator[Options]:
    """Temporarily override options within a block."""
    global _options
    with _lock:
        prev = _options
        _options = replace(_options, **kwargs)
    try:
        yield _options
    finally:
        with _lock:
            _options = prev


def install_compilation_cache() -> None:
    """Enable JAX's persistent compilation cache (idempotent). Called by
    the engine on first use; makes repeated runs of structurally
    identical fused scans skip XLA compilation entirely."""
    global _compile_cache_installed
    if _compile_cache_installed:
        return
    cache_dir = _options.compilation_cache_dir
    if not cache_dir:
        return
    try:
        import jax

        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
        # swap in the torn-write-safe store: atomic entry writes and
        # validate-on-read, so a crash mid-put can never poison later
        # runs with a truncated executable (docs/RESILIENCE.md)
        from deequ_tpu.engine import compile_cache

        compile_cache.install(cache_dir)
        _compile_cache_installed = True
    except Exception:  # cache is an optimization, never fatal
        pass
