"""Cross-cutting hardening: timestamp columns through the analyzers,
anomaly-check wiring through the suite builder, and the profiler over a
streamed parquet source."""

import datetime
import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from deequ_tpu import (
    Completeness,
    Dataset,
    InMemoryMetricsRepository,
    Maximum,
    Minimum,
    RelativeRateOfChangeStrategy,
    ResultKey,
    Size,
    VerificationSuite,
)
from deequ_tpu.data.table import Kind
from deequ_tpu.profiles.profiler import ColumnProfiler


class TestTimestampColumns:
    @pytest.fixture(scope="class")
    def ds(self):
        base = datetime.datetime(2024, 1, 1)
        stamps = [base + datetime.timedelta(days=i) for i in range(10)]
        return Dataset.from_arrow(
            pa.table(
                {
                    "ts": pa.array(stamps, pa.timestamp("ms")),
                    "ts_null": pa.array(
                        stamps[:5] + [None] * 5, pa.timestamp("ms")
                    ),
                }
            )
        )

    def test_kind(self, ds):
        assert ds.schema.kind_of("ts") == Kind.TIMESTAMP

    def test_min_max_reject_timestamps_like_reference(self, ds):
        """The reference's Minimum/Maximum preconditions require numeric
        columns (Spark's TimestampType is not) — a timestamp column
        degrades to a failure metric, never a wrong answer."""
        for metric in (
            Minimum("ts").calculate(ds),
            Maximum("ts").calculate(ds),
        ):
            assert metric.value.is_failure
            assert "numeric" in str(metric.value.exception)

    def test_completeness_with_nulls(self, ds):
        assert Completeness("ts_null").calculate(ds).value.get() == 0.5


class TestAnomalyCheckWiring:
    def test_add_anomaly_check_flags_regression(self):
        repo = InMemoryMetricsRepository()

        def run(n, t):
            return (
                VerificationSuite()
                .on_data(Dataset.from_pydict({"x": list(range(n))}))
                .use_repository(repo)
                .save_or_append_result(ResultKey.of(t))
                .add_anomaly_check(
                    RelativeRateOfChangeStrategy(
                        max_rate_decrease=0.5, max_rate_increase=2.0
                    ),
                    Size(),
                )
                .run()
            )

        for t, n in enumerate([1000, 1100, 950, 1050]):
            assert run(n, t).status.value in ("Success", "Warning") or t == 0
        # 10x explosion must flag
        assert run(11_000, 10).status.value != "Success"
        # and a normal day after is fine again
        assert run(10_900, 11).status.value in ("Success", "Warning")

    def test_anomaly_check_requires_repository(self):
        with pytest.raises(ValueError):
            (
                VerificationSuite()
                .on_data(Dataset.from_pydict({"x": [1]}))
                .add_anomaly_check(
                    RelativeRateOfChangeStrategy(), Size()
                )
            )


class TestProfilerOverParquet:
    def test_profile_streamed_source(self, tmp_path):
        rng = np.random.default_rng(11)
        n = 20_000
        table = pa.table(
            {
                "v": rng.normal(50, 5, n),
                "qty_str": pa.array([str(i % 7) for i in range(n)]),
                "label": pa.array(
                    np.array(["x", "y", "z"])[rng.integers(0, 3, n)]
                ),
            }
        )
        path = os.path.join(tmp_path, "p.parquet")
        pq.write_table(table, path)
        streamed = ColumnProfiler.profile(Dataset.from_parquet(path))
        in_memory = ColumnProfiler.profile(Dataset.from_arrow(table))
        assert streamed.num_records == in_memory.num_records == n
        for c in ("v", "qty_str", "label"):
            s, m = streamed[c], in_memory[c]
            assert s.data_type == m.data_type, c
            assert s.completeness == m.completeness, c
        # numeric-string promotion worked on the parquet path too
        assert streamed["qty_str"].data_type == Kind.INTEGRAL
        assert streamed["qty_str"].mean == pytest.approx(
            in_memory["qty_str"].mean
        )
        # histograms agree
        hs = streamed["label"].histogram
        hm = in_memory["label"].histogram
        assert {k: v.absolute for k, v in hs.values.items()} == {
            k: v.absolute for k, v in hm.values.items()
        }


class TestEngineSelectionFlag:
    def test_cpu_engine_matches_default(self):
        """config.engine='cpu' (the deequ.engine analog) places data on
        the host platform and produces identical metrics."""
        from deequ_tpu import Mean, StandardDeviation, config
        from deequ_tpu.analyzers import AnalysisRunner

        ds = Dataset.from_pydict(
            {"x": list(np.random.default_rng(5).normal(0, 1, 50_000))}
        )
        analyzers = [Mean("x"), StandardDeviation("x"), Size()]
        default_ctx = AnalysisRunner.do_analysis_run(ds, analyzers)
        ds2 = Dataset.from_pydict(
            {"x": list(np.asarray(ds.table.column("x")))}
        )
        with config.configure(engine="cpu"):
            cpu_ctx = AnalysisRunner.do_analysis_run(ds2, analyzers)
        for a in analyzers:
            assert default_ctx.metric(a).value.get() == pytest.approx(
                cpu_ctx.metric(a).value.get(), rel=1e-12
            ), a


class TestEdgeDtypes:
    """Narrow/unsigned/half dtypes flow through the whole engine with
    numpy-oracle-exact basic stats (the wire-narrowing and widening
    rules must never change a metric)."""

    def test_all_numeric_storage_dtypes(self):
        import pyarrow as pa

        from deequ_tpu import Dataset
        from deequ_tpu.analyzers import (
            AnalysisRunner,
            CountDistinct,
            Maximum,
            Mean,
            Minimum,
            Sum,
        )

        rng = np.random.default_rng(8)
        cols = {
            "i8": rng.integers(-100, 100, 4_000).astype(np.int8),
            "u16": rng.integers(0, 60_000, 4_000).astype(np.uint16),
            "u32": rng.integers(1 << 31, 1 << 32, 4_000).astype(np.uint32),
            "i64": rng.integers(-(1 << 60), 1 << 60, 4_000),
            "f16": rng.normal(0, 1, 4_000).astype(np.float16),
            "f32": rng.normal(0, 1, 4_000).astype(np.float32),
        }
        ds = Dataset.from_arrow(
            pa.table({k: pa.array(v) for k, v in cols.items()})
        )
        analyzers = []
        for c in cols:
            analyzers += [Mean(c), Minimum(c), Maximum(c), Sum(c)]
        analyzers += [CountDistinct("u32"), CountDistinct("f32")]
        ctx = AnalysisRunner.do_analysis_run(ds, analyzers)
        for c, vals in cols.items():
            # f16 materializes as f32 on the wire; the oracle follows
            wide = vals.astype(np.float64)
            assert ctx.metric(Mean(c)).value.get() == pytest.approx(
                float(wide.mean()), rel=1e-6
            ), c
            assert ctx.metric(Minimum(c)).value.get() == pytest.approx(
                float(wide.min())
            ), c
            assert ctx.metric(Maximum(c)).value.get() == pytest.approx(
                float(wide.max())
            ), c
            assert ctx.metric(Sum(c)).value.get() == pytest.approx(
                float(wide.sum()), rel=1e-6
            ), c
        assert ctx.metric(CountDistinct("u32")).value.get() == float(
            len(np.unique(cols["u32"]))
        )
        assert ctx.metric(CountDistinct("f32")).value.get() == float(
            len(np.unique(cols["f32"]))
        )
