"""Lint: hot-path modules must not roll their own timing/tracing —
or their own out-of-memory classification, or their own device syncs.

All wall-clock attribution lives in ``deequ_tpu/telemetry/`` (spans,
PhaseClock, pass timing) so trace names stay consistent with XProf and
timings stay comparable across PRs. This tool tokenizes every module
under the hot-path packages and flags ``time.perf_counter``,
``jax.profiler.start_trace``/``stop_trace``, and ``TraceAnnotation``
references outside the telemetry layer.

Likewise, all memory-pressure classification lives in
``deequ_tpu/engine/memory.py`` (classify_memory_pressure): an ad-hoc
``except MemoryError`` or a bare OOM marker string
(``RESOURCE_EXHAUSTED`` / "out of memory") anywhere else in the hot
path would fork the taxonomy — flagged the same way.

Sync discipline (the r6 rule): inside ``deequ_tpu/engine/`` the ONE
sanctioned host<->device fetch is the packed epilogue
(``engine/pack.py`` ``packed_device_get``) — a stray ``device_get`` or
``asarray`` in a scan hot loop is a per-batch tunnel round trip, the
exact regression class the 2-syncs-per-profile pin exists to prevent
(tests/test_sync_discipline.py). ``device_get``/``asarray`` NAME
tokens in engine modules outside pack.py are flagged unless the line
carries an inline ``# sync-ok: <reason>`` waiver documenting why the
call is host-side or a deliberate, clock-attributed sync (checkpoint
drain, mesh epilogue).

Service discipline (PR 7): modules under ``deequ_tpu/service/`` may
not read or burn wall time themselves (``time.time``/``time.sleep``/
``monotonic``/``perf_counter``) — every scheduling decision rides the
injectable clocks from ``engine/deadline.py`` so the whole scheduler
is assertable on fake time — and may not bypass the runner's admission
layer by referencing the engine scan entry points (``run_scan``,
``prepare_scan``, ``execute_plan``). Run from the test suite
(tests/test_telemetry.py) and by hand:

    python -m tools.telemetry_lint [repo_root]
"""

from __future__ import annotations

import io
import os
import sys
import tokenize
from typing import List, Optional, Tuple

# packages whose modules the fused-scan / verification flow executes;
# utils is included (observe.py is a pure adapter now)
HOT_PATH_DIRS = (
    "deequ_tpu/engine",
    "deequ_tpu/data",
    "deequ_tpu/analyzers",
    "deequ_tpu/profiles",
    "deequ_tpu/verification",
    "deequ_tpu/sketches",
    "deequ_tpu/checks",
    "deequ_tpu/io",
    "deequ_tpu/utils",
    "deequ_tpu/service",
)

# NAME tokens that mean "module does its own timing/tracing"
FORBIDDEN_NAMES = frozenset(
    {"perf_counter", "start_trace", "stop_trace", "TraceAnnotation"}
)

# the one place allowed to touch clocks and the profiler
EXEMPT_PREFIX = "deequ_tpu/telemetry/"

# NAME tokens that mean "module rolls its own OOM taxonomy" (the
# MemoryPressureError family + classify_memory_pressure are fine —
# different token)
FORBIDDEN_OOM_NAMES = frozenset({"MemoryError"})

# STRING-literal markers that mean "module string-matches allocator
# failures itself" (lowercased containment check)
FORBIDDEN_OOM_MARKERS = ("resource_exhausted", "out of memory")

# the one classification point (engine/memory.py docstring)
OOM_EXEMPT_FILES = frozenset({"deequ_tpu/engine/memory.py"})

# NAME tokens that mean "module syncs with the device on its own"
# inside the engine layer; every legitimate use is either in pack.py
# (the packed epilogue) or carries a same-line `# sync-ok:` waiver
FORBIDDEN_SYNC_NAMES = frozenset({"device_get", "asarray"})
SYNC_HOT_PREFIX = "deequ_tpu/engine/"
SYNC_EXEMPT_FILES = frozenset({"deequ_tpu/engine/pack.py"})
SYNC_WAIVER_MARKER = "sync-ok:"

# the service layer (deequ_tpu/service/, docs/SERVICE.md) runs on
# INJECTED clocks only — the engine/deadline.py discipline that makes
# every scheduling behavior assertable on fake time — and must enter
# execution through the runner's admission layer, never the engine
# directly. Two rule families:
# - direct time: bare ``sleep``/``monotonic``/``perf_counter`` NAME
#   tokens, plus the ``time.<attr>`` attribute chain (``time.time`` is
#   caught by sequence, not by banning the ubiquitous NAME "time")
# - admission bypass: any reference to the engine's scan entry points
SERVICE_PREFIX = "deequ_tpu/service/"
SERVICE_FORBIDDEN_NAMES = frozenset(
    {
        "sleep",
        "monotonic",
        "run_scan",
        "prepare_scan",
        "execute_plan",
        "_run_scan_resident",
        "_run_scan_streaming",
    }
)
SERVICE_TIME_ATTRS = frozenset(
    {"time", "sleep", "monotonic", "perf_counter"}
)


def find_violations(root: str) -> List[Tuple[str, int, str]]:
    """(relpath, line, token) for every forbidden NAME token in a
    hot-path module — own-timing names everywhere outside the telemetry
    layer, ad-hoc OOM classification (``MemoryError`` NAME tokens, OOM
    marker STRING literals) outside engine/memory.py, and engine-layer
    device syncs (``device_get``/``asarray``) outside pack.py without a
    same-line ``# sync-ok:`` waiver. Tokenize-based: a mention in a
    comment or docstring does not flag; an aliased import (``from time
    import perf_counter``) does."""
    violations: List[Tuple[str, int, str]] = []
    for rel_dir in HOT_PATH_DIRS:
        top = os.path.join(root, rel_dir)
        if not os.path.isdir(top):
            continue
        for dirpath, _dirnames, filenames in os.walk(top):
            for filename in sorted(filenames):
                if not filename.endswith(".py"):
                    continue
                path = os.path.join(dirpath, filename)
                rel = os.path.relpath(path, root).replace(os.sep, "/")
                if rel.startswith(EXEMPT_PREFIX):
                    continue
                oom_exempt = rel in OOM_EXEMPT_FILES
                sync_checked = rel.startswith(
                    SYNC_HOT_PREFIX
                ) and rel not in SYNC_EXEMPT_FILES
                service_checked = rel.startswith(SERVICE_PREFIX)
                with open(path, "rb") as fh:
                    source = fh.read()
                try:
                    tokens = list(
                        tokenize.tokenize(io.BytesIO(source).readline)
                    )
                except tokenize.TokenizeError:
                    violations.append((rel, 0, "<tokenize error>"))
                    continue
                # lines waived for the sync rule by an inline comment
                waived = {
                    tok.start[0]
                    for tok in tokens
                    if tok.type == tokenize.COMMENT
                    and SYNC_WAIVER_MARKER in tok.string
                }
                for tok in tokens:
                    if tok.type == tokenize.NAME and (
                        tok.string in FORBIDDEN_NAMES
                        or (
                            not oom_exempt
                            and tok.string in FORBIDDEN_OOM_NAMES
                        )
                    ):
                        violations.append(
                            (rel, tok.start[0], tok.string)
                        )
                    elif (
                        tok.type == tokenize.NAME
                        and sync_checked
                        and tok.string in FORBIDDEN_SYNC_NAMES
                        and tok.start[0] not in waived
                    ):
                        violations.append(
                            (rel, tok.start[0], tok.string)
                        )
                    elif (
                        tok.type == tokenize.STRING
                        and not oom_exempt
                        and any(
                            marker in tok.string.lower()
                            for marker in FORBIDDEN_OOM_MARKERS
                        )
                    ):
                        violations.append(
                            (rel, tok.start[0], "<oom marker string>")
                        )
                if service_checked:
                    violations.extend(
                        (rel, line, name)
                        for line, name in _service_violations(tokens)
                    )
    return violations


def _service_violations(tokens) -> List[Tuple[int, str]]:
    """Service-layer rules on one module's token stream: banned NAME
    tokens (own sleeps/clocks, engine scan entry points) plus the
    ``time.<attr>`` attribute-chain check for ``time.time`` (sequence
    over significant tokens, so comments/docstrings never flag)."""
    out: List[Tuple[int, str]] = []
    significant = [
        tok
        for tok in tokens
        if tok.type
        in (tokenize.NAME, tokenize.OP, tokenize.NUMBER, tokenize.STRING)
    ]
    for i, tok in enumerate(significant):
        if tok.type != tokenize.NAME:
            continue
        if tok.string in SERVICE_FORBIDDEN_NAMES:
            out.append((tok.start[0], tok.string))
        elif (
            tok.string == "time"
            and i + 2 < len(significant)
            and significant[i + 1].string == "."
            and significant[i + 2].type == tokenize.NAME
            and significant[i + 2].string in SERVICE_TIME_ATTRS
        ):
            out.append(
                (tok.start[0], f"time.{significant[i + 2].string}")
            )
    return out


def main(argv: Optional[List[str]] = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    root = argv[0] if argv else os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))
    )
    violations = find_violations(root)
    for rel, line, token in violations:
        print(f"{rel}:{line}: forbidden hot-path token {token}")
    if violations:
        print(
            f"{len(violations)} violation(s): timing/tracing belongs in "
            "the telemetry layer (docs/OBSERVABILITY.md); engine syncs "
            "belong in the packed epilogue (engine/pack.py) or need a "
            "'# sync-ok:' waiver"
        )
        return 1
    print("telemetry lint clean")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
