"""State persistence: the checkpoint/resume + incremental subsystem.

Reference: ``src/main/scala/com/amazon/deequ/analyzers/StateProvider.scala``
(SURVEY.md §2.2, §5.4): ``StateLoader``/``StatePersister`` with an
in-memory provider (concurrent map) and a filesystem provider doing
binary serde of every state type. Because every state is a mergeable
monoid, persisted states give (a) incremental append-only datasets,
(b) partition-parallel computation merged later, (c) resume-from-state.

deequ_tpu states are pytrees of numpy arrays (NamedTuples) or the
host-side ``FrequenciesAndNumRows``; the filesystem format is one ``.npz``
per (analyzer, state) plus a JSON index keyed by the analyzer's stable
repr — its own format, not bit-compatible with the reference's
(SURVEY.md §7 hard part #5 recommends exactly this).
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
from typing import Any, Dict, Optional

import numpy as np

from deequ_tpu.analyzers.base import Analyzer
from deequ_tpu.analyzers.grouping import FrequenciesAndNumRows
from deequ_tpu.analyzers.states import STATE_FORMAT_VERSIONS, STATE_TYPES
from deequ_tpu.sketches.kll import KLLSketchState


class StateLoader:
    def load(self, analyzer: Analyzer) -> Optional[Any]:
        raise NotImplementedError


class StatePersister:
    def persist(self, analyzer: Analyzer, state: Any) -> None:
        raise NotImplementedError


class InMemoryStateProvider(StateLoader, StatePersister):
    """Thread-safe in-process store (reference: InMemoryStateProvider)."""

    def __init__(self) -> None:
        self._states: Dict[str, Any] = {}
        self._lock = threading.Lock()

    def load(self, analyzer: Analyzer) -> Optional[Any]:
        with self._lock:
            return self._states.get(repr(analyzer))

    def persist(self, analyzer: Analyzer, state: Any) -> None:
        with self._lock:
            self._states[repr(analyzer)] = state

    def __repr__(self) -> str:
        return f"InMemoryStateProvider({len(self._states)} states)"


def _to_host(value):
    return np.asarray(value)


class FileSystemStateProvider(StateLoader, StatePersister):
    """Binary state serde to a directory OR storage URI (reference:
    HdfsStateProvider — local/HDFS/S3 via the Hadoop FS registry; here
    plain paths use the local filesystem and ``scheme://`` URIs route
    through deequ_tpu.io.storage's backend registry — ``mem://`` ships
    in-tree, cloud backends register in a few lines)."""

    def __init__(self, path: str, allow_overwrite: bool = True):
        from deequ_tpu.io.storage import storage_for

        self._path = path
        self._allow_overwrite = allow_overwrite
        self._storage = storage_for(path)

    def _key(self, analyzer: Analyzer) -> str:
        digest = hashlib.sha1(repr(analyzer).encode()).hexdigest()[:16]
        return f"state-{digest}.npz"

    def _update_index(self, analyzer: Analyzer, key: str) -> None:
        index: Dict[str, str] = {}
        raw = self._storage.read_bytes("index.json")
        if raw is not None:
            index = json.loads(raw.decode())
        index[repr(analyzer)] = key
        self._storage.write_bytes(
            "index.json", json.dumps(index, indent=2).encode()
        )

    def persist(self, analyzer: Analyzer, state: Any) -> None:
        import io as _io

        key = self._key(analyzer)
        if not self._allow_overwrite and self._storage.exists(key):
            raise FileExistsError(f"{self._path}/{key}")
        buf = _io.BytesIO()
        if isinstance(state, FrequenciesAndNumRows):
            np.savez(
                buf,
                __type__=np.asarray("FrequenciesAndNumRows"),
                columns=np.asarray(json.dumps(list(state.columns))),
                keys=np.asarray(
                    json.dumps([[_json_safe(v) for v in row] for row in state.keys])
                ),
                counts=state.counts,
                num_rows=np.int64(state.num_rows),
            )
        elif isinstance(state, KLLSketchState):
            np.savez(
                buf,
                __type__=np.asarray("KLLSketchState"),
                **state.to_arrays(),
            )
        elif hasattr(state, "_fields"):  # NamedTuple state
            name = type(state).__name__
            payload = {
                field: _to_host(getattr(state, field))
                for field in state._fields
            }
            np.savez(
                buf,
                __type__=np.asarray(name),
                __version__=np.int64(STATE_FORMAT_VERSIONS.get(name, 1)),
                **payload,
            )
        else:
            raise TypeError(
                f"cannot persist state of type {type(state).__name__}"
            )
        self._storage.write_bytes(key, buf.getvalue())
        self._update_index(analyzer, key)

    def load(self, analyzer: Analyzer) -> Optional[Any]:
        import io as _io

        raw = self._storage.read_bytes(self._key(analyzer))
        if raw is None:
            return None
        with np.load(_io.BytesIO(raw), allow_pickle=False) as data:
            type_name = str(data["__type__"])
            if type_name == "FrequenciesAndNumRows":
                columns = tuple(json.loads(str(data["columns"])))
                key_rows = json.loads(str(data["keys"]))
                keys = np.empty((len(key_rows), len(columns)), dtype=object)
                for i, row in enumerate(key_rows):
                    keys[i, :] = row
                return FrequenciesAndNumRows(
                    columns, keys, data["counts"], int(data["num_rows"])
                )
            if type_name == "KLLSketchState":
                return KLLSketchState.from_arrays(data)
            cls = STATE_TYPES.get(type_name)
            if cls is None:
                raise TypeError(f"unknown persisted state type {type_name}")
            expected = STATE_FORMAT_VERSIONS.get(type_name, 1)
            found = int(data["__version__"]) if "__version__" in data else 1
            if found != expected:
                raise TypeError(
                    f"persisted {type_name} has format v{found}, this "
                    f"build reads v{expected} — recompute the state "
                    "(merging across versions would be silently wrong)"
                )
            return cls(
                **{f: data[f] for f in cls._fields}
            )


def _json_safe(value):
    if value is None or isinstance(value, (str, bool)):
        return value
    if isinstance(value, (np.integer, int)):
        return int(value)
    if isinstance(value, (np.floating, float)):
        return float(value)
    return str(value)
