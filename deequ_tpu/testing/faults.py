"""Deterministic fault injection for scan resilience tests.

:class:`FaultInjectingDataset` wraps any Dataset and injects faults at
exact batch indices — every fault is a pure function of the configured
indices and the wrapper's mutable fault ledger (no RNG, no wall clock),
so a failing test replays byte-for-byte:

- ``transient={index: n}`` — the batch raises
  :class:`~deequ_tpu.engine.resilience.TransientScanError` for its
  first ``n`` reads, then succeeds (raise-then-succeed: the retry path);
- ``permanent={index, ...}`` — the batch always raises ``ValueError``
  (a decode error: deterministic, must quarantine without retries);
- ``corrupt={index, ...}`` — the batch's arrays arrive truncated (the
  integrity-check path: quarantined, never shipped to the device);
- ``kill_at_batch=N`` — producing batch N raises
  :class:`~deequ_tpu.engine.resilience.ScanKilled` (a BaseException:
  the scan unwinds like real process death, and with ``kill_once`` the
  next run survives — the checkpoint/resume differential tests);
- ``hang_at_batch={index: n}`` — producing the batch BLOCKS until the
  scan supervisor's watchdog interrupts it (the hung-source path: the
  wrapper spins on the interrupt event the engine attaches via
  ``attach_interrupt``, advancing the injectable ``clock`` by
  ``hang_tick_s`` per spin so fake-clock stall detection fires without
  any real sleeping), then raises
  :class:`~deequ_tpu.engine.resilience.ScanStalled`; the batch re-hangs
  ``n`` times (one per retry attempt) before serving normally;
- ``slow_batch={index: delay_s}`` — producing the batch advances the
  injectable ``clock`` by ``delay_s`` once (the slow-but-arriving path:
  stall/deadline detection on a batch that DOES show up);
- ``on_batch={index: callable}`` — the callable runs every time the
  batch is produced, before fault checks (the deterministic trigger for
  cancel-mid-scan tests: cancel a token at exactly batch k);
- ``crash_at_batch=N`` — producing batch N HARD-CRASHES the process
  (``signal.raise_signal(SIGSEGV)``, with ``os._exit(139)`` as the
  fallback): no exception, no unwinding, no atexit — the deterministic
  stand-in for a real XLA segfault, driving ``engine/subproc.py``'s
  relaunch path without flaky real crashes. ``crash_token_path`` gives
  cross-process crash-once semantics: the in-memory ledger dies WITH
  the process, so the wrapper drops a marker file before crashing and a
  relaunched child that finds the marker serves the batch normally.
  Without a token path the batch is POISON — it crashes every launch,
  which is exactly what the crash-loop breaker tests need;
- ``crash_every_n=k`` — every k-th batch hard-crashes (token-gated per
  index when ``crash_token_path`` is set, so each crash fires once
  across the run's relaunch chain).

Memory-pressure faults (engine/memory.py) fire through the engine's
``oom_probe`` protocol — the engine calls ``probe(stage, index, rows)``
INSIDE its guarded transfer/dispatch/finalize stages, so an injected
OOM rides the exact classification path a live device allocation
failure would, with zero real memory pressure:

- ``oom_at_batch={index: n}`` (or a bare iterable, n=1) — the unit's
  dispatch raises a simulated ``RESOURCE_EXHAUSTED`` for its first
  ``n`` attempts at that index, then succeeds (raise-then-succeed:
  backoff shrinks, the retried sub-batches pass);
- ``oom_every_n=k`` — every k-th unit's dispatch OOMs once;
- ``oom_rows_over=limit`` — ANY dispatch/transfer wider than ``limit``
  rows OOMs: the natural geometric-backoff fault (the scan settles at
  the first effective size <= limit; exact analog of a device that
  fits only so many rows);
- ``oom_transfer_at={index: n}`` — like ``oom_at_batch`` but fired at
  the transfer (device_put) stage;
- ``oom_finalize=n`` / ``oom_deferred=n`` — the first ``n``
  collector-finalize / deferred-path probes OOM (the spill downgrade
  chain in analyzers/grouping.py).

The fault ledger (remaining transient raises, remaining hangs, one-shot
slow delays, remaining OOMs, the kill flag) is SHARED across iterator
restarts and re-runs of the same wrapper instance, mirroring a real
flaky source that eventually serves the batch.
"""

from __future__ import annotations

import os
import signal
from typing import Any, Callable, Dict, Iterable, Iterator, Optional, Set

import numpy as np

from deequ_tpu.engine.memory import simulated_device_oom
from deequ_tpu.engine.resilience import (
    ScanKilled,
    ScanStalled,
    TransientScanError,
)


def hard_crash(signum: Optional[int] = None) -> None:
    """Kill THIS process the way a real fault would: raise the signal
    (default SIGSEGV — the parent sees exitcode ``-signum``), falling
    back to ``os._exit(128 + signum)`` (the shell convention, e.g. 139)
    when the signal somehow returns. Never raises, never unwinds, never
    runs atexit — by design."""
    num = int(signum) if signum is not None else int(signal.SIGSEGV)
    try:
        signal.raise_signal(num)
    except Exception:  # noqa: BLE001 — no signal support: exit hard
        pass
    os._exit(128 + num)


class FaultInjectingDataset:
    """Wrap a Dataset, injecting faults at configured batch indices.

    Everything not overridden here (``num_rows``, ``schema``,
    ``fingerprint``, cache internals, ...) delegates to the inner
    dataset, so the wrapper is drop-in for the engine's resident,
    streaming and mesh paths. Fault indices are BATCH indices for
    ``device_batches`` and CHUNK indices for ``device_scan_chunks``
    (identical while the engine stacks one batch per chunk).
    """

    def __init__(
        self,
        inner: Any,
        transient: Optional[Dict[int, int]] = None,
        permanent: Optional[Iterable[int]] = None,
        corrupt: Optional[Iterable[int]] = None,
        kill_at_batch: Optional[int] = None,
        kill_once: bool = True,
        crash_at_batch: Optional[int] = None,
        crash_every_n: int = 0,
        crash_token_path: Optional[str] = None,
        crash_signum: Optional[int] = None,
        hang_at_batch: Optional[Any] = None,
        slow_batch: Optional[Dict[int, float]] = None,
        on_batch: Optional[Dict[int, Callable[[], None]]] = None,
        clock: Optional[Any] = None,
        hang_tick_s: float = 0.25,
        oom_at_batch: Optional[Any] = None,
        oom_every_n: int = 0,
        oom_rows_over: int = 0,
        oom_transfer_at: Optional[Any] = None,
        oom_finalize: int = 0,
        oom_deferred: int = 0,
        decode_transient: Optional[Dict[int, int]] = None,
        decode_permanent: Optional[Iterable[int]] = None,
    ):
        self._inner = inner
        self._transient_remaining = dict(transient or {})
        self._permanent: Set[int] = set(permanent or ())
        self._corrupt: Set[int] = set(corrupt or ())
        self._kill_at_batch = kill_at_batch
        self._kill_once = kill_once
        self._killed = False
        # hard-crash faults (process death, not exceptions)
        self._crash_at_batch = crash_at_batch
        self._crash_every_n = int(crash_every_n)
        self._crash_token_path = crash_token_path
        self._crash_signum = crash_signum
        # hang_at_batch accepts {index: n_hangs} or a bare iterable of
        # indices (one hang each)
        if hang_at_batch is None:
            self._hangs_remaining: Dict[int, int] = {}
        elif isinstance(hang_at_batch, dict):
            self._hangs_remaining = dict(hang_at_batch)
        else:
            self._hangs_remaining = {i: 1 for i in hang_at_batch}
        self._slow_remaining = dict(slow_batch or {})
        self._on_batch = dict(on_batch or {})
        self._clock = clock
        self._hang_tick_s = float(hang_tick_s)
        self._interrupt_event: Optional[Any] = None
        # memory-pressure ledgers ({index: n} or bare iterable, n=1)
        self._oom_remaining = self._oom_spec(oom_at_batch)
        self._oom_transfer_remaining = self._oom_spec(oom_transfer_at)
        self._oom_every_n = int(oom_every_n)
        self._oom_every_fired: Set[int] = set()
        self._oom_rows_over = int(oom_rows_over)
        self._oom_finalize_remaining = int(oom_finalize)
        self._oom_deferred_remaining = int(oom_deferred)
        # r10 worker-stage faults: fired inside ``item.decode()`` on a
        # pool WORKER thread (simulated worker death), surfacing
        # through the ordered reassembly stage at the batch's exact
        # sequence position
        self._decode_transient_remaining = dict(decode_transient or {})
        self._decode_permanent: Set[int] = set(decode_permanent or ())
        # observability for assertions: every fault actually fired
        self.faults_fired: list = []

    @staticmethod
    def _oom_spec(spec: Optional[Any]) -> Dict[int, int]:
        if spec is None:
            return {}
        if isinstance(spec, dict):
            return {int(k): int(v) for k, v in spec.items()}
        return {int(i): 1 for i in spec}

    def oom_probe(self, stage: str, index: int = 0, rows: int = 0) -> None:
        """Engine protocol hook (engine/memory.py ``oom_probe_of``):
        called inside the guarded transfer/dispatch/finalize stages
        with the unit index and the dispatch width in rows; raises a
        simulated XLA ``RESOURCE_EXHAUSTED`` when a configured
        memory-pressure fault is due at that point."""

        def fire():
            self.faults_fired.append(("oom", stage, index, int(rows)))
            raise simulated_device_oom(rows, f"{stage}@{index}")

        if stage == "finalize":
            if self._oom_finalize_remaining > 0:
                self._oom_finalize_remaining -= 1
                fire()
            return
        if stage == "deferred":
            if self._oom_deferred_remaining > 0:
                self._oom_deferred_remaining -= 1
                fire()
            return
        # a device that fits only `limit` rows: any wider allocation
        # fails, at full size AND at still-too-wide backed-off sizes —
        # the scan settles at the first effective size <= limit
        if self._oom_rows_over and rows > self._oom_rows_over:
            fire()
        ledger = (
            self._oom_transfer_remaining
            if stage == "transfer"
            else self._oom_remaining
        )
        remaining = ledger.get(index, 0)
        if remaining > 0:
            ledger[index] = remaining - 1
            fire()
        if (
            self._oom_every_n > 0
            and stage == "dispatch"
            and (index + 1) % self._oom_every_n == 0
            and index not in self._oom_every_fired
        ):
            self._oom_every_fired.add(index)
            fire()

    def attach_interrupt(self, event: Any) -> None:
        """Engine protocol hook: the scan supervisor hands the source an
        Event it will set when the watchdog wants the source unblocked
        (a fresh one per iterator (re)start)."""
        self._interrupt_event = event

    def __getattr__(self, name: str) -> Any:
        return getattr(self._inner, name)

    # -- fault core ----------------------------------------------------

    def _fire_hook(self, index: int) -> None:
        hook = self._on_batch.get(index)
        if hook is not None:
            self.faults_fired.append(("hook", index))
            hook()

    def _maybe_slow(self, index: int) -> None:
        delay = self._slow_remaining.pop(index, None)
        if delay is None:
            return
        self.faults_fired.append(("slow", index))
        if self._clock is not None:
            self._clock.advance(delay)

    def _maybe_hang(self, index: int) -> None:
        remaining = self._hangs_remaining.get(index, 0)
        if remaining <= 0:
            return
        self._hangs_remaining[index] = remaining - 1
        self.faults_fired.append(("hang", index))
        ev = self._interrupt_event
        if ev is None:
            # no supervisor armed this source: a real hang would block
            # forever — self-report the stall instead of deadlocking
            # the test process
            if self._clock is not None:
                self._clock.advance(self._hang_tick_s)
            raise ScanStalled(
                f"injected hang at batch {index} (no supervisor attached)"
            )
        ticks = 0
        while not ev.is_set():
            # the hang is where fake time passes: tick the injectable
            # clock so the watchdog's stall rule (now - last_progress >
            # stall_s) trips without any real sleeping, then yield the
            # GIL briefly so the watchdog thread actually runs
            if self._clock is not None:
                self._clock.advance(self._hang_tick_s)
            ev.wait(0.001)
            ticks += 1
            if ticks > 20_000:  # ~20s real: supervision is broken
                raise RuntimeError(
                    f"injected hang at batch {index} was never "
                    "interrupted by the watchdog"
                )
        raise ScanStalled(f"injected hang at batch {index} interrupted")

    def _crash_due(self, index: int) -> bool:
        if self._crash_at_batch is not None and index == self._crash_at_batch:
            return True
        return (
            self._crash_every_n > 0
            and index > 0
            and index % self._crash_every_n == 0
        )

    def _maybe_crash(self, index: int) -> None:
        """Hard process death at ``index`` — fires BEFORE the softer
        faults (a segfault does not politely defer to a retry)."""
        if not self._crash_due(index):
            return
        if self._crash_token_path is not None:
            token = f"{self._crash_token_path}.crashed-b{index}"
            if os.path.exists(token):
                return  # this launch already paid the crash here
            with open(token, "x", encoding="utf-8") as fh:
                fh.write(f"batch {index}\n")
                fh.flush()
                os.fsync(fh.fileno())
        hard_crash(self._crash_signum)

    def _check_faults(self, index: int) -> None:
        """Raise the configured fault for ``index``, if any — BEFORE the
        item is yielded, so the engine's failing-index arithmetic
        (start + items_yielded) lands exactly on ``index``."""
        self._maybe_crash(index)
        if (
            self._kill_at_batch is not None
            and index == self._kill_at_batch
            and not (self._kill_once and self._killed)
        ):
            self._killed = True
            self.faults_fired.append(("kill", index))
            raise ScanKilled(f"injected kill at batch {index}")
        if index in self._permanent:
            self.faults_fired.append(("permanent", index))
            raise ValueError(f"injected decode error at batch {index}")
        remaining = self._transient_remaining.get(index, 0)
        if remaining > 0:
            self._transient_remaining[index] = remaining - 1
            self.faults_fired.append(("transient", index))
            raise TransientScanError(
                f"injected transient error at batch {index} "
                f"({remaining - 1} more)"
            )

    def _maybe_corrupt(self, index: int, batch: Dict[str, Any]):
        if index not in self._corrupt:
            return batch
        self.faults_fired.append(("corrupt", index))
        out = {}
        for k, v in batch.items():
            arr = np.asarray(v)
            out[k] = (
                arr[: max(arr.shape[0] // 2, 1)] if arr.ndim else arr
            )
        return out

    # -- Dataset surface -----------------------------------------------

    def device_batches(
        self, requests, batch_size: int, start_batch: int = 0
    ) -> Iterator[Dict[str, Any]]:
        index = start_batch
        for batch in self._inner.device_batches(
            requests, batch_size, start_batch=start_batch
        ):
            self._fire_hook(index)
            self._maybe_slow(index)
            self._maybe_hang(index)
            self._check_faults(index)
            yield self._maybe_corrupt(index, batch)
            index += 1

    # r10: the ordered ingest pool engages only on datasets whose CLASS
    # declares support (a bare __getattr__ delegation would let the
    # engine reach the INNER planner and silently bypass every fault
    # here) — so the wrapper declares support exactly when its inner
    # dataset does, and wraps each work item in its own fault surface.
    @property
    def supports_parallel_ingest(self) -> bool:
        return bool(
            getattr(type(self._inner), "supports_parallel_ingest", False)
        )

    def _check_decode_faults(self, index: int) -> None:
        """Worker-death simulation: raised inside ``decode()`` on the
        pool worker that picked this batch up."""
        if index in self._decode_permanent:
            self.faults_fired.append(("decode_permanent", index))
            raise ValueError(
                f"injected worker decode error at batch {index}"
            )
        remaining = self._decode_transient_remaining.get(index, 0)
        if remaining > 0:
            self._decode_transient_remaining[index] = remaining - 1
            self.faults_fired.append(("decode_transient", index))
            raise TransientScanError(
                f"injected worker death at batch {index} "
                f"({remaining - 1} more)"
            )

    def ingest_work_items(
        self, requests, batch_size: int, start_batch: int = 0
    ):
        """Pool-path twin of ``device_batches``: reader-side faults
        (hook/slow/hang/kill/transient/permanent) fire BEFORE the item
        is yielded — same failing-index arithmetic — while corruption
        and the decode_* faults ride the item into the worker stage."""
        index = start_batch
        for item in self._inner.ingest_work_items(
            requests, batch_size, start_batch=start_batch
        ):
            self._fire_hook(index)
            self._maybe_slow(index)
            self._maybe_hang(index)
            self._check_faults(index)
            yield _FaultyIngestItem(self, item)
            index += 1

    def device_scan_chunks(
        self, requests, batch_size: int, start_chunk: int = 0, **kwargs
    ):
        # chunk items are device-resident stacks; corruption is a host
        # concept, so only transient/permanent/kill apply here
        index = start_chunk
        for chunk in self._inner.device_scan_chunks(
            requests, batch_size, start_chunk=start_chunk, **kwargs
        ):
            self._fire_hook(index)
            self._maybe_slow(index)
            self._maybe_hang(index)
            self._check_faults(index)
            yield chunk
            index += 1


class _FaultyIngestItem:
    """One wrapped work item: decode-stage faults (worker death,
    corruption) fire on whichever pool worker runs ``decode()``; the
    ordered ``commit`` passes through untouched."""

    __slots__ = ("_owner", "_item")

    def __init__(self, owner: FaultInjectingDataset, item: Any):
        self._owner = owner
        self._item = item

    @property
    def index(self) -> int:
        return self._item.index

    @property
    def complete(self) -> bool:
        return self._item.complete

    @property
    def final(self) -> bool:
        return self._item.final

    def decode(self):
        owner = self._owner
        index = self._item.index
        owner._check_decode_faults(index)
        batch = self._item.decode()
        return owner._maybe_corrupt(index, batch)

    def commit(self, decoded):
        return self._item.commit(decoded)
