from deequ_tpu.utils.trylike import Failure, Success, Try

__all__ = ["Failure", "Success", "Try"]
