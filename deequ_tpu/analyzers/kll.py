"""KLL-backed quantile analyzers: KLLSketch, ApproxQuantile,
ApproxQuantiles.

Reference: ``analyzers/KLLSketch.scala`` / ``ApproxQuantile.scala`` /
``ApproxQuantiles.scala`` (SURVEY.md §2.2; the reference's
StatefulApproxQuantile is superseded by KLL per §2.3). The device side
of the update rides the shared fused scan: sort the batch, emit k
strided samples at a static compaction level (fixed shapes — SURVEY.md
§7 hard part #2); the host folds them into the compactor hierarchy
(deequ_tpu.sketches.kll), which is also the incremental/mesh merge.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deequ_tpu.analyzers.base import (
    EmptyStateException,
    Precondition,
    ScanOps,
    ScanShareableAnalyzer,
    has_column,
    is_numeric,
)
from deequ_tpu.analyzers.basic import _compile_where, _row_mask
from deequ_tpu.data.table import ColumnRequest, Dataset
from deequ_tpu.metrics.kll import BucketDistribution, BucketValue, KLLMetric
from deequ_tpu.metrics.metric import DoubleMetric, Entity, KeyedDoubleMetric, Metric
from deequ_tpu.sketches.hll import fmix32
from deequ_tpu.sketches.kll import KLLParameters, KLLSketchState
from deequ_tpu.utils.trylike import Success

_F64 = jnp.float64


def _make_kll_ops(
    analyzer: "KLLSketch | ApproxQuantile | ApproxQuantiles",
    dataset: Dataset,
    params: KLLParameters,
) -> ScanOps:
    where_fn, _ = _compile_where(analyzer.where, dataset)
    col = analyzer.column
    k = params.sketch_size

    def init():
        # per-batch output slot (overwritten each batch, not a carry)
        return (
            np.zeros(k, dtype=np.float32),  # samples
            np.zeros(k, dtype=bool),  # sample validity
            np.int64(0),  # valid count
            np.float32(np.inf),  # min
            np.float32(-np.inf),  # max
            np.int32(0),  # compaction level
        )

    def update(_state, batch):
        # device kernel stays in f32/u32 lanes: TPU-native (no x64
        # emulation in the sort); the host compactor hierarchy is f64
        mask = batch[f"{col}::mask"] & _row_mask(batch, where_fn)
        x = batch[f"{col}::values"].astype(jnp.float32)
        # non-finite values cannot enter the compactors (they'd corrupt
        # sort/searchsorted); they are excluded like the reference's
        # null-skipping aggregates skip nulls
        mask = mask & jnp.isfinite(x)
        B = x.shape[0]
        sorted_x = jnp.sort(jnp.where(mask, x, jnp.inf))
        nv = jnp.sum(mask, dtype=jnp.int64)
        # compaction level from the SURVIVING row count (a where-filter
        # or padding can make nv << B): level = ceil_log2(ceil(nv / k)),
        # computed with integer bit tricks so exact powers stay exact
        q = ((nv + k - 1) // k).astype(jnp.uint32)
        level = jnp.where(
            q > 1, 32 - jax.lax.clz(jnp.maximum(q - 1, 1)), 0
        ).astype(jnp.int32)
        stride = (jnp.int64(1) << level.astype(jnp.int64))
        # data-derived random offset in [0, stride): stride is a power of
        # two, so masking the avalanche hash of the valid count + first
        # value's bits is uniform enough for the compactor offset
        bits = jax.lax.bitcast_convert_type(sorted_x[0], jnp.uint32)
        seed = fmix32(nv.astype(jnp.uint32) ^ bits)
        offset = (seed.astype(jnp.int64)) & (stride - 1)
        idx = offset + jnp.arange(k, dtype=jnp.int64) * stride
        valid = idx < nv
        samples = sorted_x[jnp.clip(idx, 0, B - 1)]
        mn = jnp.min(jnp.where(mask, x, jnp.inf))
        mx = jnp.max(jnp.where(mask, x, -jnp.inf))
        return (
            samples,
            valid,
            nv,
            mn,
            mx,
            level,
        )

    def host_init() -> KLLSketchState:
        return KLLSketchState(params)

    def host_fold(acc: KLLSketchState, out) -> KLLSketchState:
        samples, valid, nv, mn, mx, level = out
        acc.add_pre_compacted(
            np.asarray(samples)[np.asarray(valid)],
            int(level),
            int(nv),
            float(mn),
            float(mx),
        )
        return acc

    return ScanOps(
        init,
        update,
        KLLSketchState.merge,
        host_init=host_init,
        host_fold=host_fold,
    )


class _KLLBase(ScanShareableAnalyzer):
    column: str
    where: Optional[str]

    @property
    def instance(self) -> str:
        return self.column

    def preconditions(self) -> List[Precondition]:
        return [has_column(self.column), is_numeric(self.column)]

    def device_requests(self, dataset: Dataset) -> List[ColumnRequest]:
        _, reqs = _compile_where(self.where, dataset)
        return [
            ColumnRequest(self.column, "values"),
            ColumnRequest(self.column, "mask"),
        ] + reqs


@dataclass(frozen=True)
class KLLSketch(_KLLBase):
    """Full KLL sketch metric (reference: analyzers/KLLSketch.scala)."""

    column: str
    params: KLLParameters = field(default_factory=KLLParameters)
    where: Optional[str] = None

    def make_ops(self, dataset: Dataset) -> ScanOps:
        return _make_kll_ops(self, dataset, self.params)

    def compute_metric_from_state(self, state) -> Metric:
        if state is None or state.is_empty:
            return self.to_failure_metric(
                EmptyStateException("Empty state for analyzer KLLSketch.")
            )
        buckets = [
            BucketValue(lo, hi, count)
            for lo, hi, count in state.buckets(self.params.number_of_buckets)
        ]
        dist = BucketDistribution(
            buckets,
            parameters=(
                self.params.shrinking_factor,
                float(self.params.sketch_size),
            ),
            data=tuple(tuple(map(float, lv)) for lv in state.levels),
        )
        return KLLMetric(Entity.COLUMN, "KLL", self.instance, Success(dist))


@dataclass(frozen=True)
class ApproxQuantile(_KLLBase):
    """Single approximate quantile (reference: ApproxQuantile.scala)."""

    column: str
    quantile: float = 0.5
    relative_error: float = 0.01  # accepted for API parity; KLL governs
    where: Optional[str] = None
    params: KLLParameters = field(default_factory=KLLParameters)

    def preconditions(self) -> List[Precondition]:
        def quantile_in_range(schema):
            if not (0.0 <= self.quantile <= 1.0):
                from deequ_tpu.analyzers.base import (
                    IllegalAnalyzerParameterException,
                )

                raise IllegalAnalyzerParameterException(
                    f"quantile must be in [0, 1], got {self.quantile}"
                )

        return super().preconditions() + [quantile_in_range]

    def make_ops(self, dataset: Dataset) -> ScanOps:
        return _make_kll_ops(self, dataset, self.params)

    def compute_metric_from_state(self, state) -> DoubleMetric:
        if state is None or state.is_empty:
            return self.to_failure_metric(
                EmptyStateException("Empty state for analyzer ApproxQuantile.")
            )
        result = state.quantile(self.quantile)
        if math.isnan(result):
            return self.to_failure_metric(
                EmptyStateException(
                    "ApproxQuantile sketch holds no samples."
                )
            )
        return DoubleMetric.success(
            self.entity, "ApproxQuantile", self.instance, result
        )


@dataclass(frozen=True)
class ApproxQuantiles(_KLLBase):
    """Several quantiles from ONE sketch (reference: ApproxQuantiles.scala)."""

    column: str
    quantiles: Tuple[float, ...] = (0.25, 0.5, 0.75)
    where: Optional[str] = None
    params: KLLParameters = field(default_factory=KLLParameters)

    def __post_init__(self):
        object.__setattr__(self, "quantiles", tuple(self.quantiles))

    def make_ops(self, dataset: Dataset) -> ScanOps:
        return _make_kll_ops(self, dataset, self.params)

    def compute_metric_from_state(self, state) -> Metric:
        if state is None or state.is_empty:
            return self.to_failure_metric(
                EmptyStateException(
                    "Empty state for analyzer ApproxQuantiles."
                )
            )
        results = state.quantiles(self.quantiles)  # one sort for all qs
        values = {
            str(q): value for q, value in zip(self.quantiles, results)
        }
        return KeyedDoubleMetric(
            Entity.COLUMN, "ApproxQuantiles", self.instance, Success(values)
        )
