"""Streaming parquet ingest: scan-feed batches without materializing
whole columns.

Reference context: the reference delegates IO to Spark's parquet reader
feeding partitioned scans (SURVEY.md §7 stage 0, §5.7 "streamed
chunking over record batches"). Here :class:`ParquetDataset` exposes
the same Dataset contract over a (multi-file) parquet source:

- ``device_batches`` STREAMS: Arrow record batches are read column-
  pruned from the files, re-chunked to the engine's fixed batch size,
  converted to device representations per batch, and fed to the fused
  scan — host memory stays O(batch x requested columns), so a table
  far larger than RAM profiles fine.
- string columns get a GLOBAL dictionary built in one streaming
  pre-pass (O(distinct) memory) so code-based LUT closures (PatternMatch,
  predicates, HLL) see stable codes across batches.
- ``materialize`` (full column) still works — the resident fast path
  uses it when the request set fits the device cache budget — but the
  streaming path never calls it.
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Optional, Sequence

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc
import pyarrow.dataset as pads

from deequ_tpu.data.table import (
    ColumnRequest,
    Dataset,
    Field,
    Kind,
    ROW_MASK,
    Schema,
    _kind_of,
    convert_basic_repr,
    dictionary_to_numpy,
    narrow_codes,
)


def _column_batch_to_reprs(
    column: pa.Array,
    kind: Kind,
    requests: List[str],
    value_set: Optional[pa.Array] = None,
    values_dtype: Optional[np.dtype] = None,
) -> Dict[str, np.ndarray]:
    """Convert one record-batch column into the requested device reprs.
    mask/values/lengths/u64bits share Dataset.materialize's conversion
    rules (table.convert_basic_repr); codes come from a vectorized
    ``pc.index_in`` against the dataset-global dictionary (Arrow treats
    NaN as equal to NaN, matching the in-memory dictionary_encode
    path; nulls index to -1). ``values_dtype`` applies the PER-COLUMN
    wire-narrowing decision (from parquet statistics) — narrowing per
    batch would make streamed batch dtypes unstable and recompile the
    fused scan per dtype combination."""
    out: Dict[str, np.ndarray] = {}
    for repr_name in requests:
        if repr_name == "codes":
            assert value_set is not None
            if pa.types.is_dictionary(column.type):
                column = pc.cast(column, column.type.value_type)
            idx = pc.index_in(column, value_set=value_set)
            idx = pc.fill_null(idx, pa.scalar(-1, idx.type))
            out["codes"] = np.ascontiguousarray(
                narrow_codes(
                    idx.to_numpy(zero_copy_only=False).astype(np.int32),
                    len(value_set),
                )
            )
        else:
            arr = convert_basic_repr(column, kind, repr_name)
            if repr_name == "values" and values_dtype is not None:
                arr = arr.astype(values_dtype)
            out[repr_name] = arr
    return out


class ParquetDataset(Dataset):
    """A Dataset over parquet file(s)/directory, scanned lazily."""

    def __init__(self, source, read_batch_rows: int = 1 << 20):
        # no super().__init__: there is no in-memory table
        self._source = pads.dataset(source, format="parquet")
        self._read_batch_rows = read_batch_rows
        self._schema = Schema(
            tuple(
                Field(name, _kind_of(typ))
                for name, typ in zip(
                    self._source.schema.names, self._source.schema.types
                )
            )
        )
        self._num_rows = self._source.count_rows()
        self._materialized: Dict[str, np.ndarray] = {}
        self._dictionaries: Dict[str, np.ndarray] = {}
        self._value_sets: Dict[str, pa.Array] = {}
        self._null_counts: Dict[str, int] = {}
        self._device_cache: Dict = {}
        self._cache_key = id(self)
        import weakref

        weakref.finalize(self, Dataset._drop_cache_key, self._cache_key)

    # -- metadata -------------------------------------------------------

    @property
    def table(self) -> pa.Table:  # loads everything; avoid on big data
        return self._source.to_table()

    @property
    def num_rows(self) -> int:
        return self._num_rows

    @property
    def num_columns(self) -> int:
        return len(self._schema)

    def filter_rows(self, mask: np.ndarray) -> Dataset:
        return Dataset(self.table.filter(pa.array(mask)))

    def select(self, columns: Sequence[str]) -> Dataset:
        return Dataset(self._source.to_table(columns=list(columns)))

    def record_batches(
        self, columns: Sequence[str], batch_rows: int = 1 << 20
    ) -> Iterator[pa.RecordBatch]:
        scanner = self._source.scanner(
            columns=list(columns), batch_size=batch_rows
        )
        return iter(scanner.to_batches())

    def fingerprint(self) -> str:
        """STRONG source identity for checkpoint invalidation: the
        sorted file list plus per-file size and mtime (rewritten,
        appended or touched files all change it). Falls back to
        path-only identity for storage without stat support."""
        import hashlib
        import os

        h = hashlib.sha1()
        for path in sorted(self._source.files):
            h.update(path.encode())
            try:
                st = os.stat(path)
                h.update(f":{st.st_size}:{st.st_mtime_ns}".encode())
            except OSError:
                pass
        h.update(str(self._num_rows).encode())
        return f"parquet-{h.hexdigest()[:20]}"

    # -- statistics from parquet metadata -------------------------------

    def _column_null_count(self, column: str) -> int:
        if column not in self._null_counts:
            total = 0
            known = True
            for fragment in self._source.get_fragments():
                meta = fragment.metadata
                idx = self._source.schema.get_field_index(column)
                for rg in range(meta.num_row_groups):
                    stats = meta.row_group(rg).column(idx).statistics
                    if stats is None or stats.null_count is None:
                        known = False
                        break
                    total += stats.null_count
                if not known:
                    break
            # unknown stats -> conservatively "has nulls" (mask ships)
            self._null_counts[column] = total if known else 1
        return self._null_counts[column]

    def _is_all_valid(self, column: str) -> bool:
        return self._column_null_count(column) == 0

    def _values_dtype(self, column: str) -> Optional[np.dtype]:
        """Per-COLUMN wire-narrowing decision for int64 columns, from
        parquet row-group min/max statistics (one decision for the whole
        stream; see _column_batch_to_reprs). None = keep native."""
        if not hasattr(self, "_values_dtypes"):
            self._values_dtypes: Dict[str, Optional[np.dtype]] = {}
        if column in self._values_dtypes:
            return self._values_dtypes[column]
        decision: Optional[np.dtype] = None
        arrow_type = self._column_arrow_type(column)
        if (
            self._schema.kind_of(column) == Kind.INTEGRAL
            and pa.types.is_integer(arrow_type)
            and arrow_type.bit_width == 64
        ):
            rng = self._stats_min_max(column)
            if (
                rng is not None
                and rng[0] >= -(2**31)
                and rng[1] < 2**31
            ):
                decision = np.dtype(np.int32)
        self._values_dtypes[column] = decision
        return decision

    def _stats_min_max(self, column: str):
        """(min, max) folded over every fragment's row-group
        statistics, or None when any group lacks them — THE one stats
        walk (consumed by the wire-narrowing decision above and the
        integral-range probe below)."""
        lo, hi = None, None
        idx = self._source.schema.get_field_index(column)
        for fragment in self._source.get_fragments():
            meta = fragment.metadata
            for rg in range(meta.num_row_groups):
                stats = meta.row_group(rg).column(idx).statistics
                if (
                    stats is None
                    or not stats.has_min_max
                    or stats.min is None
                    or stats.max is None
                ):
                    return None
                lo = stats.min if lo is None else min(lo, stats.min)
                hi = stats.max if hi is None else max(hi, stats.max)
        return None if lo is None else (lo, hi)

    def _column_arrow_type(self, column: str) -> pa.DataType:
        idx = self._source.schema.get_field_index(column)
        return self._source.schema.types[idx]

    def request_dtype(self, req: ColumnRequest) -> np.dtype:
        """Batch dtype WITHOUT materializing the stream: run the one
        authoritative conversion (_column_batch_to_reprs) on a ZERO-ROW
        column of the file's type, so any future change to the
        conversion/narrowing rules is reflected here automatically."""
        if req.repr == "mask":
            return np.dtype(bool)
        kind = self._schema.kind_of(req.column)
        value_set = (
            self._dict_value_set(req.column)
            if req.repr == "codes"
            else None
        )
        values_dtype = (
            self._values_dtype(req.column)
            if req.repr == "values"
            else None
        )
        empty = pa.array([], type=self._column_arrow_type(req.column))
        out = _column_batch_to_reprs(
            empty, kind, [req.repr], value_set, values_dtype
        )
        return np.dtype(out[req.repr].dtype)

    # -- global dictionaries (streaming pre-pass) -----------------------

    def _collect_uniques(
        self, column: str, cap: Optional[int]
    ) -> Optional[pa.Array]:
        """Stream distinct non-null values, staying ENTIRELY in Arrow
        (pc.unique per chunk, periodic compaction) — a Python set would
        cost GBs at tens of millions of distinct values. Returns None
        once the count provably exceeds ``cap``."""
        base: Optional[pa.Array] = None  # already-deduped accumulator
        fresh: List[pa.Array] = []  # per-batch uniques since last compact
        fresh_n = 0

        def compact() -> None:
            nonlocal base, fresh, fresh_n
            arrays = ([base] if base is not None else []) + fresh
            base = pc.unique(pa.concat_arrays(arrays))
            fresh = []
            fresh_n = 0

        scanner = self._source.scanner(
            columns=[column], batch_size=self._read_batch_rows
        )
        field_type = self._source.schema.field(column).type
        if pa.types.is_dictionary(field_type):
            field_type = field_type.value_type
        for batch in scanner.to_batches():
            col = batch.column(0)
            if pa.types.is_dictionary(col.type):
                col = pc.cast(col, col.type.value_type)
            u = pc.drop_null(pc.unique(col))
            if len(u):
                fresh.append(u)
                fresh_n += len(u)
            # compact on FRESH volume only (an accumulator already past
            # the threshold must not trigger a full re-unique per batch),
            # or when the optimistic total might prove the cap exceeded
            over_cap_maybe = cap is not None and (
                (0 if base is None else len(base)) + fresh_n > cap
            )
            if fresh_n > 4 * self._read_batch_rows or over_cap_maybe:
                compact()
                if cap is not None and len(base) > cap:
                    return None
        if fresh_n:
            compact()
        if base is None:
            return pa.array([], field_type)
        if cap is not None and len(base) > cap:
            return None
        return base

    def integral_range(self, column: str):
        """Row-group min/max statistics make the range probe FREE for
        parquet sources (no data scan); unknown stats -> None (treated
        as unbounded)."""
        if self._schema.kind_of(column) != Kind.INTEGRAL:
            return None
        if not hasattr(self, "_integral_ranges"):
            self._integral_ranges = {}
        if column not in self._integral_ranges:
            rng = self._stats_min_max(column)
            self._integral_ranges[column] = (
                (int(rng[0]), int(rng[1]))
                if rng is not None and isinstance(rng[0], int)
                else None
            )
        return self._integral_ranges[column]

    def dictionary_size_within(self, column: str, cap: int):
        if column in self._dictionaries:
            n = len(self._dictionaries[column])
            return n if n <= cap else None
        uniques = self._collect_uniques(column, cap)
        if uniques is None:
            return None  # over cap: never materialize the full set
        self._store_dictionary(column, uniques)
        return len(self._dictionaries[column])

    def _store_dictionary(self, column: str, uniques: pa.Array) -> None:
        self._value_sets[column] = uniques
        self._dictionaries[column] = dictionary_to_numpy(uniques)

    def dictionary(self, column: str) -> np.ndarray:
        if column not in self._dictionaries:
            self._store_dictionary(
                column, self._collect_uniques(column, None)
            )
        return self._dictionaries[column]

    def _dict_value_set(self, column: str) -> pa.Array:
        self.dictionary(column)
        return self._value_sets[column]

    # -- full-column materialization (resident path only) ---------------

    def _reprs_for_kind(self, kind: Kind) -> List[str]:
        """All reprs one scan can fill for a column of this kind —
        materializing any repr fills the others too, so callers needing
        several (values+mask, codes+mask+lengths) cost ONE file scan."""
        if kind == Kind.STRING:
            return ["codes", "mask", "lengths"]
        return ["values", "mask"]

    def materialize(self, req: ColumnRequest) -> np.ndarray:
        key = req.key
        if key in self._materialized:
            return self._materialized[key]
        kind = self._schema.kind_of(req.column)
        reprs = self._reprs_for_kind(kind)
        if req.repr not in reprs:
            reprs = reprs + [req.repr]  # let the converter raise clearly
        value_set = (
            self._dict_value_set(req.column) if "codes" in reprs else None
        )
        chunks: Dict[str, List[np.ndarray]] = {r: [] for r in reprs}
        scanner = self._source.scanner(
            columns=[req.column], batch_size=self._read_batch_rows
        )
        values_dtype = self._values_dtype(req.column)
        for batch in scanner.to_batches():
            out = _column_batch_to_reprs(
                batch.column(0), kind, reprs, value_set, values_dtype
            )
            for r in reprs:
                chunks[r].append(out[r])
        for r in reprs:
            if chunks[r]:
                arr = np.concatenate(chunks[r])
            else:
                arr = _column_batch_to_reprs(
                    pa.array([], self._source.schema.field(req.column).type),
                    kind,
                    [r],
                    value_set,
                    values_dtype,
                )[r]
            self._materialized[f"{req.column}::{r}"] = arr
        return self._materialized[key]

    # -- streaming batches ----------------------------------------------

    def device_batches(
        self,
        requests: Sequence[ColumnRequest],
        batch_size: Optional[int] = None,
        start_batch: int = 0,
    ) -> Iterator[Dict[str, np.ndarray]]:
        """Stream fixed-size batches from the parquet source: read
        column-pruned record batches, convert to device reprs, re-chunk
        to ``batch_size``, zero-pad the tail. Host memory is bounded by
        O(read_batch + batch_size) per requested repr.

        ``start_batch`` (resilience-layer retry/resume) skips the first
        ``start_batch * batch_size`` rows of the stream by slicing the
        leading record batches away before any conversion; since the
        skip is a whole number of engine batches, the re-chunker's batch
        boundaries — and therefore every yielded batch — are identical
        to the corresponding batches of a full stream."""
        n = self.num_rows
        if batch_size is None:
            batch_size = n if n > 0 else 1
        batch_size = max(1, batch_size)
        skip_rows = start_batch * batch_size

        keys = self._dedup_requests(requests)
        by_column: Dict[str, List[str]] = {}
        for r in keys.values():
            by_column.setdefault(r.column, []).append(r.repr)
        columns = sorted(by_column)
        if not columns or n == 0:
            # degenerate: no columns requested (e.g. Size only) or empty
            yield from self._empty_or_counting_batches(
                keys, batch_size, n, skip_rows
            )
            return
        # pre-build dictionaries for code requests (streaming pre-pass)
        value_sets = {
            c: self._dict_value_set(c)
            for c, reprs in by_column.items()
            if "codes" in reprs
        }
        values_dtypes = {
            c: self._values_dtype(c)
            for c, reprs in by_column.items()
            if "values" in reprs
        }

        pending: Dict[str, List[np.ndarray]] = {k: [] for k in keys}
        pending_rows = 0

        def drain(force_pad: bool):
            nonlocal pending, pending_rows
            while pending_rows >= batch_size or (
                force_pad and pending_rows > 0
            ):
                batch: Dict[str, np.ndarray] = {}
                width = min(pending_rows, batch_size)
                pad = batch_size - width
                for k in keys:
                    joined = (
                        np.concatenate(pending[k])
                        if len(pending[k]) > 1
                        else pending[k][0]
                    )
                    head, tail = joined[:width], joined[width:]
                    pending[k] = [tail] if len(tail) else []
                    if pad:
                        head = np.concatenate(
                            [head, np.zeros((pad,), dtype=head.dtype)]
                        )
                    batch[k] = head
                row_mask = np.ones((batch_size,), dtype=bool)
                if pad:
                    row_mask[width:] = False
                    for k in keys:
                        if k.endswith("::mask"):
                            batch[k] = batch[k] & row_mask
                batch[ROW_MASK] = row_mask
                pending_rows -= width
                yield batch

        scanner = self._source.scanner(
            columns=columns, batch_size=self._read_batch_rows
        )
        for record_batch in scanner.to_batches():
            if skip_rows > 0:
                if record_batch.num_rows <= skip_rows:
                    skip_rows -= record_batch.num_rows
                    continue
                record_batch = record_batch.slice(skip_rows)
                skip_rows = 0
            if record_batch.num_rows == 0:
                continue
            for ci, column_name in enumerate(columns):
                kind = self._schema.kind_of(column_name)
                reprs = _column_batch_to_reprs(
                    record_batch.column(ci),
                    kind,
                    by_column[column_name],
                    value_sets.get(column_name),
                    values_dtypes.get(column_name),
                )
                for repr_name, arr in reprs.items():
                    pending[f"{column_name}::{repr_name}"].append(arr)
            pending_rows += record_batch.num_rows
            yield from drain(force_pad=False)
        yield from drain(force_pad=True)

    def _empty_or_counting_batches(
        self, keys, batch_size: int, n: int, skip_rows: int = 0
    ):
        """No requested columns (Size()-only) or an empty source."""
        if n == 0:
            if skip_rows > 0:
                return
            batch: Dict[str, np.ndarray] = {}
            for k, r in keys.items():
                kind = self._schema.kind_of(r.column)
                value_set = (
                    self._dict_value_set(r.column)
                    if r.repr == "codes"
                    else None
                )
                empty = _column_batch_to_reprs(
                    pa.array([], self._source.schema.field(r.column).type),
                    kind,
                    [r.repr],
                    value_set,
                    self._values_dtype(r.column)
                    if r.repr == "values"
                    else None,
                )[r.repr]
                batch[k] = np.zeros((batch_size,), dtype=empty.dtype)
            batch[ROW_MASK] = np.zeros((batch_size,), dtype=bool)
            yield batch
            return
        remaining = n - skip_rows
        while remaining > 0:
            width = min(remaining, batch_size)
            row_mask = np.zeros((batch_size,), dtype=bool)
            row_mask[:width] = True
            yield {ROW_MASK: row_mask}
            remaining -= width
