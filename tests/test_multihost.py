"""Multi-host execution evidence (SURVEY §7 stage 8): two REAL processes
initialize jax.distributed over loopback, profile their own parquet
shards, persist states, and the merged states equal the whole-table run.
Delegates to examples/multihost_profiling.py — the runnable demo IS the
test."""

import os
import subprocess
import sys


def test_two_process_loopback_merge_equals_whole_table():
    """Spawns real worker processes; ~60-90s wall (backend init x2)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = os.path.join(repo, "examples", "multihost_profiling.py")
    result = subprocess.run(
        [sys.executable, script],
        capture_output=True,
        text=True,
        timeout=400,
    )
    assert result.returncode == 0, result.stdout + result.stderr
    assert "merged == whole-table" in result.stdout
