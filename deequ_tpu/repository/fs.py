"""Filesystem metrics repository: one JSON file of all results.

Reference: ``repository/fs/FileSystemMetricsRepository.scala`` (SURVEY.md
§2.5) — JSON file on local/HDFS/S3 via the Hadoop FS API; here any
mounted filesystem path. Concurrent writers are serialized by an
advisory in-process lock; the file is rewritten atomically.
"""

from __future__ import annotations

import os
import tempfile
import threading
from typing import List, Optional

from deequ_tpu.repository import serde
from deequ_tpu.repository.base import (
    AnalysisResult,
    MetricsRepository,
    MetricsRepositoryMultipleResultsLoader,
    ResultKey,
)


class FileSystemMetricsRepository(MetricsRepository):
    def __init__(self, path: str):
        self._path = path
        self._lock = threading.Lock()
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)

    def _read_all(self) -> List[AnalysisResult]:
        if not os.path.exists(self._path):
            return []
        with open(self._path) as fh:
            text = fh.read()
        if not text.strip():
            return []
        return serde.deserialize(text)

    def _write_all(self, results: List[AnalysisResult]) -> None:
        directory = os.path.dirname(os.path.abspath(self._path))
        fd, tmp = tempfile.mkstemp(dir=directory, suffix=".tmp")
        try:
            with os.fdopen(fd, "w") as fh:
                fh.write(serde.serialize(results))
            os.replace(tmp, self._path)
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)

    def save(self, result: AnalysisResult) -> None:
        with self._lock:
            results = [
                r
                for r in self._read_all()
                if r.result_key != result.result_key
            ]
            results.append(result)
            self._write_all(results)

    def load_by_key(self, key: ResultKey) -> Optional[AnalysisResult]:
        with self._lock:
            for result in self._read_all():
                if result.result_key == key:
                    return result
        return None

    def load(self) -> MetricsRepositoryMultipleResultsLoader:
        with self._lock:
            return MetricsRepositoryMultipleResultsLoader(self._read_all())
