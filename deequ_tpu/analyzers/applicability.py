"""Applicability: can this check / these analyzers run on this schema?

Reference: ``analyzers/applicability/Applicability.scala`` (SURVEY.md
§1 L12): instantiate the check or analyzers against a ``StructType``,
synthesize a row of matching types, and report per-constraint/
per-analyzer applicability. Here the synthesized data is a two-row
typed Arrow table generated from the Schema's kinds; each analyzer runs
through the ordinary runner, so precondition failures AND runtime
planning failures (bad predicate, wrong types) surface exactly as they
would in production — as failure metrics, mapped to per-item report
entries.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import numpy as np
import pyarrow as pa

from typing import TYPE_CHECKING

from deequ_tpu.analyzers.base import Analyzer
from deequ_tpu.analyzers.runner import AnalysisRunner
from deequ_tpu.data.table import Dataset, Kind, Schema

if TYPE_CHECKING:  # avoid the analyzers <-> checks import cycle
    from deequ_tpu.checks.check import Check


def _synthesize_dataset(schema: Schema, num_rows: int = 2) -> Dataset:
    """A tiny typed table matching the schema's kinds (reference:
    Applicability synthesizes a row of matching types)."""
    arrays = {}
    for f in schema.fields:
        if f.kind == Kind.INTEGRAL:
            arrays[f.name] = pa.array(
                np.arange(1, num_rows + 1, dtype=np.int64)
            )
        elif f.kind == Kind.FRACTIONAL:
            arrays[f.name] = pa.array(
                np.linspace(1.0, 2.0, num_rows).astype(np.float64)
            )
        elif f.kind == Kind.BOOLEAN:
            arrays[f.name] = pa.array(
                [(i % 2 == 0) for i in range(num_rows)]
            )
        elif f.kind == Kind.TIMESTAMP:
            arrays[f.name] = pa.array(
                np.arange(num_rows, dtype=np.int64),
                pa.timestamp("ms"),
            )
        else:  # STRING / UNKNOWN
            arrays[f.name] = pa.array([f"v{i}" for i in range(num_rows)])
    return Dataset(pa.table(arrays))


@dataclass
class ApplicabilityResult:
    is_applicable: bool
    # item (constraint repr or analyzer repr) -> None if ok, else reason
    failures: Dict[str, Optional[str]] = field(default_factory=dict)


class Applicability:
    """Evaluates checks/analyzers against a Schema without real data."""

    def is_applicable(
        self, check: "Check", schema: Schema
    ) -> ApplicabilityResult:
        """Per-constraint applicability of a whole check."""
        data = _synthesize_dataset(schema)
        analyzers = check.required_analyzers()
        context = AnalysisRunner.do_analysis_run(data, analyzers)
        failures: Dict[str, Optional[str]] = {}
        ok = True
        for constraint_result in check.evaluate(context).constraint_results:
            name = repr(constraint_result.constraint)
            metric = constraint_result.metric
            if metric is not None and metric.value.is_failure:
                failures[name] = str(metric.value.exception)
                ok = False
            else:
                failures[name] = None
        return ApplicabilityResult(ok, failures)

    def are_applicable(
        self, analyzers: Sequence[Analyzer], schema: Schema
    ) -> ApplicabilityResult:
        """Per-analyzer applicability."""
        data = _synthesize_dataset(schema)
        context = AnalysisRunner.do_analysis_run(data, list(analyzers))
        failures: Dict[str, Optional[str]] = {}
        ok = True
        for analyzer in analyzers:
            metric = context.metric(analyzer)
            if metric is None or metric.value.is_failure:
                failures[repr(analyzer)] = (
                    str(metric.value.exception)
                    if metric is not None
                    else "no metric computed"
                )
                ok = False
            else:
                failures[repr(analyzer)] = None
        return ApplicabilityResult(ok, failures)
