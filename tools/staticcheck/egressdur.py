"""Egress-durability analyzer: no cursor without a durable flush.

The write-ahead invariant of exactly-once row-level egress
(docs/EGRESS.md "Durable egress"): an ``EgressCursor`` — the durable
high-water mark a resumed run trusts — may only be constructed, and a
``ScanCursor`` only assembled, AFTER the span segment (and the plane
spool) it names has been durably flushed. A call site that mints a
cursor without flushing first can persist a cursor that points past
the durable data; a crash then makes resume silently DROP the rows
between the flush and the cursor.

The rule is structural, the ``preempt-discipline`` pattern applied to
egress: inside ``deequ_tpu/egress/``, every call to a name in the
guarded set (``EgressCursor``, ``ScanCursor``) must be LEXICALLY
PRECEDED, within the same enclosing function, by a durable-flush call
(``flush_durable``, ``_finalize_open_segment``, ``fsync``, or
``durable_replace``). Flow-insensitive on purpose: flush-then-cursor
is written straight-line in the writer, so lexical order IS the
ordering being protected.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Sequence, Tuple

from tools.staticcheck.core import (
    Analyzer,
    Finding,
    SourceFile,
    dotted_name,
    register,
)

SCOPE_PREFIX = "deequ_tpu/egress/"

#: cursor constructions/writes that need durable-flush evidence
GUARDED_NAMES = frozenset({"EgressCursor", "ScanCursor"})
#: any of these, earlier in the same function, licenses the cursor
EVIDENCE_NAMES = frozenset(
    {"flush_durable", "_finalize_open_segment", "fsync", "durable_replace"}
)


def _call_name(node: ast.Call) -> Optional[str]:
    """The last path segment of the called name ('fsync' for
    ``os.fsync(...)``, 'EgressCursor' for a bare constructor), or None
    for computed callees."""
    name = dotted_name(node.func)
    if name is None:
        return None
    return name.split(".")[-1]


def _function_sites(
    tree: ast.AST,
) -> Iterable[Tuple[Optional[ast.AST], List[ast.Call]]]:
    """(enclosing function, calls directly inside it) pairs; calls in
    nested functions belong to the NESTED function (each scope must
    establish its own evidence), module-level calls to None."""
    functions = [
        node
        for node in ast.walk(tree)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
    ]
    owner: dict[int, ast.AST] = {}
    for fn in functions:
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                # innermost function wins: walk visits outer functions
                # first, so a later (nested) owner overwrites
                owner[id(node)] = fn
    by_fn: dict[int, List[ast.Call]] = {}
    module_level: List[ast.Call] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = owner.get(id(node))
        if fn is None:
            module_level.append(node)
        else:
            by_fn.setdefault(id(fn), []).append(node)
    for fn in functions:
        yield fn, by_fn.get(id(fn), [])
    if module_level:
        yield None, module_level


class EgressDurabilityAnalyzer(Analyzer):
    name = "egressdur"
    rules = ("egress-durability",)
    description = (
        "EgressCursor/ScanCursor constructions in deequ_tpu/egress/ "
        "not preceded by a durable-flush call"
    )

    def analyze(
        self, files: Sequence[SourceFile], root: str
    ) -> Iterable[Finding]:
        for sf in files:
            if not sf.rel.startswith(SCOPE_PREFIX) or sf.tree is None:
                continue
            for fn, calls in _function_sites(sf.tree):
                evidence_lines = [
                    c.lineno
                    for c in calls
                    if _call_name(c) in EVIDENCE_NAMES
                ]
                first_evidence = (
                    min(evidence_lines) if evidence_lines else None
                )
                for call in calls:
                    name = _call_name(call)
                    if name not in GUARDED_NAMES:
                        continue
                    if (
                        first_evidence is not None
                        and first_evidence < call.lineno
                    ):
                        continue
                    where = (
                        f"function {getattr(fn, 'name', '?')!r}"
                        if fn is not None
                        else "module level"
                    )
                    yield Finding(
                        rule="egress-durability",
                        path=sf.rel,
                        line=call.lineno,
                        message=(
                            f"{name}(...) at {where} without a "
                            "preceding durable-flush call "
                            "(flush_durable/_finalize_open_segment/"
                            "fsync/durable_replace) — a cursor written "
                            "before its span is durable makes resume "
                            "drop rows (docs/EGRESS.md "
                            '"Durable egress")'
                        ),
                        symbol=name,
                    )


register(EgressDurabilityAnalyzer())
