"""Parallel host ingest (engine/ingest.py, docs/PERF.md "r10"): the
ordered decode/encode worker pool and the process-sharded feed.

The load-bearing assertion is DIFFERENTIAL: ``ingest_workers=1`` runs
the exact pre-r10 single-prefetcher path, so every metric computed
with the pool engaged (workers > 1) must equal the workers=1 oracle
bit-for-bit — on streaming and mesh paths, through mid-stream codec
widening, dictionary-delta growth, worker-scoped faults, and
checkpoint/resume. The ordering machinery itself (reassembly,
lookahead bound, error position, teardown) gets unit scenarios against
``ordered_ingest`` directly.
"""

import os
import threading
import time

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from deequ_tpu import config
from deequ_tpu.analyzers import (
    AnalysisRunner,
    ApproxCountDistinct,
    Completeness,
    DataType,
    Maximum,
    Mean,
    Minimum,
    Size,
)
from deequ_tpu.data import Dataset
from deequ_tpu.data.parquet import ParquetDataset
from deequ_tpu.engine.ingest import (
    IngestPoolStats,
    active_ingest_threads,
    ordered_ingest,
    resolve_ingest_lookahead,
    resolve_ingest_workers,
)
from deequ_tpu.engine.resilience import RetryPolicy, ScanKilled
from deequ_tpu.engine.scan import AnalysisEngine, active_prefetch_workers
from deequ_tpu.io.state_provider import ScanCheckpointer
from deequ_tpu.telemetry import get_telemetry
from deequ_tpu.testing.faults import FaultInjectingDataset

FAST_RETRY = RetryPolicy(max_attempts=3, sleep=lambda _s: None)


# --------------------------------------------------------------------------
# ordered_ingest unit scenarios
# --------------------------------------------------------------------------


class TestOrderedIngest:
    def test_release_order_is_source_order_under_jitter(self):
        rng = np.random.default_rng(7)
        delays = rng.uniform(0.0, 0.004, 32).tolist()

        def work(i):
            time.sleep(delays[i])
            return i * 10

        out = list(
            ordered_ingest(
                range(32), work, workers=4, lookahead=8, emit_event=False
            )
        )
        assert out == [i * 10 for i in range(32)]

    def test_workers_1_is_plain_passthrough(self):
        out = list(
            ordered_ingest(
                range(10), lambda i: i + 1, workers=1, lookahead=2,
                emit_event=False,
            )
        )
        assert out == list(range(1, 11))

    def test_error_surfaces_at_exact_position(self):
        def work(i):
            if i == 5:
                raise ValueError("boom at five")
            return i

        received = []
        with pytest.raises(ValueError, match="boom at five"):
            for value in ordered_ingest(
                range(12), work, workers=4, lookahead=6, emit_event=False
            ):
                received.append(value)
        # every earlier item released, nothing after the failure
        assert received == [0, 1, 2, 3, 4]

    def test_commit_runs_on_consumer_thread_in_order(self):
        consumer = threading.current_thread()
        committed = []

        def commit(result, item):
            assert threading.current_thread() is consumer
            committed.append(item)
            return result

        out = list(
            ordered_ingest(
                range(16), lambda i: -i, commit, workers=3, lookahead=4,
                emit_event=False,
            )
        )
        assert committed == list(range(16))
        assert out == [-i for i in range(16)]

    def test_lookahead_bounds_in_flight_items(self):
        stats = IngestPoolStats()
        list(
            ordered_ingest(
                range(40), lambda i: i, workers=4, lookahead=5,
                stats=stats, emit_event=False,
            )
        )
        assert stats.released == 40
        assert 1 <= stats.peak_in_flight <= 5

    def test_sizer_prices_peak_in_flight_bytes(self):
        stats = IngestPoolStats()
        list(
            ordered_ingest(
                range(8), lambda i: i, workers=2, lookahead=4,
                stats=stats, sizer=lambda _r: 1000, emit_event=False,
            )
        )
        assert 1000 <= stats.peak_in_flight_bytes <= 4000

    def test_abandoned_consumer_tears_down_all_threads(self):
        gen = ordered_ingest(
            range(1000), lambda i: time.sleep(0.001) or i,
            workers=4, lookahead=4, emit_event=False,
        )
        assert next(gen) == 0
        gen.close()  # teardown: stop + drain + join
        deadline = time.time() + 5.0
        while active_ingest_threads() and time.time() < deadline:
            time.sleep(0.01)
        assert active_ingest_threads() == []

    def test_resolvers(self):
        assert resolve_ingest_workers(3) == 3
        auto = resolve_ingest_workers(0)
        assert 1 <= auto <= 4
        assert resolve_ingest_lookahead(7, workers=2) == 7
        # auto = 2x workers, floored at workers
        assert resolve_ingest_lookahead(0, workers=3) == 6
        assert resolve_ingest_lookahead(1, workers=4) == 4


# --------------------------------------------------------------------------
# engine differentials: workers=1 is the pre-r10 oracle
# --------------------------------------------------------------------------


@pytest.fixture(scope="module")
def pool_parquet(tmp_path_factory):
    """Four-file parquet source shaped to exercise the pool: floats
    (one masked), stats-narrowable ints, and a string vocabulary that
    GROWS per file so dictionary deltas are cut mid-stream."""
    directory = tmp_path_factory.mktemp("poolpq")
    rng = np.random.default_rng(29)
    for i in range(4):
        n = 900 + i * 150
        vocab = np.array([f"tok{j:03d}" for j in range((i + 1) * 5)])
        flat = np.array(["red", "green", "blue"])
        x = rng.normal(0.0, 1.0, n)
        pq.write_table(
            pa.table(
                {
                    "f": pa.array(rng.normal(50.0, 9.0, n)),
                    "x": pa.array(
                        x, pa.float64(), mask=(rng.random(n) < 0.1)
                    ),
                    "k": pa.array(
                        rng.integers(0, 120, n, dtype=np.int64)
                    ),
                    "s": pa.array(vocab[rng.integers(0, len(vocab), n)]),
                    "t": pa.array(flat[rng.integers(0, 3, n)]),
                }
            ),
            os.path.join(directory, f"part-{i}.parquet"),
        )
    return str(directory)


POOL_ANALYZERS = [
    Size(),
    Mean("f"),
    Minimum("f"),
    Maximum("f"),
    Completeness("x"),
    Mean("x"),
    Minimum("k"),
    Maximum("k"),
    # TWO string columns each carrying the ACD + DataType pair: the
    # planner only forms the pooled one-pass codes unit for groups of
    # >= 2 members (a lone string ACD takes the singles path and pays
    # the dictionary pre-pass), so this keeps the delta protocol on
    # the hot path with data_passes == 1
    ApproxCountDistinct("s"),
    DataType("s"),
    ApproxCountDistinct("t"),
    DataType("t"),
]


def _metric_values(ctx, analyzers=POOL_ANALYZERS):
    out = []
    for a in analyzers:
        value = ctx.metric(a).value
        assert value.is_success, (a, value)
        out.append((str(a), value.get()))
    return out


def _run(source, workers, *, engine=None, analyzers=POOL_ANALYZERS,
         **overrides):
    overrides.setdefault("device_cache_bytes", 0)
    overrides.setdefault("batch_size", 512)
    overrides.setdefault("wire_codecs", True)
    overrides.setdefault("dict_deltas", True)
    with config.configure(ingest_workers=workers, **overrides):
        ctx = AnalysisRunner.do_analysis_run(
            Dataset.from_parquet(source)
            if isinstance(source, str)
            else source,
            analyzers,
            engine=engine,
        )
    return _metric_values(ctx, analyzers), ctx


class TestPoolDifferential:
    def test_streaming_bit_identity_and_one_pass(self, pool_parquet):
        tm = get_telemetry()

        def passes(thunk):
            p0 = tm.counter("engine.data_passes").value
            out = thunk()
            return out, tm.counter("engine.data_passes").value - p0

        (ref, _), p1 = passes(lambda: _run(pool_parquet, 1))
        for workers in (2, 4):
            (got, _), pn = passes(lambda: _run(pool_parquet, workers))
            assert got == ref
            assert pn == p1 == 1
        assert active_prefetch_workers() == []

    def test_mesh_bit_identity_with_process_sharded_feed(
        self, pool_parquet, cpu_mesh
    ):
        # single-process identity: the process-sharded feed resolves to
        # make_array_from_process_local_data over the whole batch
        engine = lambda: AnalysisEngine(mesh=cpu_mesh)  # noqa: E731
        ref, _ = _run(pool_parquet, 1, engine=engine(), batch_size=512)
        got, _ = _run(pool_parquet, 4, engine=engine(), batch_size=512)
        assert got == ref
        off, _ = _run(
            pool_parquet, 4, engine=engine(), batch_size=512,
            process_sharded_ingest=False,
        )
        assert off == ref

    def test_resident_path_unaffected(self, pool_parquet):
        ref, _ = _run(pool_parquet, 1, device_cache_bytes=1 << 30)
        got, _ = _run(pool_parquet, 4, device_cache_bytes=1 << 30)
        assert got == ref

    def test_mid_stream_codec_widen_under_concurrency(self, tmp_path):
        # file 0's stats admit i8 for "k"; file 2 violates mid-stream,
        # forcing CodecTable.widen while several batches are in flight
        rng = np.random.default_rng(31)
        for i, hi in enumerate((90, 90, 30_000)):
            n = 800
            pq.write_table(
                pa.table(
                    {
                        "k": pa.array(
                            rng.integers(0, hi, n, dtype=np.int64)
                        ),
                        "f": pa.array(rng.normal(size=n)),
                    }
                ),
                os.path.join(tmp_path, f"part-{i}.parquet"),
            )
        analyzers = [Minimum("k"), Maximum("k"), Mean("k"), Mean("f")]
        ref, _ = _run(
            str(tmp_path), 1, analyzers=analyzers, batch_size=256
        )
        got, _ = _run(
            str(tmp_path), 4, analyzers=analyzers, batch_size=256
        )
        assert got == ref

    def test_dictionary_delta_order_pin(self, pool_parquet):
        # the growing vocabulary must be discovered in FIRST-OCCURRENCE
        # order on both paths: compare the cached end-of-stream
        # dictionaries, not just the metrics (both columns kept so the
        # pooled delta unit forms and deltas are actually cut)
        analyzers = [
            ApproxCountDistinct("s"),
            DataType("s"),
            ApproxCountDistinct("t"),
            DataType("t"),
        ]

        def dictionary_after(workers):
            ds = Dataset.from_parquet(pool_parquet)
            with config.configure(
                device_cache_bytes=0, batch_size=512,
                wire_codecs=True, dict_deltas=True,
                ingest_workers=workers,
            ):
                AnalysisRunner.do_analysis_run(ds, analyzers)
            cached = ds._dictionaries.get("s")
            return None if cached is None else list(cached)

        d1 = dictionary_after(1)
        d4 = dictionary_after(4)
        assert d1 is not None
        assert d4 == d1

    def test_checkpoint_resume_lands_mid_pool(
        self, pool_parquet, tmp_path
    ):
        tm = get_telemetry()
        with config.configure(
            device_cache_bytes=0, batch_size=512,
            scan_retry=FAST_RETRY, checkpoint_every_batches=2,
            ingest_workers=4,
        ):
            ref = _metric_values(
                AnalysisRunner.do_analysis_run(
                    Dataset.from_parquet(pool_parquet), POOL_ANALYZERS,
                    engine=AnalysisEngine(),
                )
            )
            ckpt = ScanCheckpointer(str(tmp_path))
            engine = AnalysisEngine(checkpointer=ckpt)
            ds = FaultInjectingDataset(
                Dataset.from_parquet(pool_parquet), kill_at_batch=5
            )
            resumes_before = tm.counter("engine.resumes").value
            with pytest.raises(ScanKilled):
                AnalysisRunner.do_analysis_run(
                    ds, POOL_ANALYZERS, engine=engine
                )
            assert ckpt._storage.list_keys("scan-ckpt-")
            ctx = AnalysisRunner.do_analysis_run(
                ds, POOL_ANALYZERS, engine=engine
            )
            assert tm.counter("engine.resumes").value - resumes_before == 1
        assert _metric_values(ctx) == ref
        assert active_prefetch_workers() == []

    def test_worker_death_retries_then_matches_oracle(self, pool_parquet):
        tm = get_telemetry()
        with config.configure(
            device_cache_bytes=0, batch_size=512,
            scan_retry=FAST_RETRY, ingest_workers=4,
        ):
            ref = _metric_values(
                AnalysisRunner.do_analysis_run(
                    Dataset.from_parquet(pool_parquet), POOL_ANALYZERS
                )
            )
            retries_before = tm.counter("engine.batch_retries").value
            ds = FaultInjectingDataset(
                Dataset.from_parquet(pool_parquet),
                decode_transient={3: 1},
            )
            ctx = AnalysisRunner.do_analysis_run(ds, POOL_ANALYZERS)
        assert ("decode_transient", 3) in ds.faults_fired
        assert tm.counter("engine.batch_retries").value > retries_before
        assert _metric_values(ctx) == ref

    def test_permanent_worker_fault_quarantines(self, pool_parquet):
        tm = get_telemetry()
        before = tm.counter("engine.batches_quarantined").value
        ds = FaultInjectingDataset(
            Dataset.from_parquet(pool_parquet), decode_permanent={2}
        )
        with config.configure(
            device_cache_bytes=0, batch_size=512,
            scan_retry=FAST_RETRY, ingest_workers=4,
        ):
            ctx = AnalysisRunner.do_analysis_run(ds, POOL_ANALYZERS)
        degr = ctx.degradation
        assert degr is not None and degr.is_degraded
        assert degr.batches_quarantined == 1
        assert tm.counter("engine.batches_quarantined").value - before == 1
        assert ("decode_permanent", 2) in ds.faults_fired
        assert active_prefetch_workers() == []

    def test_pool_emits_telemetry_event(self, pool_parquet):
        with config.configure(
            device_cache_bytes=0, batch_size=512, ingest_workers=4
        ):
            ctx = AnalysisRunner.do_analysis_run(
                Dataset.from_parquet(pool_parquet), POOL_ANALYZERS
            )
        events = [
            e for e in ctx.run_metadata.events
            if e.get("event") == "ingest_pool"
        ]
        assert events, "pool run must emit an ingest_pool event"
        assert events[0]["workers"] == 4
        assert events[0]["released"] > 0

    def test_wrapper_without_declaration_stays_on_legacy_path(
        self, pool_parquet
    ):
        # a plain __getattr__-delegating wrapper does NOT declare
        # supports_parallel_ingest at class level, so the engine must
        # not engage the pool through it (dir() gate), yet metrics
        # still match because the legacy path runs
        class Opaque:
            def __init__(self, inner):
                self._inner = inner

            def __getattr__(self, name):
                return getattr(self._inner, name)

        ref, _ = _run(pool_parquet, 1)
        wrapped = Opaque(Dataset.from_parquet(pool_parquet))
        with config.configure(
            device_cache_bytes=0, batch_size=512, ingest_workers=4
        ):
            ctx = AnalysisRunner.do_analysis_run(wrapped, POOL_ANALYZERS)
        assert _metric_values(ctx) == ref
        assert not any(
            e.get("event") == "ingest_pool"
            for e in ctx.run_metadata.events
        )


# --------------------------------------------------------------------------
# planner twin: ingest_work_items replays device_batches exactly
# --------------------------------------------------------------------------


class TestIngestWorkItems:
    def _requests(self, ds):
        from deequ_tpu.data.table import ColumnRequest

        return [
            ColumnRequest("f", "values"),
            ColumnRequest("x", "values"),
            ColumnRequest("x", "mask"),
            ColumnRequest("s", "codes"),
        ]

    def _drain_items(self, ds, requests, batch_size, start_batch=0):
        out = []
        for item in ds.ingest_work_items(
            requests, batch_size, start_batch=start_batch
        ):
            out.append(item.commit(item.decode()))
        return out

    @staticmethod
    def _assert_batches_equal(got, want):
        assert len(got) == len(want)
        for g, w in zip(got, want):
            assert set(g.keys()) == set(w.keys())
            for key in w:
                gv, wv = g[key], w[key]
                if isinstance(wv, dict):  # dict-delta payloads
                    assert gv["start"] == wv["start"]
                    assert list(gv["values"]) == list(wv["values"])
                else:
                    np.testing.assert_array_equal(gv, wv)

    def test_batches_bit_equal_to_device_batches(self, pool_parquet):
        with config.configure(dict_deltas=True):
            a = ParquetDataset(pool_parquet)
            b = ParquetDataset(pool_parquet)
            requests = self._requests(a)
            want = list(a.device_batches(requests, 512))
            got = self._drain_items(b, requests, 512)
        self._assert_batches_equal(got, want)
        # end-of-stream dictionary caching matches too
        da, db = a._dictionaries.get("s"), b._dictionaries.get("s")
        assert da is not None and db is not None
        assert list(da) == list(db)

    def test_resume_from_start_batch(self, pool_parquet):
        with config.configure(dict_deltas=True):
            a = ParquetDataset(pool_parquet)
            b = ParquetDataset(pool_parquet)
            requests = self._requests(a)
            want = list(a.device_batches(requests, 512, start_batch=3))
            got = self._drain_items(b, requests, 512, start_batch=3)
        self._assert_batches_equal(got, want)


# --------------------------------------------------------------------------
# process-sharded planner (single-host legs)
# --------------------------------------------------------------------------


class TestShardPlanner:
    def test_shard_views_cover_disjointly(self, pool_parquet):
        full = ParquetDataset(pool_parquet)
        views = [full.shard_view(i, 4) for i in range(4)]
        assert sum(v.num_rows for v in views) == full.num_rows
        assert len({v.fingerprint() for v in views}) == 4
        assert all(
            v.fingerprint() != full.fingerprint() for v in views
        )

    def test_shard_row_groups_balance_and_bounds(self, pool_parquet):
        full = ParquetDataset(pool_parquet)
        rows = []
        for i in range(3):
            frags = full.shard_row_groups(i, 3)
            rows.append(
                sum(
                    int(rg.num_rows)
                    for f in frags
                    for rg in f.row_groups
                )
            )
        assert sum(rows) == full.num_rows
        assert min(rows) > 0  # greedy assignment strands no process
        with pytest.raises(ValueError):
            full.shard_row_groups(3, 3)
        with pytest.raises(ValueError):
            full.shard_row_groups(-1, 3)

    def test_sharded_union_matches_full_metrics(self, pool_parquet):
        # scanning each shard and merging states must equal one full
        # scan: Mean is a monoid, so compare count-weighted sums
        full = ParquetDataset(pool_parquet)
        total = 0.0
        count = 0
        for i in range(4):
            view = full.shard_view(i, 4)
            with config.configure(device_cache_bytes=0, batch_size=512):
                ctx = AnalysisRunner.do_analysis_run(
                    view, [Size(), Mean("f")]
                )
            n = ctx.metric(Size()).value.get()
            total += ctx.metric(Mean("f")).value.get() * n
            count += n
        with config.configure(device_cache_bytes=0, batch_size=512):
            ref = AnalysisRunner.do_analysis_run(
                full, [Size(), Mean("f")]
            )
        assert count == ref.metric(Size()).value.get()
        assert total / count == pytest.approx(
            ref.metric(Mean("f")).value.get(), rel=1e-12
        )


# --------------------------------------------------------------------------
# config plumbing
# --------------------------------------------------------------------------


class TestIngestConfig:
    def test_ingest_depth_reaches_prefetcher(self, pool_parquet):
        # depth is a host-pipeline knob: any positive value must give
        # identical metrics (it only changes queue capacity)
        ref, _ = _run(pool_parquet, 1, ingest_depth=1)
        got, _ = _run(pool_parquet, 1, ingest_depth=5)
        assert got == ref

    def test_defaults(self):
        opts = config.options()
        assert opts.ingest_depth >= 1
        assert opts.ingest_workers >= 0
        assert opts.ingest_lookahead >= 0
        assert isinstance(opts.process_sharded_ingest, bool)
