"""Observability compatibility layer over deequ_tpu.telemetry.

Historically this module owned per-pass wall-time metadata and the
jax.profiler hooks; those now live in :mod:`deequ_tpu.telemetry`
(spans, counters, run listeners, JSONL export — docs/OBSERVABILITY.md).
:class:`RunMetadata`/:class:`PassTiming` remain as the stable
result-facing shape (``ctx.run_metadata``), built FROM telemetry run
summaries via :meth:`RunMetadata.from_telemetry_summary`; the context
managers below are thin delegating shims kept for callers of the old
API.
"""

from __future__ import annotations

import contextlib
from dataclasses import dataclass, field
from typing import Iterator, List, Optional


@dataclass
class PassTiming:
    name: str  # "scan" | "grouping" | "direct" | custom
    wall_s: float
    rows: int
    num_analyzers: int

    @property
    def rows_per_sec(self) -> float:
        return self.rows / self.wall_s if self.wall_s > 0 else 0.0


@dataclass
class RunMetadata:
    """Timings for one AnalysisRunner run, plus notable engine events
    (e.g. grouping plans spilling out of the dense device path — a user
    must be able to SEE why a high-card pass got slower)."""

    passes: List[PassTiming] = field(default_factory=list)
    events: List[dict] = field(default_factory=list)

    @property
    def total_wall_s(self) -> float:
        return sum(p.wall_s for p in self.passes)

    def record(
        self, name: str, wall_s: float, rows: int, num_analyzers: int
    ) -> None:
        self.passes.append(PassTiming(name, wall_s, rows, num_analyzers))

    def merge(self, other: Optional["RunMetadata"]) -> "RunMetadata":
        """Always a FRESH instance — never alias a mutable passes list
        between contexts."""
        if other is None:
            return RunMetadata(list(self.passes), list(self.events))
        return RunMetadata(
            self.passes + other.passes, self.events + other.events
        )

    @staticmethod
    def merge_optional(
        a: Optional["RunMetadata"], b: Optional["RunMetadata"]
    ) -> Optional["RunMetadata"]:
        if a is None and b is None:
            return None
        if a is None:
            return b.merge(None)
        return a.merge(b)

    @staticmethod
    def from_telemetry_summary(
        summary: Optional[dict],
    ) -> Optional["RunMetadata"]:
        """The compatibility adapter: rebuild the classic pass/event
        shape from a telemetry run summary (runtime.RunCapture)."""
        if summary is None:
            return None
        metadata = RunMetadata()
        for p in summary.get("passes", []):
            metadata.record(
                p["pass"], p["wall_s"], p["rows"], p["num_analyzers"]
            )
        metadata.events.extend(summary.get("events", []))
        return metadata

    def as_records(self) -> List[dict]:
        return [
            {
                "pass": p.name,
                "wall_s": round(p.wall_s, 6),
                "rows": p.rows,
                "num_analyzers": p.num_analyzers,
                "rows_per_sec": round(p.rows_per_sec, 1),
            }
            for p in self.passes
        ]


@contextlib.contextmanager
def timed_pass(
    metadata: Optional[RunMetadata],
    name: str,
    rows: int,
    num_analyzers: int,
) -> Iterator[None]:
    """Deprecated shim: time a pass through the telemetry layer (span +
    TraceAnnotation + listener callbacks) and record it into
    ``metadata``. Prefer ``get_telemetry().pass_span(...)``."""
    if metadata is None:
        yield
        return
    from deequ_tpu.telemetry import get_telemetry

    with get_telemetry().pass_span(name, rows, num_analyzers) as span:
        yield
    metadata.record(name, span.wall_s, rows, num_analyzers)


@contextlib.contextmanager
def profiler_trace(log_dir: str) -> Iterator[None]:
    """Deprecated shim for :func:`deequ_tpu.telemetry.profiler_trace`."""
    from deequ_tpu.telemetry import profiler_trace as _trace

    with _trace(log_dir):
        yield
