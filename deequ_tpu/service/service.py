"""VerificationService: the always-on, multi-tenant facade.

Composition (docs/SERVICE.md has the architecture picture):

- ``submit()`` validates quotas, wraps the suite in a ``RunTicket``
  (deadline budget pinned at submit — queue wait burns it, matching
  the admission controller), and returns a ``RunHandle``;
- the ``Scheduler``'s workers pop by priority and drive the run
  through ``VerificationSuite.do_verification_run`` — i.e. through
  the runner's admission layer (``max_concurrent_runs`` +
  ``memory_watermark_bytes`` still gate device admission underneath;
  the service NEVER calls ``engine.run_scan`` directly, enforced by
  tools/telemetry_lint.py);
- the shared ``DatasetCache`` hands every run of the same table the
  same resident handle (one ``device_put`` for N tenants), pinned for
  the run's duration;
- ``warmup()`` precompiles the submitted suites' fused plans at
  startup via the ``tools/warmup.py`` machinery and records the warmed
  plan tokens in the ``PlanCache`` ledger, so steady state shows zero
  recompiles.

Shutdown: ``stop(drain=True)`` finishes queued work; ``drain(reason)``
(also wired to SIGTERM when ``start(install_sigterm=True)``) cancels
QUEUED runs cleanly while RUNNING runs finish under the engine's
graceful-shutdown supervision — checkpointed, partial metrics, the
same contract as a direct bounded run.
"""

from __future__ import annotations

import itertools
import threading
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Sequence

from deequ_tpu.engine.deadline import (
    MonotonicClock,
    RunBudget,
    shutdown_token,
)
from deequ_tpu.service.caches import DatasetCache, PlanCache
from deequ_tpu.service.queue import (
    Priority,
    RunHandle,
    RunQueue,
    RunTicket,
)
from deequ_tpu.service.scheduler import Scheduler
from deequ_tpu.telemetry import get_telemetry


@dataclass
class RunRequest:
    """One suite submission. ``dataset_key`` + ``dataset_factory``
    address the shared dataset cache (same key -> same resident
    handle); pass a ``dataset`` directly to bypass sharing (it becomes
    a single-use factory keyed by object id)."""

    tenant: str
    checks: Sequence[Any]
    dataset_key: Optional[str] = None
    dataset_factory: Optional[Callable[[], Any]] = None
    dataset: Optional[Any] = None
    required_analyzers: Sequence[Any] = ()
    priority: int = Priority.STANDARD
    deadline_s: Optional[float] = None
    metrics_repository: Any = None
    result_key: Any = None

    def __post_init__(self):
        if self.dataset is not None and self.dataset_factory is None:
            ds = self.dataset
            self.dataset_factory = lambda: ds
            if self.dataset_key is None:
                self.dataset_key = f"dataset-{id(ds):x}"
        if self.dataset_key is None or self.dataset_factory is None:
            raise ValueError(
                "RunRequest needs dataset_key + dataset_factory "
                "(or a dataset)"
            )


class VerificationService:
    """Long-lived multi-tenant verification daemon. All knobs default
    from ``config.options()`` (service_* options); ``clock`` is
    injectable for fake-time tests and drives every scheduling
    decision."""

    def __init__(
        self,
        workers: Optional[int] = None,
        interactive_reserve: Optional[int] = None,
        clock: Any = None,
        dataset_watermark_bytes: Optional[int] = None,
        tenant_max_pending: Optional[int] = None,
        tenant_max_active: Optional[int] = None,
        execute: Optional[Callable[[RunTicket], Any]] = None,
    ):
        from deequ_tpu import config

        opts = config.options()
        self.clock = clock or MonotonicClock()
        watermark = (
            dataset_watermark_bytes
            if dataset_watermark_bytes is not None
            else (
                opts.service_dataset_watermark_bytes
                or opts.device_cache_bytes
            )
        )
        self.datasets = DatasetCache(watermark_bytes=watermark)
        self.plans = PlanCache()
        self.queue = RunQueue(
            clock=self.clock,
            tenant_max_pending=(
                tenant_max_pending
                if tenant_max_pending is not None
                else opts.service_tenant_max_pending
            ),
            tenant_max_active=(
                tenant_max_active
                if tenant_max_active is not None
                else opts.service_tenant_max_active
            ),
        )
        self.scheduler = Scheduler(
            self.queue,
            execute if execute is not None else self._execute,
            workers=(
                workers if workers is not None else opts.service_workers
            ),
            interactive_reserve=(
                interactive_reserve
                if interactive_reserve is not None
                else opts.service_interactive_reserve
            ),
            clock=self.clock,
        )
        self._run_ids = itertools.count(1)
        self._handles: Dict[str, RunHandle] = {}
        self._handles_lock = threading.Lock()
        self._uninstall_sigterm: Optional[Callable[[], None]] = None
        self._sigterm_watcher: Optional[threading.Thread] = None
        self._watcher_stop = threading.Event()

    # -- lifecycle ------------------------------------------------------

    def start(self, install_sigterm: bool = False) -> "VerificationService":
        if install_sigterm:
            from deequ_tpu.engine.deadline import install_graceful_shutdown

            self._uninstall_sigterm = install_graceful_shutdown()
            self._watcher_stop.clear()
            # lint-ok: thread-discipline: service-scoped watcher joined
            # in stop(); not part of a scan, so the ingest probe (which
            # tier-1 asserts empty between scans) must not see it
            self._sigterm_watcher = threading.Thread(
                target=self._watch_shutdown,
                daemon=True,
                name="deequ-tpu-service-shutdown-watch",
            )
            self._sigterm_watcher.start()
        self.scheduler.start()
        get_telemetry().event(
            "service_started",
            workers=self.scheduler.workers,
            interactive_reserve=self.scheduler.interactive_reserve,
        )
        return self

    def _watch_shutdown(self) -> None:
        token = shutdown_token()
        while not self._watcher_stop.is_set():
            # Event.wait on the token — event-driven, not a time poll;
            # the short timeout only lets a stopped service reclaim the
            # watcher thread
            if token.wait(timeout=0.1):
                self.drain(token.reason or "shutdown requested")
                return

    def stop(
        self, drain: bool = True, timeout: Optional[float] = 30.0
    ) -> None:
        """Shut the service down. ``drain=True`` finishes everything
        already queued first; ``drain=False`` cancels queued runs
        (running ones still finish — workers are cooperative, not
        preemptive)."""
        if drain:
            self.wait_idle(timeout=timeout)
        self.queue.close()
        if not drain:
            self.queue.drain_queued("service stopping")
        self._watcher_stop.set()
        self.scheduler.stop(timeout=timeout)
        if self._uninstall_sigterm is not None:
            self._uninstall_sigterm()
            self._uninstall_sigterm = None
        get_telemetry().event("service_stopped", drained=drain)

    def drain(self, reason: str = "shutdown requested") -> int:
        """SIGTERM semantics: refuse new work, cancel QUEUED runs with
        ``reason``, let RUNNING runs finish under the engine's
        supervision (checkpoint + partial metrics). Returns the number
        of queued runs drained."""
        self.queue.close()
        drained = self.queue.drain_queued(reason)
        get_telemetry().event(
            "service_drained", reason=reason, drained=drained
        )
        return drained

    def wait_idle(self, timeout: Optional[float] = None) -> bool:
        """Block until nothing is queued or running (best-effort;
        returns False on timeout). Poll cadence comes from the clock so
        fake-time tests spin fast."""
        deadline = (
            None if timeout is None else self.clock.now() + timeout
        )
        while True:
            snap = self.queue.snapshot()
            active = sum(snap["active_by_tenant"].values())
            if snap["depth"] == 0 and active == 0:
                return True
            if deadline is not None and self.clock.now() > deadline:
                return False
            self.queue.wait_event(self.clock.queue_poll_s())

    # -- submission -----------------------------------------------------

    def submit(self, request: RunRequest) -> RunHandle:
        """Queue one suite run; returns immediately with the handle.
        Raises ``QuotaExceeded`` when the tenant is over its pending
        quota. The deadline budget starts NOW — time spent queued
        counts against it."""
        run_id = f"run-{next(self._run_ids)}"
        handle = RunHandle(run_id, request.tenant, request.priority)
        budget = None
        if request.deadline_s is not None:
            budget = RunBudget(
                deadline_s=float(request.deadline_s), clock=self.clock
            )
        ticket = RunTicket(
            seq=0,  # assigned by the queue
            handle=handle,
            payload=request,
            budget=budget,
            dataset_key=request.dataset_key,
        )
        tm = get_telemetry()
        self.queue.push(ticket)  # raises QuotaExceeded pre-registration
        with self._handles_lock:
            self._handles[run_id] = handle
        tm.counter("service.submitted").inc()
        tm.counter(f"service.tenant.{request.tenant}.submitted").inc()
        tm.event(
            "service_run_submitted",
            run_id=run_id,
            tenant=request.tenant,
            priority=Priority.name(request.priority),
            dataset_key=request.dataset_key,
            deadline_s=request.deadline_s,
        )
        return handle

    def handle(self, run_id: str) -> Optional[RunHandle]:
        with self._handles_lock:
            return self._handles.get(run_id)

    # -- warmup ---------------------------------------------------------

    def warmup(
        self,
        schema: Dict[str, str],
        suite: bool = True,
        nullable=(False, True),
        **kwargs,
    ) -> List[str]:
        """Precompile the fused plans production suites will need
        (tools/warmup.py machinery) and record the warmed plan tokens.
        Returns the tokens; after this, matching submissions execute
        with zero recompiles (the acceptance telemetry in
        examples/verification_service.py)."""
        warm_plans = _load_warm_plans()
        report = warm_plans(
            schema, suite=suite, nullable=nullable, **kwargs
        )
        self.plans.note_warmed(report.get("tokens", []))
        return list(report.get("tokens", []))

    # -- the real executor ----------------------------------------------

    def _execute(self, ticket: RunTicket):
        from deequ_tpu.verification.suite import VerificationSuite

        request: RunRequest = ticket.payload
        dataset, hit = self.datasets.lease(
            request.dataset_key, request.dataset_factory
        )
        get_telemetry().event(
            "service_dataset_leased",
            run_id=ticket.handle.run_id,
            dataset_key=request.dataset_key,
            cache_hit=hit,
        )
        try:
            result = VerificationSuite.do_verification_run(
                dataset,
                request.checks,
                required_analyzers=request.required_analyzers,
                metrics_repository=request.metrics_repository,
                save_or_append_results_with_key=request.result_key,
                deadline=ticket.budget,
                cancel=ticket.handle.cancel_token,
            )
        finally:
            self.datasets.release(request.dataset_key)
        # per-run plan-cache accounting from the run's own telemetry
        # summary (counter deltas) — recompiles-after-warmup is THE
        # steady-state health signal
        self.plans.record_run(getattr(result, "telemetry", None))
        return result

    # -- introspection --------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        return {
            "queue": self.queue.snapshot(),
            "datasets": self.datasets.snapshot(),
            "plans": self.plans.snapshot(),
        }


def _load_warm_plans():
    """Resolve ``tools.warmup.warm_plans`` without requiring ``tools``
    to be an installed package: try the repo-layout import first, then
    load the module straight off the file next to this package."""
    try:
        from tools.warmup import warm_plans  # type: ignore

        return warm_plans
    except ImportError:
        pass
    import importlib.util
    import os

    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)
        ))),
        "tools",
        "warmup.py",
    )
    spec = importlib.util.spec_from_file_location(
        "deequ_tpu_tools_warmup", path
    )
    if spec is None or spec.loader is None:
        raise ImportError(f"cannot load warmup module from {path}")
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return module.warm_plans
