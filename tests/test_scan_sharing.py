"""Scan-sharing regression: the reference asserts N scan-shareable
analyzers trigger exactly ONE aggregation job by counting Spark jobs
(SparkMonitor; SURVEY.md §4). The TPU equivalent: count compilations of
the fused update — many analyzers, many batches, ONE trace."""

from deequ_tpu.analyzers import (
    AnalysisRunner,
    Completeness,
    Maximum,
    Mean,
    Minimum,
    Size,
    StandardDeviation,
    Sum,
)
from deequ_tpu.engine import AnalysisEngine
from fixtures import big_numeric


def test_one_compile_for_many_analyzers_and_batches():
    engine = AnalysisEngine(batch_size=16_384)  # 100k rows -> 7 batches
    analyzers = [
        Size(),
        Completeness("x"),
        Mean("x"),
        Sum("x"),
        Minimum("x"),
        Maximum("x"),
        StandardDeviation("x"),
        Mean("y"),
        Maximum("y"),
    ]
    context = AnalysisRunner.do_analysis_run(
        big_numeric(), analyzers, engine=engine
    )
    assert all(m.value.is_success for m in context.metric_map.values())
    # ONE fused computation for 9 analyzers over 7 batches
    assert engine.trace_count == 1


def test_batched_equals_single_batch():
    data = big_numeric()
    analyzers = [Mean("x"), StandardDeviation("x"), Minimum("x"), Sum("y")]
    ctx_one = AnalysisRunner.do_analysis_run(
        data, analyzers, engine=AnalysisEngine()
    )
    ctx_many = AnalysisRunner.do_analysis_run(
        data, analyzers, engine=AnalysisEngine(batch_size=4_096)
    )
    for analyzer in analyzers:
        a = ctx_one.metric(analyzer).value.get()
        b = ctx_many.metric(analyzer).value.get()
        assert abs(a - b) < 1e-8 * max(1.0, abs(a)), analyzer
