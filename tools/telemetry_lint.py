"""Compat shim: the telemetry/OOM/sync/service token rules now live in
``tools/staticcheck`` (docs/STATIC_ANALYSIS.md) — one framework, one
waiver syntax, one gate.

This module keeps the historical surface alive unchanged:

- ``find_violations(root)`` returns the same ``(relpath, line, token)``
  tuples tests and scripts have always consumed, now rebuilt from the
  framework's findings for the five migrated rule families
  (``telemetry-timing``, ``oom-taxonomy``, ``sync-discipline``,
  ``service-time``, ``service-admission`` — plus ``tokenize-error``,
  which restores the old ``(rel, 0, "<tokenize error>")`` tuple that a
  typo'd ``except tokenize.TokenizeError`` clause had turned into an
  AttributeError: the real exception is ``tokenize.TokenError``).
- ``python -m tools.telemetry_lint [root]`` still prints one line per
  violation and exits non-zero when any exist.

New callers should use ``python -m tools.staticcheck`` directly; it
runs these rules AND the AST analyzers (locks, interrupts, trace,
plan-key) behind the same ``# lint-ok:`` waiver syntax.
"""

from __future__ import annotations

import os
import sys
from typing import List, Optional, Tuple

from tools.staticcheck import run_analyzers, unwaived
from tools.staticcheck.tokens import TokenDisciplineAnalyzer

#: the migrated rule families this shim reports on
TOKEN_RULES: Tuple[str, ...] = TokenDisciplineAnalyzer.rules


def find_violations(root: str) -> List[Tuple[str, int, str]]:
    """(relpath, line, token) for every unwaived token-rule finding —
    the historical tuple API, served by the staticcheck framework."""
    findings = unwaived(run_analyzers(root, rules=list(TOKEN_RULES)))
    return [(f.path, f.line, f.symbol) for f in findings]


def main(argv: Optional[List[str]] = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    root = argv[0] if argv else os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))
    )
    violations = find_violations(root)
    for rel, line, token in violations:
        print(f"{rel}:{line}: forbidden hot-path token {token}")
    if violations:
        print(
            f"{len(violations)} violation(s): timing/tracing belongs in "
            "the telemetry layer (docs/OBSERVABILITY.md); engine syncs "
            "belong in the packed epilogue (engine/pack.py) or need a "
            "'# sync-ok:' waiver. Full suite: python -m tools.staticcheck"
        )
        return 1
    print("telemetry lint clean (via tools.staticcheck)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
