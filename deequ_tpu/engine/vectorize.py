"""Vectorizing scan planner: stack same-kind columns into (C, B) ops.

The fused scan (engine/scan.py) makes N analyzers cost ONE data pass,
but each analyzer still lowers its own masked reduction: a 50-column
profile emits hundreds of HLO reduce/scatter ops, which bloats XLA
compile time (the dominant cold-start cost for big plans) and leaves
per-kernel overhead on the table.

This planner groups analyzers of the same FAMILY over columns of the
same device dtype and the same ``where`` filter into one stacked op:

- ``stats``        — Mean/Sum/Minimum/Maximum/StandardDeviation (values)
                     and MinLength/MaxLength (lengths): one (C, B)
                     masked reduction per needed statistic, Welford/Chan
                     vectorized over the column axis;
- ``completeness`` — Completeness: one (C, B) mask count;
- ``hll``          — ApproxCountDistinct: hashes computed on the stacked
                     block, registers updated with ONE scatter-max into
                     a (C*M,) vector;
- ``datatype``     — DataType over string columns: stacked code->bucket
                     LUT gather + one scatter-add.

Group states hold (C,)-shaped leaves; after the scan each member
analyzer's ordinary state (states.py types) is SLICED back out, so
metric finalization, state persistence, and incremental merge are
unchanged. Numerics mirror the scalar paths in analyzers/basic.py
exactly (same masked-neutral elements, same dtype widenings, same
Welford/Chan batch merge) — only the reduction batching differs.

Reference analog: deequ fuses analyzers into one ``df.agg`` but leaves
per-expression evaluation to Tungsten (SURVEY.md §2.2); stacking is the
TPU-shaped version of that fusion, feeding the VPU 8x32-lane grid full
columns-by-rows tiles instead of one row stream per expression.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deequ_tpu import config
from deequ_tpu.analyzers import states as S
from deequ_tpu.analyzers.base import ScanOps, pad_pow2
from deequ_tpu.analyzers.basic import (
    _compile_where,
    _row_mask,
    _acc_float,
)
from deequ_tpu.data.table import ColumnRequest, Dataset, Kind
from deequ_tpu.sketches import hll

_F64 = jnp.float64


@dataclass
class ScanUnit:
    """One engine slot: either a single analyzer's ops or a vectorized
    group. ``extract(state, member_index)`` slices a member's ordinary
    state out of a group state (None for singles)."""

    members: List[Any]  # analyzers, in column order
    ops: ScanOps
    requests: List[ColumnRequest]
    extract: Optional[Callable[[Any, int], Any]] = None

    # engine adapter: run_scan asks analyzers for device_requests
    def device_requests(self, dataset: Dataset) -> List[ColumnRequest]:
        return self.requests


def _index_members(members: Sequence[Any]) -> Tuple[List[str], List[int]]:
    """Dedup member columns preserving order; returns (columns,
    member->column-index map)."""
    columns: List[str] = []
    col_index: Dict[str, int] = {}
    for a in members:
        if a.column not in col_index:
            col_index[a.column] = len(columns)
            columns.append(a.column)
    return columns, [col_index[a.column] for a in members]


def _stack_luts(luts: List[np.ndarray], fill=0) -> np.ndarray:
    """Stack per-column LUTs into one (C, L) const, padding every LUT to
    the group max and then to a power of two (see base.pad_pow2)."""
    width = max(len(lut) for lut in luts)
    return np.stack(
        [
            pad_pow2(
                np.pad(lut, (0, width - len(lut)), constant_values=fill),
                fill,
            )
            for lut in luts
        ]
    )


def _delta_cap_of(dataset: Dataset, columns: List[str]) -> Optional[int]:
    """The group's delta-LUT capacity when EVERY member column ships
    one-pass dictionary deltas (data/parquet.py dict_delta_capacity),
    else None — the builders then keep the consts-LUT form. Consulting
    the dataset COMMITS the columns to delta mode, so the decision here
    and the dataset's device_batches behavior can never diverge."""
    cap_fn = getattr(dataset, "dict_delta_capacity", None)
    if cap_fn is None:
        return None
    caps = [cap_fn(c) for c in columns]
    if not caps or any(cap is None for cap in caps):
        return None
    return int(max(caps))


def _set_lut_row(lut, i: int, row: np.ndarray):
    """Overwrite row ``i`` of a (C, L) LUT state leaf, host numpy or
    device array alike (host_delta runs outside jit: numpy before the
    first dispatch, a committed device array after)."""
    if isinstance(lut, np.ndarray):
        out = lut.copy()
        out[i, :] = row
        return out
    # lint-ok: sync-discipline: converts the HOST numpy mirror row for
    # a .at[].set update; no device fetch happens here
    return lut.at[i].set(np.asarray(row))


def _delta_overflow(column: str, needed: int, cap: int) -> RuntimeError:
    return RuntimeError(
        f"dictionary for column {column!r} grew to {needed} values, "
        f"past dict_delta_capacity={cap}: raise "
        "DEEQU_TPU_DICT_DELTA_CAPACITY or set dict_deltas=False to "
        "fall back to the pre-pass consts path"
    )


# --------------------------------------------------------------------------
# shared per-batch prologue (cross-unit stack/sort memoization)
# --------------------------------------------------------------------------
#
# Every unit in a fused step receives the SAME batch dict object — the
# engine (scan.py fused_update / the resident scan body) builds it once
# per trace and hands it to each op in turn — so the first unit to need
# a stacked (C, B) block (or the KLL group's masked f32 sort, or the
# where-filter row mask) stores the traced value back into the dict
# under a reserved key and later units reuse it. Relying on XLA HLO CSE
# for this worked for the sort (two structurally identical subgraphs)
# but NOT for the per-family stacks and row masks, whose operand sets
# differ across groups; at 40 columns the repeated prologue work was a
# measured slice of the 2.4x in-engine KLL overhead (docs/PERF.md "KLL
# unit decomposition"). The reserved prefix can never collide with wire
# keys ("col::repr", "__buf_*", "__row_width__"), and the memo entries
# live only for the duration of one trace (the dict dies with it).

_SHARED_PREFIX = "__shared__:"


def _shared_stack(batch, columns, suffix):
    """Memoized ``jnp.stack([batch[f"{c}::{suffix}"] ...])``: one stack
    per (column tuple, repr) per fused step, shared across units."""
    key = _SHARED_PREFIX + suffix + ":" + "\x1f".join(columns)
    out = batch.get(key)
    if out is None:
        out = jnp.stack([batch[f"{c}::{suffix}"] for c in columns])
        batch[key] = out
    return out


def _shared_rows(batch, where_fn, where):
    """Memoized ``_row_mask``: one row-validity vector per (batch,
    where-expression) — every group with the same filter reuses it."""
    key = _SHARED_PREFIX + "rows:" + repr(where)
    out = batch.get(key)
    if out is None:
        out = _row_mask(batch, where_fn)
        batch[key] = out
    return out


def _where_ok_for_token(where: Optional[str], dataset: Dataset) -> bool:
    if where is None:
        return True
    from deequ_tpu.sql.predicate import compile_predicate

    return compile_predicate(where, dataset).dataset_independent


def _group_token(
    family: str,
    dataset: Dataset,
    columns: Sequence[str],
    where: Optional[str],
    extra: Tuple = (),
) -> Optional[tuple]:
    if not _where_ok_for_token(where, dataset):
        return None
    kinds = tuple(
        (c, dataset.schema.kind_of(c).value) for c in columns
    )
    return ("vec", family, kinds, where, extra)


# --------------------------------------------------------------------------
# stats family
# --------------------------------------------------------------------------

_STATS_NEED = {
    "Mean": ("sum",),
    "Sum": ("sum",),
    "Minimum": ("min",),
    "Maximum": ("max",),
    "MinLength": ("min",),
    "MaxLength": ("max",),
    "StandardDeviation": ("sum", "welford"),
}


def _build_stats_group(
    dataset: Dataset,
    members: List[Any],
    repr_name: str,
    where: Optional[str],
) -> ScanUnit:
    """members: stats analyzers sharing (repr, value dtype, where)."""
    columns, member_cols = _index_members(members)
    needs = set()
    for a in members:
        needs.update(_STATS_NEED[type(a).__name__])
    where_fn, where_reqs = _compile_where(where, dataset)
    requests = [
        r
        for c in columns
        for r in (ColumnRequest(c, repr_name), ColumnRequest(c, "mask"))
    ] + where_reqs
    C = len(columns)
    acc = _acc_float()
    is_float = np.issubdtype(
        dataset.request_dtype(ColumnRequest(columns[0], repr_name)),
        np.floating,
    )

    def init():
        state = {"n": np.zeros(C, dtype=np.int64)}
        if "sum" in needs:
            state["sum"] = np.zeros(C, dtype=np.dtype(acc))
        if "min" in needs:  # NaN = nan_largest_min identity (states.py)
            state["min"] = np.full(C, np.nan, dtype=np.float64)
        if "max" in needs:
            state["max"] = np.full(C, -np.inf, dtype=np.float64)
        if "welford" in needs:
            state["w"] = S.StandardDeviationState(
                np.zeros(C), np.zeros(C), np.zeros(C)
            )
        return state

    def update(state, batch):
        x = _shared_stack(batch, columns, repr_name)
        masks = _shared_stack(batch, columns, "mask")
        masks = masks & _shared_rows(batch, where_fn, where)[None, :]
        new = dict(state)
        n_b = jnp.sum(masks, axis=1, dtype=jnp.int32).astype(jnp.int64)
        new["n"] = state["n"] + n_b
        sum_b = None
        if "sum" in needs:
            # mirrors basic._msum: float columns reduce in native dtype
            # (scalar cast to acc after); integrals widen per element to
            # f64 for exactness regardless of the accumulation knob
            if is_float:
                sum_b = jnp.sum(
                    jnp.where(masks, x, jnp.zeros((), x.dtype)), axis=1
                ).astype(acc)
            else:
                sum_b = jnp.sum(
                    jnp.where(masks, x, 0).astype(_F64), axis=1
                ).astype(acc)
            new["sum"] = state["sum"] + sum_b
        if "min" in needs:  # mirrors basic._mmin (NaN-largest ordering)
            from deequ_tpu.analyzers.basic import _mmin

            new["min"] = S.nan_largest_min(
                state["min"], _mmin(x, masks, axis=1)
            )
        if "max" in needs:  # mirrors basic._mmax
            neutral = (
                jnp.array(-jnp.inf, x.dtype)
                if is_float
                else jnp.array(jnp.iinfo(x.dtype).min, x.dtype)
            )
            new["max"] = jnp.maximum(
                state["max"],
                jnp.max(jnp.where(masks, x, neutral), axis=1).astype(_F64),
            )
        if "welford" in needs:
            # mirrors StandardDeviation.make_ops batch-state + Chan
            # merge, vectorized over the column axis
            xw = x if is_float else x.astype(_F64)
            nb_f = n_b.astype(_F64)
            safe_nb = jnp.maximum(nb_f, 1.0)
            mean_b = sum_b.astype(_F64) / safe_nb
            dx = jnp.where(
                masks, xw - mean_b.astype(xw.dtype)[:, None], 0
            )
            m2_b = jnp.sum(dx * dx, axis=1).astype(_F64)
            batch_state = S.StandardDeviationState(
                nb_f,
                jnp.where(nb_f > 0, mean_b, 0.0),
                jnp.where(nb_f > 0, m2_b, 0.0),
            )
            new["w"] = S.StandardDeviationState.merge(
                state["w"], batch_state
            )
        return new

    def merge(a, b):
        out = {"n": a["n"] + b["n"]}
        if "sum" in needs:
            out["sum"] = a["sum"] + b["sum"]
        if "min" in needs:
            out["min"] = S.nan_largest_min(a["min"], b["min"])
        if "max" in needs:
            out["max"] = jnp.maximum(a["max"], b["max"])
        if "welford" in needs:
            out["w"] = S.StandardDeviationState.merge(a["w"], b["w"])
        return out

    def extract(state, member_idx: int):
        i = member_cols[member_idx]
        a = members[member_idx]
        name = type(a).__name__
        n = state["n"][i]
        if name in ("Mean",):
            return S.MeanState(state["sum"][i], n)
        if name in ("Sum",):
            return S.SumState(state["sum"][i], n)
        if name in ("Minimum", "MinLength"):
            return S.MinState(state["min"][i], n)
        if name in ("Maximum", "MaxLength"):
            return S.MaxState(state["max"][i], n)
        w = state["w"]
        return S.StandardDeviationState(w.n[i], w.avg[i], w.m2[i])

    token = _group_token(
        "stats",
        dataset,
        columns,
        where,
        extra=(repr_name, tuple(sorted(needs)), "f" if is_float else "i"),
    )
    return ScanUnit(
        members,
        ScanOps(init, update, merge, cache_token=token),
        requests,
        extract,
    )


# --------------------------------------------------------------------------
# completeness family
# --------------------------------------------------------------------------


def _build_completeness_group(
    dataset: Dataset, members: List[Any], where: Optional[str]
) -> ScanUnit:
    columns, member_cols = _index_members(members)
    where_fn, where_reqs = _compile_where(where, dataset)
    requests = [ColumnRequest(c, "mask") for c in columns] + where_reqs
    C = len(columns)

    def init():
        return {
            "matches": np.zeros(C, dtype=np.int64),
            "rows": np.int64(0),
        }

    def update(state, batch):
        rows = _shared_rows(batch, where_fn, where)
        masks = _shared_stack(batch, columns, "mask")
        valid = masks & rows[None, :]
        return {
            "matches": state["matches"]
            + jnp.sum(valid, axis=1, dtype=jnp.int32).astype(jnp.int64),
            "rows": state["rows"]
            + jnp.sum(rows, dtype=jnp.int32).astype(jnp.int64),
        }

    def merge(a, b):
        return {
            "matches": a["matches"] + b["matches"],
            "rows": a["rows"] + b["rows"],
        }

    def extract(state, member_idx: int):
        return S.NumMatchesAndCount(
            state["matches"][member_cols[member_idx]], state["rows"]
        )

    token = _group_token("completeness", dataset, columns, where)
    return ScanUnit(
        members,
        ScanOps(init, update, merge, cache_token=token),
        requests,
        extract,
    )


# --------------------------------------------------------------------------
# hll family
# --------------------------------------------------------------------------


def _build_hll_group(
    dataset: Dataset,
    members: List[Any],
    value_repr: str,  # "values" (numeric) | "codes" (string)
    where: Optional[str],
    kll_pool_columns: Optional[Tuple[str, ...]] = None,
    runtime_gate_columns: Optional[Tuple[str, ...]] = None,
) -> ScanUnit:
    """``kll_pool_columns``: when a KLL group with the same ``where``
    shares the scan and covers this group's (f32-storage) columns, the
    planner passes the KLL group's column order — the update then
    reuses the KLL sort via the SAME memoized _kll_sorted_stack (one
    sort per step, shared through the batch dict) and every statically
    qualified column takes the sorted-dedup register builder
    unconditionally: mid-cardinality columns win from batch 1,
    high-cardinality ones pay only the unique-count probe.

    ``runtime_gate_columns``: the widened gate (config
    .hll_dedup_widening) — pooled integer columns whose O(1) range
    probe could NOT statically prove them; they dispatch per batch on
    the carried-register cardinality estimate plus an in-kernel f32
    mantissa-exactness check (sketches/hll.py
    gated_column_registers_from_sorted), falling back to the plain
    scatter whenever either check — or the inner U<=D probe — says
    no."""
    columns, member_cols = _index_members(members)
    where_fn, where_reqs = _compile_where(where, dataset)
    requests = [
        r
        for c in columns
        for r in (ColumnRequest(c, value_repr), ColumnRequest(c, "mask"))
    ] + where_reqs
    if kll_pool_columns:
        # the pooled sort reads EVERY kll column: request them so the
        # batch stays complete even if the kll unit itself degrades
        requests += [
            r
            for c in kll_pool_columns
            for r in (ColumnRequest(c, "values"), ColumnRequest(c, "mask"))
        ]
    C = len(columns)

    consts = None
    host_delta = None
    delta_cap = None
    if value_repr == "codes":
        # one-pass dictionary deltas: when every member column ships
        # deltas, the hash LUTs move from consts into STATE at a fixed
        # (C, cap) shape and host_delta folds each delta's hash pairs
        # in as it arrives — no dictionary pre-pass at build time
        delta_cap = _delta_cap_of(dataset, columns)
        if delta_cap is None:
            luts1, luts2 = [], []
            for c in columns:
                h1, h2 = hll.dictionary_hash_pairs(dataset.dictionary(c))
                luts1.append(h1)
                luts2.append(h2)
            consts = {"h1": _stack_luts(luts1), "h2": _stack_luts(luts2)}

    def init():
        if delta_cap is not None:
            return {
                "registers": np.zeros((C, hll.M), dtype=np.int8),
                "h1": np.zeros((C, delta_cap), dtype=np.uint32),
                "h2": np.zeros((C, delta_cap), dtype=np.uint32),
            }
        return S.ApproxCountDistinctState(
            np.zeros((C, hll.M), dtype=np.int8)
        )

    def update(state, batch, consts_in=None):
        registers = (
            state["registers"] if delta_cap is not None else state.registers
        )
        masks = _shared_stack(batch, columns, "mask")
        masks = masks & _shared_rows(batch, where_fn, where)[None, :]
        if value_repr == "codes":
            codes = _shared_stack(batch, columns, "codes").astype(
                jnp.int32
            )
            if delta_cap is not None:
                lut1, lut2 = state["h1"], state["h2"]
            else:
                lut1, lut2 = consts_in["h1"], consts_in["h2"]
            if lut1.shape[1] <= hll.PRESENCE_DICT_CAP:
                # small dictionaries: presence compare-reduce + one
                # D-element scatter — bit-identical registers, no
                # per-row scatter (the measured throughput wall)
                regs = hll.registers_from_code_presence(
                    codes, masks, lut1, lut2
                )
            else:
                codes = jnp.clip(codes, 0, lut1.shape[1] - 1)
                h1 = jnp.take_along_axis(lut1, codes, axis=1)
                h2 = jnp.take_along_axis(lut2, codes, axis=1)
                regs = hll.registers_from_hash_pair_stacked(
                    h1, h2, masks
                )
        elif kll_pool_columns:
            # reuse the KLL group's sort through the shared-batch memo
            # (one sort per step for both units, no CSE reliance)
            sorted_all, _, _ = _kll_sorted_stack(
                batch, kll_pool_columns, where_fn, where
            )
            row_of = {c: i for i, c in enumerate(kll_pool_columns)}
            gated = frozenset(runtime_gate_columns or ())
            regs = jnp.stack(
                [
                    hll.gated_column_registers_from_sorted(
                        sorted_all[row_of[c]],
                        batch[f"{c}::values"],
                        masks[i],
                        registers[i],
                    )
                    if c in gated
                    else hll.dedup_column_registers_from_sorted(
                        sorted_all[row_of[c]],
                        batch[f"{c}::values"],
                        masks[i],
                    )
                    for i, c in enumerate(columns)
                ]
            )
        else:
            x = _shared_stack(batch, columns, "values")
            # adaptive: sorted-dedup for mid-cardinality groups (gated
            # by the carried registers), full scatter otherwise
            regs = hll.numeric_registers_adaptive(
                x, masks, registers
            )
        new_regs = jnp.maximum(registers, regs)
        if delta_cap is not None:
            return {
                "registers": new_regs,
                "h1": state["h1"],
                "h2": state["h2"],
            }
        return S.ApproxCountDistinctState(new_regs)

    def extract(state, member_idx: int):
        regs = (
            state["registers"] if delta_cap is not None else state.registers
        )
        return S.ApproxCountDistinctState(regs[member_cols[member_idx]])

    if delta_cap is not None:
        col_index = {c: i for i, c in enumerate(columns)}
        # host mirrors of the hash LUT rows: deltas append into these,
        # then ONE row overwrite lands in the device state — the mirror
        # is what lets a delta be an append instead of a re-hash
        mirrors = {
            c: (
                np.zeros(delta_cap, dtype=np.uint32),
                np.zeros(delta_cap, dtype=np.uint32),
            )
            for c in columns
        }

        def merge(a, b):
            # registers are the real monoid; the LUT leaves follow the
            # same dictionary progression in every shard, so a
            # commutative maximum is an identity-preserving merge
            return {
                "registers": jnp.maximum(a["registers"], b["registers"]),
                "h1": jnp.maximum(a["h1"], b["h1"]),
                "h2": jnp.maximum(a["h2"], b["h2"]),
            }

        def host_delta(state, deltas):
            from deequ_tpu.analyzers.base import DELTA_PRIME

            if deltas is DELTA_PRIME:
                items = [(c, 0, dataset.dictionary(c)) for c in columns]
            else:
                items = [
                    (c, d["start"], d["values"])
                    for c, d in deltas.items()
                    if c in col_index
                ]
            if not items:
                return state
            h1s, h2s = state["h1"], state["h2"]
            for c, start, values in items:
                n = len(values)
                if start + n > delta_cap:
                    raise _delta_overflow(c, start + n, delta_cap)
                m1, m2 = mirrors[c]
                if start == 0:  # full (re)ship: reset the mirror
                    m1[:] = 0
                    m2[:] = 0
                p1, p2 = hll.dictionary_hash_pairs(
                    # lint-ok: sync-discipline: delta VALUES are host
                    # numpy strings off the parquet reader, not device
                    np.asarray(values, dtype=object)
                )
                m1[start:start + n] = p1
                m2[start:start + n] = p2
                h1s = _set_lut_row(h1s, col_index[c], m1)
                h2s = _set_lut_row(h2s, col_index[c], m2)
            return {"registers": state["registers"], "h1": h1s, "h2": h2s}

    else:
        merge = S.ApproxCountDistinctState.merge

    token = _group_token(
        "hll",
        dataset,
        columns,
        where,
        extra=(value_repr, kll_pool_columns, runtime_gate_columns,
               delta_cap),
    )
    return ScanUnit(
        members,
        ScanOps(
            init,
            update,
            merge,
            consts=consts,
            cache_token=token,
            host_delta=host_delta,
        ),
        requests,
        extract,
    )


# --------------------------------------------------------------------------
# kll family (host-folded quantile sketches)
# --------------------------------------------------------------------------


def _kll_sorted_stack(batch, columns, where_fn, where=None):
    """THE one construction of the KLL group's masked f32 sort — also
    consumed by the HLL sorted-dedup path when both units share a scan.
    Memoized into the shared batch dict (keyed by columns + where), so
    the two units PROVABLY run one sort per step — previously both
    emitted the construction and XLA HLO CSE was trusted to merge the
    structurally identical subgraphs, which held only while nothing
    perturbed either copy. Returns (sorted_x (C, B), masks, x)."""
    key = (
        _SHARED_PREFIX
        + "kllsort:"
        + repr(where)
        + ":"
        + "\x1f".join(columns)
    )
    hit = batch.get(key)
    if hit is not None:
        return hit
    masks = _shared_stack(batch, columns, "mask")
    masks = masks & _shared_rows(batch, where_fn, where)[None, :]
    x = jnp.stack(
        [batch[f"{c}::values"].astype(jnp.float32) for c in columns]
    )
    masks = masks & jnp.isfinite(x)
    sorted_x = jnp.sort(jnp.where(masks, x, jnp.inf), axis=1)
    out = (sorted_x, masks, x)
    batch[key] = out
    return out


def _build_kll_group(
    dataset: Dataset, members: List[Any], where: Optional[str]
) -> ScanUnit:
    """KLLSketch/ApproxQuantile/ApproxQuantiles sharing (params, where):
    ONE batched (C, B) sort + strided sampling per scan step instead of
    C independent sorts; the host folds each column's samples into its
    compactor hierarchy. Analyzers over the SAME column share one
    sketch (kll_profiling runs KLLSketch + ApproxQuantiles per column —
    the sort and the sketch are computed once)."""
    from deequ_tpu.sketches.hll import fmix32
    from deequ_tpu.sketches.kll import KLLSketchState

    params = members[0].params
    columns, member_cols = _index_members(members)
    where_fn, where_reqs = _compile_where(where, dataset)
    requests = [
        r
        for c in columns
        for r in (ColumnRequest(c, "values"), ColumnRequest(c, "mask"))
    ] + where_reqs
    C = len(columns)
    k = params.sketch_size

    def init():
        # per-batch output slot (overwritten each batch, not a carry)
        return (
            np.zeros((C, k), dtype=np.float32),  # samples
            np.zeros((C, k), dtype=bool),  # sample validity
            np.zeros(C, dtype=np.int64),  # valid counts
            np.full(C, np.inf, dtype=np.float32),  # min
            np.full(C, -np.inf, dtype=np.float32),  # max
            np.zeros(C, dtype=np.int32),  # compaction level
        )

    def update(_state, batch):
        # mirrors analyzers/kll._make_kll_ops exactly, vectorized over
        # the column axis; the device kernel stays in f32/u32 lanes
        sorted_x, masks, x = _kll_sorted_stack(
            batch, columns, where_fn, where
        )
        B = x.shape[1]
        nv = jnp.sum(masks, axis=1, dtype=jnp.int64)
        q = ((nv + k - 1) // k).astype(jnp.uint32)
        level = jnp.where(
            q > 1, 32 - jax.lax.clz(jnp.maximum(q - 1, 1)), 0
        ).astype(jnp.int32)
        stride = jnp.int64(1) << level.astype(jnp.int64)
        bits = jax.lax.bitcast_convert_type(sorted_x[:, 0], jnp.uint32)
        seed = fmix32(nv.astype(jnp.uint32) ^ bits)
        offset = seed.astype(jnp.int64) & (stride - 1)
        idx = offset[:, None] + jnp.arange(k, dtype=jnp.int64)[None, :] * (
            stride[:, None]
        )
        valid = idx < nv[:, None]
        samples = jnp.take_along_axis(
            sorted_x, jnp.clip(idx, 0, B - 1), axis=1
        )
        mn = jnp.min(jnp.where(masks, x, jnp.inf), axis=1)
        mx = jnp.max(jnp.where(masks, x, -jnp.inf), axis=1)
        return (samples, valid, nv, mn, mx, level)

    def host_init():
        return [KLLSketchState(params) for _ in range(C)]

    def host_fold(accs, out):
        samples, valid, nv, mn, mx, level = out
        # one host-side conversion for the whole (C, k) block; valid
        # samples are finite by construction (the device kernel masks
        # non-finite values into the +inf sentinel and invalidates
        # those slots), so the per-column isfinite net is skipped
        samples = np.asarray(samples)  # sync-ok: host fold runs on
        valid = np.asarray(valid)  # sync-ok: already-fetched numpy
        # (the packed epilogue fetched the whole block)
        for i in range(C):
            accs[i].add_pre_compacted(
                samples[i][valid[i]],
                int(level[i]),
                int(nv[i]),
                float(mn[i]),
                float(mx[i]),
                assume_finite=True,
            )
        return accs

    def merge(a, b):  # per-column sketch merge (incremental/mesh path)
        return [KLLSketchState.merge(x, y) for x, y in zip(a, b)]

    def extract(accs, member_idx: int):
        return accs[member_cols[member_idx]]

    token = _group_token(
        "kll",
        dataset,
        columns,
        where,
        extra=(k, params.shrinking_factor),
    )
    return ScanUnit(
        members,
        ScanOps(
            init,
            update,
            merge,
            host_init=host_init,
            host_fold=host_fold,
            cache_token=token,
        ),
        requests,
        extract,
    )


# --------------------------------------------------------------------------
# datatype family (string columns only)
# --------------------------------------------------------------------------


def _build_datatype_group(
    dataset: Dataset, members: List[Any], where: Optional[str]
) -> ScanUnit:
    from deequ_tpu.analyzers.datatype import classify_string

    columns, member_cols = _index_members(members)
    where_fn, where_reqs = _compile_where(where, dataset)
    requests = [
        r
        for c in columns
        for r in (ColumnRequest(c, "codes"), ColumnRequest(c, "mask"))
    ] + where_reqs
    C = len(columns)

    def _classify(value) -> int:
        return (
            S.DataTypeHistogram.NULL
            if value is None
            else classify_string(str(value))
        )

    consts = None
    host_delta = None
    # one-pass dictionary deltas: bucket LUT in STATE, classified
    # incrementally from each delta's values (see _build_hll_group)
    delta_cap = _delta_cap_of(dataset, columns)
    if delta_cap is None:
        luts = []
        for c in columns:
            dictionary = dataset.dictionary(c)
            lut = np.zeros(max(len(dictionary), 1), dtype=np.int32)
            for i, value in enumerate(dictionary):
                lut[i] = _classify(value)
            luts.append(lut)
        consts = {"lut": _stack_luts(luts, S.DataTypeHistogram.STRING)}

    def init():
        state = {"counts": np.zeros((C, 6), dtype=np.int64)}
        if delta_cap is not None:
            # padding classifies as STRING like the consts path; rows
            # beyond the shipped dictionary are never indexed by a
            # valid code, so the fill never reaches a count
            state["lut"] = np.full(
                (C, delta_cap),
                S.DataTypeHistogram.STRING,
                dtype=np.int32,
            )
        return state

    def update(state, batch, consts_in=None):
        from deequ_tpu.sketches.hll import PRESENCE_DICT_CAP

        table = (
            state["lut"] if delta_cap is not None else consts_in["lut"]
        )
        rows = _shared_rows(batch, where_fn, where)
        masks = _shared_stack(batch, columns, "mask")
        valid = masks & rows[None, :]
        codes = _shared_stack(batch, columns, "codes").astype(jnp.int32)
        if table.shape[1] <= PRESENCE_DICT_CAP:
            # shared single-source implementation — see
            # analyzers/datatype.py counts_from_code_presence
            from deequ_tpu.analyzers.datatype import (
                counts_from_code_presence,
            )

            counts = counts_from_code_presence(codes, valid, rows, table)
        else:
            codes = jnp.clip(codes, 0, table.shape[1] - 1)
            bucket = jnp.take_along_axis(table, codes, axis=1)
            bucket = jnp.where(valid, bucket, S.DataTypeHistogram.NULL)
            bucket = jnp.where(rows[None, :], bucket, 6)  # padding slot
            col_ids = jax.lax.broadcasted_iota(
                jnp.int32, bucket.shape, 0
            )
            flat = (col_ids * 8 + bucket).ravel()
            counts = (
                jnp.zeros(C * 8, dtype=jnp.int32)
                .at[flat]
                .add(1)
                .reshape(C, 8)[:, :6]
            )
        new_counts = state["counts"] + counts.astype(jnp.int64)
        if delta_cap is not None:
            return {"counts": new_counts, "lut": state["lut"]}
        return {"counts": new_counts}

    def merge(a, b):
        out = {"counts": a["counts"] + b["counts"]}
        if delta_cap is not None:
            # every shard follows the same dictionary progression, so
            # maximum preserves the (identical) LUTs
            out["lut"] = jnp.maximum(a["lut"], b["lut"])
        return out

    def extract(state, member_idx: int):
        return S.DataTypeHistogram(
            state["counts"][member_cols[member_idx]]
        )

    if delta_cap is not None:
        col_index = {c: i for i, c in enumerate(columns)}
        mirrors = {
            c: np.full(
                delta_cap, S.DataTypeHistogram.STRING, dtype=np.int32
            )
            for c in columns
        }

        def host_delta(state, deltas):
            from deequ_tpu.analyzers.base import DELTA_PRIME

            if deltas is DELTA_PRIME:
                items = [(c, 0, dataset.dictionary(c)) for c in columns]
            else:
                items = [
                    (c, d["start"], d["values"])
                    for c, d in deltas.items()
                    if c in col_index
                ]
            if not items:
                return state
            lut = state["lut"]
            for c, start, values in items:
                n = len(values)
                if start + n > delta_cap:
                    raise _delta_overflow(c, start + n, delta_cap)
                row = mirrors[c]
                if start == 0:  # full (re)ship: reset the mirror
                    row[:] = S.DataTypeHistogram.STRING
                if n:
                    row[start:start + n] = np.fromiter(
                        (_classify(v) for v in values),
                        dtype=np.int32,
                        count=n,
                    )
                lut = _set_lut_row(lut, col_index[c], row)
            return {"counts": state["counts"], "lut": lut}

    token = _group_token(
        "datatype", dataset, columns, where, extra=(delta_cap,)
    )
    return ScanUnit(
        members,
        ScanOps(
            init,
            update,
            merge,
            consts=consts,
            cache_token=token,
            host_delta=host_delta,
        ),
        requests,
        extract,
    )


# --------------------------------------------------------------------------
# planner
# --------------------------------------------------------------------------


def plan_scan_units(
    dataset: Dataset, analyzers: Sequence[Any]
) -> Tuple[List[ScanUnit], Dict[Any, BaseException]]:
    """Partition analyzers into vectorized groups + singles.

    Returns (units, plan_failures). Grouping keys include the device
    dtype of the stacked repr and the ``where`` expression; anything
    unrecognized, host-folded, or oddly-typed falls back to its own
    ``make_ops`` — behavior is identical either way.
    """
    from deequ_tpu.analyzers.basic import (
        Completeness,
        Maximum,
        MaxLength,
        Mean,
        Minimum,
        MinLength,
        StandardDeviation,
        Sum,
    )
    from deequ_tpu.analyzers.datatype import DataType
    from deequ_tpu.analyzers.hll import ApproxCountDistinct
    from deequ_tpu.analyzers.kll import (
        ApproxQuantile,
        ApproxQuantiles,
        KLLSketch,
    )

    groups: Dict[tuple, List[Any]] = {}
    singles: List[Any] = []
    failures: Dict[Any, BaseException] = {}

    def group_key(a) -> Optional[tuple]:
        t = type(a)
        try:
            if t in (Mean, Sum, Minimum, Maximum, StandardDeviation):
                dt = dataset.request_dtype(
                    ColumnRequest(a.column, "values")
                )
                return ("stats", "values", str(dt), a.where)
            if t in (MinLength, MaxLength):
                return ("stats", "lengths", "int32", a.where)
            if t is Completeness:
                return ("completeness", a.where)
            if t is ApproxCountDistinct:
                kind = dataset.schema.kind_of(a.column)
                if kind == Kind.STRING:
                    dt = dataset.request_dtype(
                        ColumnRequest(a.column, "codes")
                    )
                    return ("hll", "codes", str(dt), a.where)
                dt = dataset.request_dtype(ColumnRequest(a.column, "values"))
                return ("hll", "values", str(dt), a.where)
            if (
                t is DataType
                and dataset.schema.kind_of(a.column) == Kind.STRING
            ):
                dt = dataset.request_dtype(ColumnRequest(a.column, "codes"))
                return ("datatype", str(dt), a.where)
            if t in (KLLSketch, ApproxQuantile, ApproxQuantiles):
                # values cast to f32 inside the kernel, so mixed input
                # dtypes stack fine; sketches keyed by params + where
                return (
                    "kll",
                    a.params.sketch_size,
                    a.params.shrinking_factor,
                    a.where,
                )
        except Exception:  # noqa: BLE001 — fall back to the single path
            return None
        return None

    for a in analyzers:
        key = group_key(a)
        if key is None:
            singles.append(a)
        else:
            groups.setdefault(key, []).append(a)

    units: List[ScanUnit] = []
    # KLL groups' column orders, per where-clause: an f32 HLL group
    # whose columns a same-where KLL group covers rides that group's
    # sort (see _build_hll_group's kll_pool_columns)
    kll_pools: Dict[Optional[str], Tuple[str, ...]] = {}
    for key, members in groups.items():
        if key[0] == "kll" and len(members) > 1:
            cols, _ = _index_members(members)
            prev = kll_pools.get(key[3])
            if prev is None or len(cols) > len(prev):
                kll_pools[key[3]] = tuple(cols)
    for key, members in groups.items():
        if len(members) == 1:
            singles.extend(members)
            continue
        try:
            if key[0] == "stats":
                units.append(
                    _build_stats_group(dataset, members, key[1], key[3])
                )
            elif key[0] == "completeness":
                units.append(
                    _build_completeness_group(dataset, members, key[1])
                )
            elif key[0] == "hll":
                pool = None
                runtime_gated: Tuple[str, ...] = ()
                pooled_members, plain_members = members, []
                candidate = kll_pools.get(key[3])
                if (
                    key[1] == "values"
                    and candidate is not None
                    and key[2]
                    in ("float32", "int8", "int16", "int32", "int64")
                ):
                    if key[2] == "float32":
                        cols, _ = _index_members(members)
                        if set(cols) <= set(candidate):
                            pool = candidate
                    else:
                        # integer storage rides the f32-cast pool
                        # STATICALLY when the column's RANGE both fits
                        # the 24-bit mantissa (cast exact; dict entries
                        # cast back before the integral hash —
                        # sketches/hll.py) and BOUNDS the cardinality
                        # near the dict cap, so guaranteed-high-card
                        # key columns keep the one stacked scatter
                        # instead of per-column probes. Coverage is
                        # judged per POOLED column (an unbounded
                        # group-mate must not veto its bounded
                        # neighbors).
                        lim = 4 * hll.DEDUP_DICT_CAP
                        exact = 1 << 24  # f32 mantissa
                        cand_set = set(candidate)

                        def poolable(c):
                            r = dataset.integral_range(c)
                            return (
                                c in cand_set
                                and r is not None
                                and (r[1] - r[0]) < lim
                                and -exact <= r[0]
                                and r[1] <= exact
                            )

                        pooled_idx = {
                            i
                            for i, a in enumerate(members)
                            if poolable(a.column)
                        }
                        # widened gate: KLL-covered integer columns
                        # the probe could NOT qualify (unknown/wide
                        # range) still join the pooled unit, gated at
                        # RUNTIME on the carried-register estimate +
                        # an in-batch mantissa check (sketches/hll.py
                        # gated_column_registers_from_sorted) — the
                        # probe's range is a cardinality PROXY; the
                        # registers measure cardinality directly
                        if config.options().hll_dedup_widening:
                            gated_idx = {
                                i
                                for i, a in enumerate(members)
                                if i not in pooled_idx
                                and a.column in cand_set
                            }
                            if gated_idx:
                                seen = set()
                                runtime_gated = tuple(
                                    a.column
                                    for i, a in enumerate(members)
                                    if i in gated_idx
                                    and a.column not in seen
                                    and not seen.add(a.column)
                                )
                                pooled_idx |= gated_idx
                        if pooled_idx:
                            pool = candidate
                            pooled_members = [
                                a
                                for i, a in enumerate(members)
                                if i in pooled_idx
                            ]
                            plain_members = [
                                a
                                for i, a in enumerate(members)
                                if i not in pooled_idx
                            ]
                # build EVERY unit before appending ANY: a failure
                # mid-way would otherwise leave the already-appended
                # half ALSO planned as singles by the except below —
                # the same analyzer computed twice per batch (review
                # finding)
                new_units = []
                if plain_members:
                    new_units.append(
                        _build_hll_group(
                            dataset, plain_members, key[1], key[3]
                        )
                    )
                if pooled_members:
                    new_units.append(
                        _build_hll_group(
                            dataset,
                            pooled_members,
                            key[1],
                            key[3],
                            kll_pool_columns=pool,
                            runtime_gate_columns=runtime_gated or None,
                        )
                    )
                units.extend(new_units)
            elif key[0] == "kll":
                units.append(
                    _build_kll_group(dataset, members, key[3])
                )
            else:
                units.append(
                    _build_datatype_group(dataset, members, key[2])
                )
        except Exception:  # noqa: BLE001 — vectorization is an
            # optimization; degrade to the per-analyzer path
            singles.extend(members)

    from deequ_tpu.analyzers.base import CACHE_TOKEN_AUTO, make_cache_token

    for a in singles:
        try:
            ops = a.make_ops(dataset)
            if ops.cache_token is CACHE_TOKEN_AUTO:
                ops.cache_token = make_cache_token(
                    a,
                    dataset,
                    predicates=(
                        getattr(a, "where", None),
                        getattr(a, "predicate", None),
                    ),
                )
            units.append(
                ScanUnit([a], ops, a.device_requests(dataset), None)
            )
        except Exception as exc:  # noqa: BLE001
            failures[a] = exc
    if units:
        from deequ_tpu.telemetry import get_telemetry

        tm = get_telemetry()
        tm.counter("engine.vectorize.units").inc(len(units))
        tm.counter("engine.vectorize.stacked_members").inc(
            sum(len(u.members) for u in units if len(u.members) > 1)
        )
    return units, failures
