"""State persistence: the checkpoint/resume + incremental subsystem.

Reference: ``src/main/scala/com/amazon/deequ/analyzers/StateProvider.scala``
(SURVEY.md §2.2, §5.4): ``StateLoader``/``StatePersister`` with an
in-memory provider (concurrent map) and a filesystem provider doing
binary serde of every state type. Because every state is a mergeable
monoid, persisted states give (a) incremental append-only datasets,
(b) partition-parallel computation merged later, (c) resume-from-state.

deequ_tpu states are pytrees of numpy arrays (NamedTuples) or the
host-side ``FrequenciesAndNumRows``; the filesystem format is one ``.npz``
per (analyzer, state) plus a JSON index keyed by the analyzer's stable
repr — its own format, not bit-compatible with the reference's
(SURVEY.md §7 hard part #5 recommends exactly this).
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
from dataclasses import dataclass
from typing import Any, Dict, Optional

import numpy as np

from deequ_tpu.analyzers.base import Analyzer
from deequ_tpu.analyzers.grouping import FrequenciesAndNumRows
from deequ_tpu.analyzers.states import STATE_FORMAT_VERSIONS, STATE_TYPES
from deequ_tpu.sketches.kll import KLLSketchState


class StateLoader:
    def load(self, analyzer: Analyzer) -> Optional[Any]:
        raise NotImplementedError


class StatePersister:
    def persist(self, analyzer: Analyzer, state: Any) -> None:
        raise NotImplementedError


class InMemoryStateProvider(StateLoader, StatePersister):
    """Thread-safe in-process store (reference: InMemoryStateProvider)."""

    def __init__(self) -> None:
        self._states: Dict[str, Any] = {}
        self._lock = threading.Lock()

    def load(self, analyzer: Analyzer) -> Optional[Any]:
        with self._lock:
            return self._states.get(repr(analyzer))

    def persist(self, analyzer: Analyzer, state: Any) -> None:
        with self._lock:
            self._states[repr(analyzer)] = state

    def __repr__(self) -> str:
        return f"InMemoryStateProvider({len(self._states)} states)"


def _to_host(value):
    return np.asarray(value)


class FileSystemStateProvider(StateLoader, StatePersister):
    """Binary state serde to a directory OR storage URI (reference:
    HdfsStateProvider — local/HDFS/S3 via the Hadoop FS registry; here
    plain paths use the local filesystem and ``scheme://`` URIs route
    through deequ_tpu.io.storage's backend registry — ``mem://`` ships
    in-tree, cloud backends register in a few lines)."""

    def __init__(self, path: str, allow_overwrite: bool = True):
        from deequ_tpu.io.storage import storage_for

        self._path = path
        self._allow_overwrite = allow_overwrite
        self._storage = storage_for(path)

    def _key(self, analyzer: Analyzer) -> str:
        digest = hashlib.sha1(repr(analyzer).encode()).hexdigest()[:16]
        return f"state-{digest}.npz"

    def _update_index(self, analyzer: Analyzer, key: str) -> None:
        index: Dict[str, str] = {}
        raw = self._storage.read_bytes("index.json")
        if raw is not None:
            index = json.loads(raw.decode())
        index[repr(analyzer)] = key
        self._storage.write_bytes(
            "index.json", json.dumps(index, indent=2).encode()
        )

    def persist(self, analyzer: Analyzer, state: Any) -> None:
        import io as _io

        key = self._key(analyzer)
        if not self._allow_overwrite and self._storage.exists(key):
            raise FileExistsError(f"{self._path}/{key}")
        buf = _io.BytesIO()
        if isinstance(state, FrequenciesAndNumRows):
            np.savez(
                buf,
                __type__=np.asarray("FrequenciesAndNumRows"),
                columns=np.asarray(json.dumps(list(state.columns))),
                keys=np.asarray(
                    json.dumps([[_json_safe(v) for v in row] for row in state.keys])
                ),
                counts=state.counts,
                num_rows=np.int64(state.num_rows),
            )
        elif isinstance(state, KLLSketchState):
            np.savez(
                buf,
                __type__=np.asarray("KLLSketchState"),
                **state.to_arrays(),
            )
        elif hasattr(state, "_fields"):  # NamedTuple state
            name = type(state).__name__
            payload = {
                field: _to_host(getattr(state, field))
                for field in state._fields
            }
            np.savez(
                buf,
                __type__=np.asarray(name),
                __version__=np.int64(STATE_FORMAT_VERSIONS.get(name, 1)),
                **payload,
            )
        else:
            raise TypeError(
                f"cannot persist state of type {type(state).__name__}"
            )
        self._storage.write_bytes(key, buf.getvalue())
        self._update_index(analyzer, key)

    def load(self, analyzer: Analyzer) -> Optional[Any]:
        import io as _io

        raw = self._storage.read_bytes(self._key(analyzer))
        if raw is None:
            return None
        with np.load(_io.BytesIO(raw), allow_pickle=False) as data:
            type_name = str(data["__type__"])
            if type_name == "FrequenciesAndNumRows":
                columns = tuple(json.loads(str(data["columns"])))
                key_rows = json.loads(str(data["keys"]))
                keys = np.empty((len(key_rows), len(columns)), dtype=object)
                for i, row in enumerate(key_rows):
                    keys[i, :] = row
                return FrequenciesAndNumRows(
                    columns, keys, data["counts"], int(data["num_rows"])
                )
            if type_name == "KLLSketchState":
                return KLLSketchState.from_arrays(data)
            cls = STATE_TYPES.get(type_name)
            if cls is None:
                raise TypeError(f"unknown persisted state type {type_name}")
            expected = STATE_FORMAT_VERSIONS.get(type_name, 1)
            found = int(data["__version__"]) if "__version__" in data else 1
            if found != expected:
                raise TypeError(
                    f"persisted {type_name} has format v{found}, this "
                    f"build reads v{expected} — recompute the state "
                    "(merging across versions would be silently wrong)"
                )
            return cls(
                **{f: data[f] for f in cls._fields}
            )


def _json_safe(value):
    if value is None or isinstance(value, (str, bool)):
        return value
    if isinstance(value, (np.integer, int)):
        return int(value)
    if isinstance(value, (np.floating, float)):
        return float(value)
    return str(value)


# --------------------------------------------------------------------------
# Scan checkpointing (engine resilience — docs/RESILIENCE.md)
# --------------------------------------------------------------------------


@dataclass(frozen=True)
class EgressCursor:
    """Durable high-water mark of a run's row-level egress at one
    checkpoint (docs/EGRESS.md "Durable egress"). Constructed ONLY
    after the span segment's fsync returned — the write-ahead ordering
    (flush THEN cursor) the ``egress-durability`` staticcheck rule
    makes structural — so a resume that trusts it replays zero rows
    and drops zero rows.

    ``last_durably_flushed_span_seq`` is the sequence number of the
    newest ``spans/seg-*.parquet`` segment on durable storage (-1 when
    none, e.g. the spool-mode scan phase); ``plane_spool_offset`` is
    the fsynced byte length of ``_scan_bits.spool`` (0 outside spool
    mode). The row/byte counters restore the writer's accounting so a
    resumed run's report is bit-identical to an uninterrupted one."""

    last_durably_flushed_span_seq: int
    rows_emitted_clean: int
    rows_emitted_quarantined: int
    plane_spool_offset: int
    bytes_raw: int = 0
    bytes_encoded: int = 0


@dataclass(frozen=True)
class ScanCursor:
    """Position of a checkpoint inside a scan: ``batch_index`` batches
    are already folded into the saved states (resume starts there);
    ``row_offset`` is the source-row high-water mark; the fingerprint
    pins the SOURCE (a changed source invalidates the checkpoint — the
    monoid fold would silently mix two datasets otherwise). A run with
    a row-level sink additionally carries the sink's
    :class:`EgressCursor` — written only AFTER the span segment it
    names was durably flushed, so resume never re-emits a row."""

    batch_index: int
    row_offset: int
    source_fingerprint: str
    batch_size: int
    egress: Optional[Any] = None


class ScanCheckpointer:
    """Periodic whole-scan checkpoints for the fused scan loop.

    Where :class:`FileSystemStateProvider` persists one FINAL state per
    analyzer, the checkpointer persists the engine's entire carried
    state tuple MID-SCAN — every ``checkpoint_every_batches`` batches —
    together with a :class:`ScanCursor` and the scan's degradation
    record, so an interrupted scan resumes from the last checkpoint and
    produces bit-identical metrics (states are monoids; host folds are
    drained in order before each save, so the fold sequence on resume
    matches the uninterrupted run).

    Storage routes through :func:`deequ_tpu.io.storage.storage_for`
    (plain paths, ``file://``, ``mem://``, registered cloud schemes);
    local writes are temp-file + atomic rename, so a kill mid-save
    leaves the previous checkpoint intact. The payload is a pickle —
    analyzer states are numpy pytrees and host accumulators are Python
    sketch objects; the blob is keyed by a PLAN TOKEN (a digest of the
    scan's state-tree structure, shapes and dtypes), so a checkpoint
    can only ever be restored into the plan shape that wrote it, and
    several concurrent plans can share one checkpoint directory.
    """

    def __init__(
        self,
        path: str,
        every_batches: Optional[int] = None,
    ):
        from deequ_tpu.io.storage import storage_for

        self._path = path
        self._storage = storage_for(path)
        # None -> config.checkpoint_every_batches at scan time
        self.every_batches = every_batches

    def _key(self, plan_token: str) -> str:
        return f"scan-ckpt-{plan_token}.pkl"

    def interval(self) -> int:
        """Batches between checkpoints (<= 0 disables)."""
        if self.every_batches is not None:
            return int(self.every_batches)
        from deequ_tpu import config

        return int(config.options().checkpoint_every_batches)

    def save(
        self,
        cursor: ScanCursor,
        plan_token: str,
        states: Any,
        host_accs: Dict[int, Any],
        degradation: Any,
    ) -> None:
        import pickle

        payload = {
            "version": 1,
            "cursor": cursor,
            "plan_token": plan_token,
            "states": states,  # host (numpy) pytrees — device_get'd
            "host_accs": host_accs,
            "degradation": degradation,
        }
        blob = pickle.dumps(payload, protocol=pickle.HIGHEST_PROTOCOL)
        key = self._key(plan_token)
        # checkpoints exist to survive crashes, so ask the backend for
        # power-loss durability (fsync on LocalStorage); a custom
        # Storage subclass predating the ``durable=`` parameter still
        # works via the fallback
        try:
            self._storage.write_bytes(key, blob, durable=True)
        except TypeError:
            self._storage.write_bytes(key, blob)

    def load(
        self, source_fingerprint: str, plan_token: str
    ) -> Optional[Dict[str, Any]]:
        """The latest checkpoint for this (source, plan), or None when
        there is none / it belongs to a different source or plan shape /
        the blob is corrupt (a partial write from a crashed process
        must degrade to a fresh scan, never abort it)."""
        import pickle

        raw = self._storage.read_bytes(self._key(plan_token))
        if raw is None:
            return None
        try:
            payload = pickle.loads(raw)
        except Exception:  # noqa: BLE001 — corrupt checkpoint = no checkpoint
            return None
        if not isinstance(payload, dict) or payload.get("version") != 1:
            return None
        cursor = payload.get("cursor")
        if (
            not isinstance(cursor, ScanCursor)
            or cursor.source_fingerprint != source_fingerprint
            or payload.get("plan_token") != plan_token
        ):
            return None
        return payload

    def clear(self, plan_token: Optional[str] = None) -> None:
        """Drop checkpoints — the one for ``plan_token``, or every scan
        checkpoint under the path (a completed scan must not leave a
        stale cursor for the next run to resume into)."""
        if plan_token is not None:
            self._storage.delete(self._key(plan_token))
            return
        for key in self._storage.list_keys("scan-ckpt-"):
            self._storage.delete(key)

    def __repr__(self) -> str:
        return f"ScanCheckpointer({self._path!r})"
