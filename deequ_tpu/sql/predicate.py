"""A small SQL-expression compiler for predicates over device columns.

The reference's ``Compliance`` analyzer and ``.where(...)`` filters take
arbitrary Spark SQL expression strings (reference:
``src/main/scala/com/amazon/deequ/analyzers/Compliance.scala``,
``checks/Check.scala``; SURVEY.md §2.2). deequ_tpu keeps that surface but
compiles the expression to pure JAX ops at plan time:

- numeric columns evaluate on their device ``values``;
- string comparisons become *dictionary-code* operations — equality/IN
  become host-side dictionary lookups producing code sets, LIKE/RLIKE
  become a host-side regex sweep over the (small) dictionary producing a
  device bool lookup table gathered by code. Strings never reach the TPU
  (SURVEY.md §7 hard part #3).

Three-valued logic follows SQL: comparisons involving NULL are NULL; a
row "complies" iff the predicate is TRUE (not NULL, not FALSE).

Supported grammar: OR / AND / NOT, comparisons (= == != <> < <= > >=),
arithmetic (+ - * / %), IS [NOT] NULL, [NOT] IN (...), BETWEEN x AND y,
[NOT] LIKE 'pat%' (SQL wildcards), RLIKE 'regex', unary minus, literals
(numbers, 'strings', TRUE/FALSE/NULL), parentheses, and a few functions
(ABS, LENGTH).
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from deequ_tpu.data.table import ColumnRequest, Dataset, Kind

# --------------------------------------------------------------------------
# Tokenizer
# --------------------------------------------------------------------------

_TOKEN_RE = re.compile(
    r"""
    (?P<ws>\s+)
  | (?P<number>\d+\.\d*(?:[eE][+-]?\d+)?|\.\d+(?:[eE][+-]?\d+)?|\d+(?:[eE][+-]?\d+)?)
  | (?P<string>'(?:[^'\\]|\\.)*'|"(?:[^"\\]|\\.)*")
  | (?P<bq_ident>`[^`]+`)
  | (?P<op><=|>=|!=|<>|==|=|<|>|\+|-|\*|/|%|\(|\)|,)
  | (?P<ident>[A-Za-z_][A-Za-z_0-9.]*)
    """,
    re.VERBOSE,
)

_KEYWORDS = {
    "AND", "OR", "NOT", "IS", "NULL", "IN", "BETWEEN", "LIKE", "RLIKE",
    "TRUE", "FALSE",
}


@dataclass(frozen=True)
class Token:
    kind: str  # 'number' | 'string' | 'ident' | 'op' | 'kw'
    text: str


def tokenize(expression: str) -> List[Token]:
    tokens: List[Token] = []
    pos = 0
    while pos < len(expression):
        m = _TOKEN_RE.match(expression, pos)
        if not m:
            raise PredicateParseError(
                f"cannot tokenize {expression[pos:pos + 20]!r} in predicate"
            )
        pos = m.end()
        if m.lastgroup == "ws":
            continue
        text = m.group()
        kind = m.lastgroup
        if kind == "bq_ident":
            tokens.append(Token("ident", text[1:-1]))
        elif kind == "ident" and text.upper() in _KEYWORDS:
            tokens.append(Token("kw", text.upper()))
        else:
            tokens.append(Token(kind, text))
    return tokens


class PredicateParseError(ValueError):
    pass


# --------------------------------------------------------------------------
# AST
# --------------------------------------------------------------------------


@dataclass(frozen=True)
class Node:
    pass


@dataclass(frozen=True)
class ColumnRef(Node):
    name: str


@dataclass(frozen=True)
class NumberLit(Node):
    value: float


@dataclass(frozen=True)
class StringLit(Node):
    value: str


@dataclass(frozen=True)
class BoolLit(Node):
    value: bool


@dataclass(frozen=True)
class NullLit(Node):
    pass


@dataclass(frozen=True)
class UnaryOp(Node):
    op: str  # 'NOT' | 'NEG'
    operand: Node


@dataclass(frozen=True)
class BinOp(Node):
    op: str  # 'AND','OR','=','!=','<','<=','>','>=','+','-','*','/','%'
    left: Node
    right: Node


@dataclass(frozen=True)
class IsNull(Node):
    operand: Node
    negate: bool


@dataclass(frozen=True)
class InList(Node):
    operand: Node
    items: Tuple[Node, ...]
    negate: bool


@dataclass(frozen=True)
class Between(Node):
    operand: Node
    low: Node
    high: Node


@dataclass(frozen=True)
class Like(Node):
    operand: Node
    pattern: str
    regex: bool
    negate: bool


@dataclass(frozen=True)
class StarLit(Node):
    """The `*` inside COUNT(*) (aggregate expressions only)."""


@dataclass(frozen=True)
class FuncCall(Node):
    name: str
    args: Tuple[Node, ...]


class _Parser:
    def __init__(self, tokens: List[Token]):
        self.tokens = tokens
        self.pos = 0

    def peek(self) -> Optional[Token]:
        return self.tokens[self.pos] if self.pos < len(self.tokens) else None

    def next(self) -> Token:
        tok = self.peek()
        if tok is None:
            raise PredicateParseError("unexpected end of predicate")
        self.pos += 1
        return tok

    def accept(self, kind: str, text: Optional[str] = None) -> Optional[Token]:
        tok = self.peek()
        if tok and tok.kind == kind and (text is None or tok.text == text):
            return self.next()
        return None

    def expect(self, kind: str, text: Optional[str] = None) -> Token:
        tok = self.accept(kind, text)
        if tok is None:
            got = self.peek()
            raise PredicateParseError(
                f"expected {text or kind}, got {got.text if got else 'EOF'!r}"
            )
        return tok

    def parse(self) -> Node:
        node = self.or_expr()
        if self.peek() is not None:
            raise PredicateParseError(
                f"trailing tokens starting at {self.peek().text!r}"
            )
        return node

    def or_expr(self) -> Node:
        node = self.and_expr()
        while self.accept("kw", "OR"):
            node = BinOp("OR", node, self.and_expr())
        return node

    def and_expr(self) -> Node:
        node = self.not_expr()
        while self.accept("kw", "AND"):
            node = BinOp("AND", node, self.not_expr())
        return node

    def not_expr(self) -> Node:
        if self.accept("kw", "NOT"):
            return UnaryOp("NOT", self.not_expr())
        return self.comparison()

    def comparison(self) -> Node:
        node = self.additive()
        tok = self.peek()
        if tok is None:
            return node
        if tok.kind == "op" and tok.text in ("=", "==", "!=", "<>", "<", "<=", ">", ">="):
            self.next()
            op = {"==": "=", "<>": "!="}.get(tok.text, tok.text)
            return BinOp(op, node, self.additive())
        if tok.kind == "kw" and tok.text == "IS":
            self.next()
            negate = self.accept("kw", "NOT") is not None
            self.expect("kw", "NULL")
            return IsNull(node, negate)
        negate = False
        if tok.kind == "kw" and tok.text == "NOT":
            nxt = (
                self.tokens[self.pos + 1]
                if self.pos + 1 < len(self.tokens)
                else None
            )
            if nxt and nxt.kind == "kw" and nxt.text in ("IN", "LIKE", "RLIKE"):
                self.next()
                negate = True
                tok = self.peek()
        if tok and tok.kind == "kw" and tok.text == "IN":
            self.next()
            self.expect("op", "(")
            items = [self.additive()]
            while self.accept("op", ","):
                items.append(self.additive())
            self.expect("op", ")")
            return InList(node, tuple(items), negate)
        if tok and tok.kind == "kw" and tok.text == "BETWEEN":
            self.next()
            low = self.additive()
            self.expect("kw", "AND")
            high = self.additive()
            return Between(node, low, high)
        if tok and tok.kind == "kw" and tok.text in ("LIKE", "RLIKE"):
            self.next()
            pat = self.next()
            if pat.kind != "string":
                raise PredicateParseError(
                    f"{tok.text} expects a string pattern"
                )
            return Like(
                node,
                _unquote(pat.text),
                regex=tok.text == "RLIKE",
                negate=negate,
            )
        return node

    def additive(self) -> Node:
        node = self.multiplicative()
        while True:
            tok = self.peek()
            if tok and tok.kind == "op" and tok.text in ("+", "-"):
                self.next()
                node = BinOp(tok.text, node, self.multiplicative())
            else:
                return node

    def multiplicative(self) -> Node:
        node = self.unary()
        while True:
            tok = self.peek()
            if tok and tok.kind == "op" and tok.text in ("*", "/", "%"):
                self.next()
                node = BinOp(tok.text, node, self.unary())
            else:
                return node

    def unary(self) -> Node:
        if self.accept("op", "-"):
            return UnaryOp("NEG", self.unary())
        return self.primary()

    def primary(self) -> Node:
        tok = self.next()
        if tok.kind == "number":
            return NumberLit(float(tok.text))
        if tok.kind == "string":
            return StringLit(_unquote(tok.text))
        if tok.kind == "kw" and tok.text == "TRUE":
            return BoolLit(True)
        if tok.kind == "kw" and tok.text == "FALSE":
            return BoolLit(False)
        if tok.kind == "kw" and tok.text == "NULL":
            return NullLit()
        if tok.kind == "op" and tok.text == "(":
            node = self.or_expr()
            self.expect("op", ")")
            return node
        if tok.kind == "ident":
            if self.accept("op", "("):
                args: List[Node] = []
                if tok.text.upper() == "COUNT" and self.accept("op", "*"):
                    args.append(StarLit())  # COUNT(*) only
                    self.expect("op", ")")
                elif not self.accept("op", ")"):
                    args.append(self.or_expr())
                    while self.accept("op", ","):
                        args.append(self.or_expr())
                    self.expect("op", ")")
                return FuncCall(tok.text.upper(), tuple(args))
            return ColumnRef(tok.text)
        raise PredicateParseError(f"unexpected token {tok.text!r}")


def _unquote(s: str) -> str:
    body = s[1:-1]
    return re.sub(r"\\(.)", r"\1", body)


def parse_predicate(expression: str) -> Node:
    return _Parser(tokenize(expression)).parse()


def _sql_like_to_regex(pattern: str) -> str:
    out = []
    for ch in pattern:
        if ch == "%":
            out.append(".*")
        elif ch == "_":
            out.append(".")
        else:
            out.append(re.escape(ch))
    return "^" + "".join(out) + "$"


# --------------------------------------------------------------------------
# Compiler: AST -> (requests, traced eval over batch)
# --------------------------------------------------------------------------

# An evaluated expression: (values, valid) with SQL null semantics, or for
# booleans (truth, valid). `values` may be numeric or int32 codes tagged
# with the column whose dictionary they index.


@dataclass
class _Val:
    values: jnp.ndarray
    valid: jnp.ndarray
    is_bool: bool = False
    codes_of: Optional[str] = None  # column name whose dictionary applies


class _PredicateData:
    """What predicate evaluation may touch: the schema (strong) and the
    dictionaries (weak — only string predicates dereference them, and
    only at trace time while the owning run holds the dataset)."""

    __slots__ = ("schema", "_ref")

    def __init__(self, schema, ref):
        self.schema = schema
        self._ref = ref

    def dictionary(self, column: str):
        dataset = self._ref()
        if dataset is None:  # pragma: no cover — contract violation
            raise RuntimeError(
                "string predicate outlived its dataset; string "
                "predicates are only traced while the owning run holds "
                "the data"
            )
        return dataset.dictionary(column)


class CompiledPredicate:
    """A predicate compiled against a dataset's schema + dictionaries.

    ``requests`` lists the device columns needed; ``evaluate(batch)`` is
    traceable and returns (truth: bool array, valid: bool array). A row
    complies iff truth & valid.
    """

    def __init__(
        self,
        node: Node,
        dataset: Dataset,
        columns_used: Sequence[str],
        requests: Sequence[ColumnRequest],
    ):
        import weakref

        self._node = node
        # WEAK reference: compiled predicates end up inside jitted
        # closures that the cross-run plan cache retains — a strong ref
        # would pin the whole Arrow table for the cache's lifetime. The
        # dataset is only dereferenced at TRACE time (schema lookups,
        # dictionary lookups for string predicates), which happens while
        # the owning run still holds the dataset.
        self._dataset_ref = weakref.ref(dataset)
        self._schema = dataset.schema
        self.columns_used = tuple(columns_used)
        self.requests = tuple(requests)
        # a predicate touching NO string column evaluates identically on
        # any dataset with the same schema kinds (no dictionary-derived
        # constants get baked into its closure) — the engine's plan
        # cache may reuse compiled scans across datasets only then
        self.dataset_independent = all(
            dataset.schema.kind_of(c) != Kind.STRING
            for c in self.columns_used
        )

    @property
    def _dataset(self) -> "_PredicateData":
        # shim: schema strongly held (all a NUMERIC predicate touches,
        # incl. on re-trace after the origin dataset is gone);
        # dictionaries resolve through the weakref (string predicates
        # only — those are never in cached cross-dataset plans)
        return _PredicateData(self._schema, self._dataset_ref)

    def evaluate(self, batch: Dict[str, jnp.ndarray]) -> Tuple[jnp.ndarray, jnp.ndarray]:
        val = _eval(self._node, batch, self._dataset)
        truth, valid = _as_bool(val)
        return truth, valid

    def complies(self, batch: Dict[str, jnp.ndarray]) -> jnp.ndarray:
        truth, valid = self.evaluate(batch)
        return truth & valid


def compile_predicate(expression: str, dataset: Dataset) -> CompiledPredicate:
    # per-dataset compile cache: device_requests() and make_ops() both
    # compile the same expressions during planning
    cache = getattr(dataset, "_predicate_cache", None)
    if cache is None:
        cache = {}
        setattr(dataset, "_predicate_cache", cache)
    if expression in cache:
        return cache[expression]
    node = parse_predicate(expression)
    cols = sorted(_columns_of(node))
    schema = dataset.schema
    requests: List[ColumnRequest] = []
    for c in cols:
        if not schema.has_column(c):
            raise KeyError(f"predicate references unknown column '{c}'")
        kind = schema.kind_of(c)
        if kind == Kind.STRING:
            requests.append(ColumnRequest(c, "codes"))
        else:
            requests.append(ColumnRequest(c, "values"))
        requests.append(ColumnRequest(c, "mask"))
    for col in _length_columns_of(node):
        requests.append(ColumnRequest(col, "lengths"))
    # static type check NOW (make_ops/planning time) so a bad predicate
    # degrades to THAT analyzer's failure metric — a raise later, inside
    # the shared fused-scan trace, would poison every co-scheduled
    # analyzer in the pass
    _check_types(node, schema)
    compiled = CompiledPredicate(node, dataset, cols, requests)
    cache[expression] = compiled
    return compiled


def _check_types(node: Node, schema) -> str:
    """Static kind inference: returns 'string' | 'stringlit' | 'value' |
    'null'; raises PredicateParseError on string/numeric mixes that the
    runtime would otherwise hit mid-trace."""

    def kind_of(n: Node) -> str:
        if isinstance(n, ColumnRef):
            return (
                "string" if schema.kind_of(n.name) == Kind.STRING else "value"
            )
        if isinstance(n, StringLit):
            return "stringlit"
        if isinstance(n, NullLit):
            return "null"
        if isinstance(n, (NumberLit, BoolLit)):
            return "value"
        if isinstance(n, UnaryOp):
            k = kind_of(n.operand)
            if k in ("string", "stringlit"):
                raise PredicateParseError(
                    f"{'negation' if n.op == 'NEG' else 'NOT'} is "
                    "undefined for string operands"
                )
            return "value"
        if isinstance(n, IsNull):
            kind_of(n.operand)
            return "value"
        if isinstance(n, Between):
            check_cmp(n.operand, n.low)
            check_cmp(n.operand, n.high)
            return "value"
        if isinstance(n, InList):
            base = kind_of(n.operand)
            for item in n.items:
                if isinstance(item, NullLit):
                    continue
                item_kind = kind_of(item)
                if base == "string" and item_kind != "stringlit":
                    raise PredicateParseError(
                        "IN on a string column requires string literals"
                    )
                if base != "string" and item_kind == "stringlit":
                    raise PredicateParseError(
                        "IN with string literals requires a string column"
                    )
            return "value"
        if isinstance(n, Like):
            if kind_of(n.operand) != "string":
                raise PredicateParseError("LIKE requires a string column")
            return "value"
        if isinstance(n, FuncCall):
            # the predicate evaluator supports only these functions;
            # aggregates (SUM/COUNT/...) belong to CustomSql expressions
            # and must fail HERE (planning time), not mid-trace where
            # they would poison every co-scheduled analyzer
            if n.name not in ("ABS", "LENGTH"):
                raise PredicateParseError(
                    f"unsupported function {n.name} in a predicate"
                )
            for a in n.args:
                if isinstance(a, StarLit):
                    raise PredicateParseError(
                        f"* is not a valid argument to {n.name}"
                    )
                kind_of(a)
            return "value"
        if isinstance(n, BinOp):
            if n.op in ("AND", "OR"):
                for side in (n.left, n.right):
                    if kind_of(side) in ("string", "stringlit"):
                        raise PredicateParseError(
                            "a bare string operand is not a boolean "
                            f"(in {n.op})"
                        )
                return "value"
            lk, rk = kind_of(n.left), kind_of(n.right)
            if n.op in _CMP:
                check_kinds(lk, rk, n.op)
                return "value"
            # arithmetic
            for k in (lk, rk):
                if k in ("string", "stringlit"):
                    raise PredicateParseError(
                        f"arithmetic {n.op!r} is undefined for string "
                        "operands"
                    )
            return "value"
        return "value"

    def check_kinds(lk: str, rk: str, op: str) -> None:
        stringish = ("string", "stringlit")
        if "null" in (lk, rk):
            return
        if (lk in stringish) != (rk in stringish):
            raise PredicateParseError(
                "cannot compare a string operand with a non-string "
                "operand (dictionary codes are not values)"
            )
        if lk == "stringlit" and rk == "stringlit":
            raise PredicateParseError(
                f"comparison {op!r} of two string literals is constant"
            )

    def check_cmp(a: Node, b: Node) -> None:
        check_kinds(kind_of(a), kind_of(b), "BETWEEN")

    return kind_of(node)


def _length_columns_of(node: Node) -> set:
    """Columns appearing as LENGTH(col) — they need the 'lengths' repr."""
    out: set = set()
    if isinstance(node, FuncCall) and node.name == "LENGTH":
        for arg in node.args:
            if isinstance(arg, ColumnRef):
                out.add(arg.name)
    for attr in ("operand", "left", "right", "low", "high"):
        child = getattr(node, attr, None)
        if isinstance(child, Node):
            out |= _length_columns_of(child)
    for attr in ("items", "args"):
        for child in getattr(node, attr, ()):
            out |= _length_columns_of(child)
    return out


def _columns_of(node: Node) -> set:
    if isinstance(node, ColumnRef):
        return {node.name}
    out: set = set()
    for attr in ("operand", "left", "right", "low", "high"):
        child = getattr(node, attr, None)
        if isinstance(child, Node):
            out |= _columns_of(child)
    for attr in ("items", "args"):
        children = getattr(node, attr, ())
        for child in children:
            out |= _columns_of(child)
    return out


def _as_bool(v: _Val) -> Tuple[jnp.ndarray, jnp.ndarray]:
    if v.is_bool:
        return v.values.astype(bool), v.valid
    return v.values != 0, v.valid


_CMP = ("=", "!=", "<", "<=", ">", ">=")
_CMP_FNS = {
    "=": jnp.equal,
    "!=": jnp.not_equal,
    "<": jnp.less,
    "<=": jnp.less_equal,
    ">": jnp.greater,
    ">=": jnp.greater_equal,
}


def _dict_lookup(dataset: Dataset, column: str, value: str) -> int:
    dictionary = dataset.dictionary(column)
    matches = np.nonzero(dictionary == value)[0]
    return int(matches[0]) if len(matches) else -2  # -2: matches nothing


def _rank_table(
    dictionaries: "list[np.ndarray]", extra: "list[str]"
) -> "dict[str, int]":
    """Lexicographic rank of every distinct string across the given
    dictionaries (+ literals): the shared value domain that makes codes
    from unrelated dictionaries comparable."""
    values = set(extra)
    for d in dictionaries:
        values.update(str(v) for v in d if v is not None)
    return {v: i for i, v in enumerate(sorted(values))}


def _ranks_for(dictionary: np.ndarray, rank: "dict[str, int]") -> np.ndarray:
    """int32 LUT code -> shared rank; one trailing slot (-1) for null
    codes so a single clipped gather covers every code."""
    out = np.full(len(dictionary) + 1, -1, dtype=np.int32)
    for i, v in enumerate(dictionary):
        if v is not None:
            out[i] = rank[str(v)]
    return out


def _gather_ranks(lut: np.ndarray, codes: jnp.ndarray) -> jnp.ndarray:
    table = jnp.asarray(lut)
    idx = jnp.where(codes < 0, table.shape[0] - 1, codes)
    return table[jnp.clip(idx, 0, table.shape[0] - 1)]


def _shared_rank_luts(dataset: Dataset, col_a: str, col_b: str):
    da, db = dataset.dictionary(col_a), dataset.dictionary(col_b)
    rank = _rank_table([da, db], [])
    return _ranks_for(da, rank), _ranks_for(db, rank)


def _rank_lut_with_literal(dataset: Dataset, column: str, literal: str):
    d = dataset.dictionary(column)
    rank = _rank_table([d], [literal])
    return _ranks_for(d, rank), rank[literal]


def _eval(node: Node, batch: Dict[str, jnp.ndarray], ds: Dataset) -> _Val:
    if isinstance(node, ColumnRef):
        kind = ds.schema.kind_of(node.name)
        mask = batch[f"{node.name}::mask"]
        if kind == Kind.STRING:
            return _Val(batch[f"{node.name}::codes"], mask, codes_of=node.name)
        vals = batch[f"{node.name}::values"]
        return _Val(vals, mask, is_bool=kind == Kind.BOOLEAN)
    if isinstance(node, NumberLit):
        return _Val(jnp.asarray(node.value), jnp.asarray(True))
    if isinstance(node, BoolLit):
        return _Val(jnp.asarray(node.value), jnp.asarray(True), is_bool=True)
    if isinstance(node, NullLit):
        return _Val(jnp.asarray(0.0), jnp.asarray(False))
    if isinstance(node, StringLit):
        # bare string literal only makes sense inside comparisons, which
        # special-case it; standing alone it is an error
        raise PredicateParseError(
            f"string literal {node.value!r} outside comparison"
        )
    if isinstance(node, UnaryOp):
        if node.op == "NEG":
            v = _eval(node.operand, batch, ds)
            return _Val(-v.values, v.valid)
        truth, valid = _as_bool(_eval(node.operand, batch, ds))
        return _Val(~truth, valid, is_bool=True)
    if isinstance(node, IsNull):
        v = _eval(node.operand, batch, ds)
        res = v.valid if node.negate else ~v.valid
        return _Val(res, jnp.ones_like(res, dtype=bool), is_bool=True)
    if isinstance(node, Between):
        return _eval(
            BinOp(
                "AND",
                BinOp(">=", node.operand, node.low),
                BinOp("<=", node.operand, node.high),
            ),
            batch,
            ds,
        )
    if isinstance(node, InList):
        base = _eval(node.operand, batch, ds)
        truth = jnp.zeros_like(base.values, dtype=bool)
        has_null_item = False
        for item in node.items:
            if isinstance(item, NullLit):
                # SQL: x IN (..., NULL) is TRUE on a match, else NULL
                has_null_item = True
            elif isinstance(item, StringLit):
                if base.codes_of is None:
                    raise PredicateParseError(
                        "IN with string literals requires a string column"
                    )
                code = _dict_lookup(ds, base.codes_of, item.value)
                truth = truth | (base.values == code)
            else:
                rhs = _eval(item, batch, ds)
                truth = truth | ((base.values == rhs.values) & rhs.valid)
        valid = base.valid
        if has_null_item:
            valid = valid & truth  # non-matches become NULL
        if node.negate:
            truth = ~truth
        return _Val(truth, valid, is_bool=True)
    if isinstance(node, Like):
        base = _eval(node.operand, batch, ds)
        if base.codes_of is None:
            raise PredicateParseError("LIKE requires a string column")
        dictionary = ds.dictionary(base.codes_of)
        pattern = (
            node.pattern if node.regex else _sql_like_to_regex(node.pattern)
        )
        prog = re.compile(pattern)
        table = np.zeros(len(dictionary) + 1, dtype=bool)
        for i, s in enumerate(dictionary):
            if s is not None and prog.search(str(s)):
                table[i] = True
        lut = jnp.asarray(table)
        truth = lut[jnp.clip(base.values, -1, len(dictionary) - 1)]
        truth = jnp.where(base.values < 0, False, truth)
        if node.negate:
            truth = ~truth
        return _Val(truth, base.valid, is_bool=True)
    if isinstance(node, FuncCall):
        if node.name == "ABS" and len(node.args) == 1:
            v = _eval(node.args[0], batch, ds)
            return _Val(jnp.abs(v.values), v.valid)
        if node.name == "LENGTH" and len(node.args) == 1:
            arg = node.args[0]
            if isinstance(arg, ColumnRef):
                mask = batch[f"{arg.name}::mask"]
                return _Val(batch[f"{arg.name}::lengths"], mask)
            raise PredicateParseError("LENGTH expects a column")
        raise PredicateParseError(f"unsupported function {node.name}")
    if isinstance(node, BinOp):
        if node.op in ("AND", "OR"):
            lt, lv = _as_bool(_eval(node.left, batch, ds))
            rt, rv = _as_bool(_eval(node.right, batch, ds))
            if node.op == "AND":
                truth = lt & rt
                # SQL 3VL: FALSE AND NULL = FALSE (valid)
                valid = (lv & rv) | (lv & ~lt) | (rv & ~rt)
            else:
                truth = lt | rt
                # TRUE OR NULL = TRUE (valid)
                valid = (lv & rv) | (lv & lt) | (rv & rt)
            return _Val(truth, valid, is_bool=True)
        # comparisons involving string literals: =/!= compare raw codes
        # (one O(n) dictionary lookup, scalar compare); orderings need
        # lexicographic ranks — codes are in order of appearance
        if node.op in _CMP and (
            isinstance(node.left, StringLit) or isinstance(node.right, StringLit)
        ):
            lit_on_right = isinstance(node.right, StringLit)
            col_node, lit = (
                (node.left, node.right)
                if lit_on_right
                else (node.right, node.left)
            )
            base = _eval(col_node, batch, ds)
            if base.codes_of is None:
                raise PredicateParseError(
                    "string comparison requires a string column"
                )
            if node.op in ("=", "!="):
                code = _dict_lookup(ds, base.codes_of, lit.value)
                truth = base.values == code
                if node.op == "!=":
                    truth = ~truth
                return _Val(truth, base.valid, is_bool=True)
            ranks, lit_rank = _rank_lut_with_literal(
                ds, base.codes_of, lit.value
            )
            col_ranks = _gather_ranks(ranks, base.values)
            lv, rv = (
                (col_ranks, lit_rank) if lit_on_right else (lit_rank, col_ranks)
            )
            return _Val(_CMP_FNS[node.op](lv, rv), base.valid, is_bool=True)
        lhs = _eval(node.left, batch, ds)
        rhs = _eval(node.right, batch, ds)
        valid = lhs.valid & rhs.valid
        lv, rv = lhs.values, rhs.values
        if node.op in _CMP:
            if lhs.codes_of is not None and rhs.codes_of is not None:
                # two string columns: dictionary codes come from
                # UNRELATED dictionaries (and even one dictionary is in
                # order of appearance, not sorted) — remap both sides to
                # ranks in a shared sorted value domain so =/!= and
                # lexicographic ordering are exact
                lut_l, lut_r = _shared_rank_luts(
                    ds, lhs.codes_of, rhs.codes_of
                )
                lv = _gather_ranks(lut_l, lv)
                rv = _gather_ranks(lut_r, rv)
            elif (lhs.codes_of is None) != (rhs.codes_of is None):
                raise PredicateParseError(
                    "cannot compare a string column with a non-string "
                    "operand (dictionary codes are not values)"
                )
            return _Val(_CMP_FNS[node.op](lv, rv), valid, is_bool=True)
        if lhs.codes_of is not None or rhs.codes_of is not None:
            raise PredicateParseError(
                f"arithmetic {node.op!r} is undefined for string columns"
            )
        if node.op == "+":
            return _Val(lv + rv, valid)
        if node.op == "-":
            return _Val(lv - rv, valid)
        if node.op == "*":
            return _Val(lv * rv, valid)
        if node.op == "/":
            denom_ok = rv != 0
            safe = jnp.where(denom_ok, rv, 1)
            return _Val(lv / safe, valid & denom_ok)
        if node.op == "%":
            denom_ok = rv != 0
            safe = jnp.where(denom_ok, rv, 1)
            return _Val(lv % safe, valid & denom_ok)
    raise PredicateParseError(f"cannot evaluate node {node!r}")
