"""deequ_tpu — a TPU-native "unit tests for data" framework.

A brand-new data-quality framework with the capabilities of Deequ
(reference: ``jmscraig/deequ``, a Scala/Spark library — see SURVEY.md):
declarative checks evaluated against data-quality metrics, single-pass
scan-shared analyzer execution, mergeable incremental state, column
profiling, constraint suggestion, a persisted metrics repository, and
metric-series anomaly detection.

The execution engine is idiomatic JAX/XLA: analyzer states are fixed-shape
pytree commutative monoids, updates are vectorized masked reductions fused
by XLA into a single pass over device-resident column batches, merges are
collectives (psum / elementwise max / gather+recompress) over a
``jax.sharding.Mesh``. Upper layers (checks, constraints, repository,
anomaly detection, suggestion rules) are pure Python and engine-agnostic —
mirroring the reference's layering where everything above AnalysisRunner
never touches a DataFrame (SURVEY.md §1).
"""

from __future__ import annotations

import os

# int64/float64 support: states carry exact row counts (int64) and
# high-precision accumulators. On TPU, f64 is emulated — the engine's hot
# accumulation dtype is configurable (see deequ_tpu.config); finalization
# epilogues are tiny so f64 there is free.
if os.environ.get("DEEQU_TPU_NO_X64", "0") != "1":
    import jax

    jax.config.update("jax_enable_x64", True)

from deequ_tpu.metrics import (  # noqa: E402
    DoubleMetric,
    Entity,
    HistogramMetric,
    KLLMetric,
    Metric,
)
from deequ_tpu.data import Dataset  # noqa: E402
from deequ_tpu.checks import Check, CheckLevel, CheckStatus  # noqa: E402
from deequ_tpu.verification import (  # noqa: E402
    VerificationResult,
    VerificationSuite,
)
from deequ_tpu.analyzers.runner import (  # noqa: E402
    AnalysisRunner,
    AnalyzerContext,
)

__version__ = "0.1.0"

__all__ = [
    "AnalysisRunner",
    "AnalyzerContext",
    "Check",
    "CheckLevel",
    "CheckStatus",
    "Dataset",
    "DoubleMetric",
    "Entity",
    "HistogramMetric",
    "KLLMetric",
    "Metric",
    "VerificationResult",
    "VerificationSuite",
]
