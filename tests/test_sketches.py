"""Sketch accuracy + merge + serde tests (reference shape:
KLLDistanceTest / KLLSketchTest / HLL accuracy tests — SURVEY.md §4)."""

import jax.numpy as jnp
import numpy as np
import pytest

from deequ_tpu.analyzers import (
    AnalysisRunner,
    ApproxCountDistinct,
    ApproxQuantile,
    ApproxQuantiles,
    KLLSketch,
)
from deequ_tpu.data import Dataset
from deequ_tpu.engine import AnalysisEngine
from deequ_tpu.io import FileSystemStateProvider, InMemoryStateProvider
from deequ_tpu.sketches.kll import KLLParameters, KLLSketchState


def value(metric):
    assert metric.value.is_success, f"metric failed: {metric.value}"
    return metric.value.get()


class TestHLL:
    def test_exact_small(self):
        ds = Dataset.from_pydict({"x": [1, 2, 3, 2, 1]})
        est = value(ApproxCountDistinct("x").calculate(ds))
        assert est == pytest.approx(3.0, rel=0.01)

    def test_accuracy_numeric(self):
        rng = np.random.default_rng(1)
        n_distinct = 80_000
        vals = rng.integers(0, n_distinct, 300_000)
        true = len(np.unique(vals))
        ds = Dataset.from_pydict({"x": vals})
        est = value(ApproxCountDistinct("x").calculate(ds))
        assert est == pytest.approx(true, rel=0.03)

    def test_strings(self):
        ds = Dataset.from_pydict(
            {"s": [f"user-{i % 500}" for i in range(5_000)]}
        )
        est = value(ApproxCountDistinct("s").calculate(ds))
        assert est == pytest.approx(500, rel=0.03)

    def test_nulls_ignored(self):
        import pyarrow as pa

        ds = Dataset.from_arrow(
            pa.table({"x": pa.array([1.0, None, 2.0, None], pa.float64())})
        )
        assert value(ApproxCountDistinct("x").calculate(ds)) == pytest.approx(
            2.0, rel=0.01
        )

    def test_merge_equals_union(self):
        rng = np.random.default_rng(2)
        a = rng.integers(0, 10_000, 50_000)
        b = rng.integers(5_000, 15_000, 50_000)
        analyzer = ApproxCountDistinct("x")
        providers = []
        for part in (a, b):
            p = InMemoryStateProvider()
            AnalysisRunner.do_analysis_run(
                Dataset.from_pydict({"x": part}), [analyzer],
                save_states_with=p,
            )
            providers.append(p)
        merged = AnalysisRunner.run_on_aggregated_states(
            Dataset.from_pydict({"x": a[:1]}).schema, [analyzer], providers
        )
        union = AnalysisRunner.do_analysis_run(
            Dataset.from_pydict({"x": np.concatenate([a, b])}), [analyzer]
        )
        # register-max merge must give the IDENTICAL estimate
        assert value(merged.metric(analyzer)) == value(union.metric(analyzer))

    def test_hash_consistency_within_type_class(self):
        """Columns of the same type class with equal values hash
        identically regardless of storage width (required for
        cross-dataset merges: day 1 stores int32, day 2 int64). Int vs
        float need NOT agree — integral columns hash the raw 64-bit
        payload (exact for the full int64 range), matching the
        reference's HLL++ hashing the raw long."""
        i32 = Dataset.from_pydict({"x": np.arange(1000, dtype=np.int32)})
        i64 = Dataset.from_pydict({"x": np.arange(1000, dtype=np.int64)})
        f32 = Dataset.from_pydict({"x": np.arange(1000, dtype=np.float32)})
        f64 = Dataset.from_pydict({"x": np.arange(1000, dtype=np.float64)})
        assert value(ApproxCountDistinct("x").calculate(i32)) == value(
            ApproxCountDistinct("x").calculate(i64)
        )
        assert value(ApproxCountDistinct("x").calculate(f32)) == value(
            ApproxCountDistinct("x").calculate(f64)
        )

    def test_large_int64_accuracy(self):
        """IDs above 2^53 (snowflake/epoch-nanos scale) must not
        collide: float canonicalization would estimate ~99 distinct for
        100k consecutive values at 2^62."""
        vals = np.arange(100_000, dtype=np.int64) + (1 << 62)
        ds = Dataset.from_pydict({"x": vals})
        est = value(ApproxCountDistinct("x").calculate(ds))
        assert abs(est - 100_000) / 100_000 < 0.03


class TestKLL:
    def test_exact_small(self):
        ds = Dataset.from_pydict({"x": list(range(1, 101))})
        q = value(ApproxQuantile("x", 0.5).calculate(ds))
        assert q == pytest.approx(50.0, abs=1.0)

    def test_rank_error_uniform(self):
        rng = np.random.default_rng(3)
        vals = rng.uniform(0, 1, 500_000)
        ds = Dataset.from_pydict({"x": vals})
        engine = AnalysisEngine(batch_size=65_536)
        analyzer = ApproxQuantiles("x", (0.1, 0.25, 0.5, 0.75, 0.9))
        ctx = AnalysisRunner.do_analysis_run(ds, [analyzer], engine=engine)
        result = value(ctx.metric(analyzer))
        for q in (0.1, 0.25, 0.5, 0.75, 0.9):
            # uniform[0,1]: value at quantile q is ~q; rank error < 1%
            assert result[str(q)] == pytest.approx(q, abs=0.01)

    def test_merge_across_partitions(self):
        rng = np.random.default_rng(4)
        vals = rng.normal(0, 1, 200_000)
        analyzer = ApproxQuantile("x", 0.5)
        providers = []
        for part in np.array_split(vals, 4):
            p = InMemoryStateProvider()
            AnalysisRunner.do_analysis_run(
                Dataset.from_pydict({"x": part}), [analyzer],
                save_states_with=p,
            )
            providers.append(p)
        merged = AnalysisRunner.run_on_aggregated_states(
            Dataset.from_pydict({"x": vals[:1]}).schema, [analyzer], providers
        )
        med = value(merged.metric(analyzer))
        assert med == pytest.approx(np.median(vals), abs=0.02)

    def test_kll_metric_buckets(self):
        ds = Dataset.from_pydict({"x": list(range(1000))})
        analyzer = KLLSketch("x", KLLParameters(number_of_buckets=10))
        dist = value(analyzer.calculate(ds))
        assert len(dist.buckets) == 10
        assert sum(b.count for b in dist.buckets) == pytest.approx(
            1000, abs=20
        )
        assert dist.buckets[0].low_value == 0.0
        assert dist.buckets[-1].high_value == 999.0

    def test_filesystem_roundtrip(self, tmp_path):
        rng = np.random.default_rng(5)
        ds = Dataset.from_pydict({"x": rng.normal(0, 1, 10_000)})
        analyzer = ApproxQuantile("x", 0.9)
        provider = FileSystemStateProvider(str(tmp_path))
        ctx = AnalysisRunner.do_analysis_run(
            ds, [analyzer], save_states_with=provider
        )
        reloaded = AnalysisRunner.run_on_aggregated_states(
            ds.schema, [analyzer], [FileSystemStateProvider(str(tmp_path))]
        )
        assert value(reloaded.metric(analyzer)) == value(ctx.metric(analyzer))

    def test_nonnumeric_fails(self):
        ds = Dataset.from_pydict({"s": ["a", "b"]})
        metric = ApproxQuantile("s", 0.5).calculate(ds)
        assert metric.value.is_failure

    def test_bad_quantile_fails(self):
        ds = Dataset.from_pydict({"x": [1.0, 2.0]})
        metric = ApproxQuantile("x", 1.5).calculate(ds)
        assert metric.value.is_failure


class TestKLLSketchStateUnit:
    def test_streaming_matches_exact(self):
        rng = np.random.default_rng(6)
        vals = rng.exponential(2.0, 100_000)
        sk = KLLSketchState()
        for chunk in np.array_split(vals, 37):
            sk.update_batch(chunk)
        assert sk.count == 100_000
        for q in (0.05, 0.5, 0.95):
            exact = np.quantile(vals, q)
            # compare by rank: estimated value's true rank within 1.5%
            est = sk.quantile(q)
            true_rank = np.mean(vals <= est)
            assert abs(true_rank - q) < 0.015, (q, est, exact)

    def test_monoid_merge(self):
        rng = np.random.default_rng(7)
        a, b = rng.normal(0, 1, 50_000), rng.normal(5, 1, 50_000)
        sa, sb = KLLSketchState(), KLLSketchState()
        sa.update_batch(a)
        sb.update_batch(b)
        merged = KLLSketchState.merge(sa, sb)
        assert merged.count == 100_000
        both = np.concatenate([a, b])
        est = merged.quantile(0.5)
        assert np.mean(both <= est) == pytest.approx(0.5, abs=0.02)


class TestKLLRegressions:
    def test_sparse_where_filter(self):
        """Compaction level derives from surviving rows, not batch size
        (a heavy where-filter must not starve the sketch)."""
        rng = np.random.default_rng(8)
        n = 200_000
        ds = Dataset.from_pydict(
            {
                "x": rng.uniform(0, 1, n),
                "y": (np.arange(n) % 2000 == 0).astype(np.int64),
            }
        )
        analyzer = ApproxQuantile("x", 0.5, where="y = 1")
        metric = analyzer.calculate(ds)
        assert metric.value.is_success, metric.value
        assert 0.3 < metric.value.get() < 0.7

    def test_nan_values_excluded(self):
        vals = np.arange(1000, dtype=np.float64)
        vals[5] = np.nan
        ds = Dataset.from_pydict({"x": vals})
        metric = ApproxQuantile("x", 1.0).calculate(ds)
        assert metric.value.is_success, metric.value
        assert metric.value.get() == 999.0

    def test_sharded_step_host_folds_kll(self, cpu_mesh):
        """Host-folded ops ride the explicit shard_map step: each
        shard's per-batch output is all_gathered and folded once on the
        host (the fold IS the sketch merge, so sharding can't change
        the metric on data small enough to stay uncompacted)."""
        import jax

        from deequ_tpu.engine import AnalysisEngine

        # small enough that BOTH paths keep every value at level 0
        # (nv < sketch_size), so sharded and single are exactly equal
        n = 8 * 128
        vals = np.arange(float(n))
        ds = Dataset.from_pydict({"x": list(vals)})
        analyzer = ApproxQuantile("x", 0.5)
        planned = [(analyzer, analyzer.make_ops(ds))]
        engine = AnalysisEngine(mesh=cpu_mesh)
        step = engine.build_sharded_step(ds, planned, cpu_mesh)
        requests = [
            r for a, _ in planned for r in a.device_requests(ds)
        ]
        (batch,) = list(ds.device_batches(requests, n))
        states = tuple(op.init() for _, op in planned)
        out = jax.block_until_ready(step(states, batch))
        (final,) = engine.fold_sharded_host_outputs(
            [op for _, op in planned], out, 8
        )
        got = analyzer.compute_metric_from_state(final).value.get()
        want = AnalysisRunner.do_analysis_run(ds, [analyzer]).metric(
            analyzer
        ).value.get()
        assert got == pytest.approx(want, rel=1e-9)


class TestKLLAdversarial:
    """docs/KLL_ERROR.md §4: the strided-batch compaction's O(1/k)
    bound must hold for adversarial ORDERINGS and degenerate value
    distributions, not just i.i.d. uniform — a broken random offset,
    boundary weight loss, or order sensitivity would blow the
    envelope on at least one of these."""

    N = 400_000
    QS = tuple(round(q / 100, 2) for q in range(1, 100))

    def _max_rank_error(self, vals: np.ndarray) -> float:
        from deequ_tpu.sketches.kll import DEFAULT_SKETCH_SIZE as k

        ds = Dataset.from_pydict({"x": vals})
        engine = AnalysisEngine(batch_size=65_536)
        analyzer = ApproxQuantiles("x", self.QS)
        ctx = AnalysisRunner.do_analysis_run(ds, [analyzer], engine=engine)
        result = value(ctx.metric(analyzer))
        hi = np.sort(vals)
        n = len(vals)
        worst = 0.0
        for q in self.QS:
            est = result[str(q)]
            # the estimate's rank in the TRUE data is an INTERVAL
            # (value plateaus hold many ranks); the sketch is correct
            # if the target rank falls inside it, and its error is the
            # distance to the interval otherwise
            lo = np.searchsorted(hi, est, side="left")
            rhi = np.searchsorted(hi, est, side="right")
            target = q * n
            err = max(lo - target, target - rhi, 0.0)
            worst = max(worst, err)
        assert worst <= 3 * n / k, worst
        return worst

    def test_sorted_input(self):
        self._max_rank_error(np.arange(self.N, dtype=np.float64))

    def test_reverse_sorted_input(self):
        self._max_rank_error(np.arange(self.N, dtype=np.float64)[::-1])

    def test_constant_heavy(self):
        rng = np.random.default_rng(7)
        vals = np.where(
            rng.random(self.N) < 0.9, 42.0, rng.normal(0, 1, self.N)
        )
        # ranks are ambiguous across a 90% constant plateau; check the
        # plateau's quantiles resolve to the constant and the tails
        # stay in-envelope via the generic check on the mixed data
        self._max_rank_error(np.sort(vals))

    def test_organ_pipe_ordering(self):
        # small/large interleaved: worst case for sequential samplers
        a = np.arange(self.N, dtype=np.float64)
        pipe = np.empty(self.N)
        pipe[0::2] = a[: self.N // 2]
        pipe[1::2] = a[self.N // 2:][::-1]
        self._max_rank_error(pipe)

    def test_few_distinct(self):
        rng = np.random.default_rng(9)
        self._max_rank_error(
            rng.integers(0, 5, self.N).astype(np.float64)
        )


class TestPresenceDTiling:
    """The presence compare-reduce chunks its D axis (r4 advisory —
    bounds the (C, TILE, B) intermediate). Multi-tile results must be
    bit-identical to the single-tile math."""

    def test_hll_presence_multi_tile_matches_unchunked(self):
        from deequ_tpu.sketches.hll import (
            _PRESENCE_D_TILE,
            registers_from_code_presence,
            registers_from_hash_pair_stacked,
        )

        rng = np.random.default_rng(11)
        C, B, D = 3, 1024, _PRESENCE_D_TILE * 2 + 64  # 3 tiles, ragged
        codes = rng.integers(-1, D, (C, B)).astype(np.int32)
        mask = codes >= 0
        lut1 = rng.integers(0, 2**32, (C, D), dtype=np.uint64).astype(
            np.uint32
        )
        lut2 = rng.integers(0, 2**32, (C, D), dtype=np.uint64).astype(
            np.uint32
        )
        got = np.asarray(
            registers_from_code_presence(codes, mask, lut1, lut2)
        )
        # oracle: presence computed densely on host
        present = np.zeros((C, D), dtype=bool)
        for c in range(C):
            occurring = np.unique(codes[c][mask[c]])
            present[c, occurring] = True
        want = np.asarray(
            registers_from_hash_pair_stacked(lut1, lut2, present)
        )
        np.testing.assert_array_equal(got, want)

    def test_datatype_presence_multi_tile_matches_host(self):
        from deequ_tpu.analyzers.datatype import (
            DataTypeHistogram,
            counts_from_code_presence,
        )
        from deequ_tpu.sketches.hll import _PRESENCE_D_TILE

        rng = np.random.default_rng(12)
        C, B, D = 2, 2048, _PRESENCE_D_TILE + 33  # 2 tiles, ragged
        codes = rng.integers(-1, D, (C, B)).astype(np.int32)
        valid = codes >= 0
        rows = np.ones(B, dtype=bool)
        table = rng.integers(0, 6, (C, D)).astype(np.int32)
        got = np.asarray(
            counts_from_code_presence(codes, valid, rows, table)
        )
        want = np.zeros((C, 6), dtype=np.int64)
        for c in range(C):
            for b in range(B):
                if valid[c, b]:
                    want[c, table[c, codes[c, b]]] += 1
                else:
                    want[c, DataTypeHistogram.NULL] += 1
        np.testing.assert_array_equal(got, want)


class TestSortedDedupRegisters:
    """r5 adaptive numeric-HLL path: the sorted-dedup branch must
    produce BIT-IDENTICAL registers to the per-row scatter (states
    from the two paths max-merge, so divergence corrupts merges).
    Covers the sentinel discipline: nulls, real +inf (the float
    sentinel value), real iinfo.max (the int sentinel value),
    canonical NaN, -0.0/+0.0, exactly-at-cap and over-cap fallback."""

    def _scatter_ref(self, x, masks):
        from deequ_tpu.sketches import hll

        h1, h2 = hll.hash_pair_numeric(jnp.asarray(x))
        return np.asarray(
            hll.registers_from_hash_pair_stacked(
                h1, h2, jnp.asarray(masks)
            )
        )

    def _dedup(self, x, masks):
        from deequ_tpu.sketches import hll

        return np.asarray(
            hll.registers_from_sorted_dedup_stacked(
                jnp.asarray(x), jnp.asarray(masks)
            )
        )

    def test_float_edges_match_scatter(self):
        rng = np.random.default_rng(31)
        B = 4096
        rows = [
            # mid-card with nulls
            np.round(rng.normal(100, 25, B) * 100).astype(np.float32)
            / 100,
            # real +inf / -inf / NaN / -0.0 / +0.0 mixture
            np.array(
                [np.inf, -np.inf, np.nan, -0.0, 0.0, 1.5] * (B // 6)
                + [1.5] * (B % 6),
                dtype=np.float32,
            ),
            # constant column
            np.full(B, 42.0, dtype=np.float32),
        ]
        x = np.stack(rows)
        masks = rng.random((3, B)) > 0.1
        got = self._dedup(x, masks)
        want = self._scatter_ref(x, masks)
        np.testing.assert_array_equal(got, want)

    def test_int_edges_match_scatter(self):
        rng = np.random.default_rng(32)
        B = 4096
        x = np.stack(
            [
                rng.integers(0, 100, B),
                # include the int sentinel value as REAL data
                np.where(
                    rng.random(B) < 0.1,
                    np.iinfo(np.int32).max,
                    rng.integers(-50, 50, B),
                ),
            ]
        ).astype(np.int32)
        masks = rng.random((2, B)) > 0.2
        got = self._dedup(x, masks)
        want = self._scatter_ref(x, masks)
        np.testing.assert_array_equal(got, want)

    def test_over_cap_falls_back_exactly(self):
        """B must exceed DEDUP_DICT_CAP so U > D actually happens and
        the inner scatter fallback (the correctness safety net the
        gate design relies on) really executes."""
        from deequ_tpu.sketches import hll

        cap = hll.DEDUP_DICT_CAP
        B = cap + 4096
        x = np.stack(
            [
                np.arange(B, dtype=np.float32),  # U = B > cap: fallback
                np.concatenate(
                    [
                        np.arange(cap, dtype=np.float32),
                        np.zeros(B - cap, dtype=np.float32),
                    ]
                ),  # U exactly == cap: dict path at the boundary
            ]
        )
        masks = np.ones((2, B), dtype=bool)
        got = self._dedup(x, masks)
        want = self._scatter_ref(x, masks)
        np.testing.assert_array_equal(got, want)

    def test_all_null_and_empty_gate(self):
        from deequ_tpu.sketches import hll

        B = 1024
        x = np.zeros((1, B), dtype=np.float32)
        masks = np.zeros((1, B), dtype=bool)
        assert (self._dedup(x, masks) == 0).all()
        # gate: empty registers -> False; mid-card registers -> True;
        # saturated registers -> False
        empty = np.zeros((1, hll.M), np.int8)
        assert not bool(np.asarray(hll.dedup_gate(jnp.asarray(empty)))[0])
        mid = np.zeros((1, hll.M), np.int8)
        mid[0, : hll.M // 16] = 3  # ~1k registers touched
        assert bool(np.asarray(hll.dedup_gate(jnp.asarray(mid)))[0])
        full = np.full((1, hll.M), 3, np.int8)
        assert not bool(np.asarray(hll.dedup_gate(jnp.asarray(full)))[0])

    def test_adaptive_end_to_end_two_batches(self):
        """Through the public analyzer: batch 1 (scatter, empty state)
        then batch 2 (gated dedup) must equal a one-shot run and the
        exact distinct count within HLL error."""
        from deequ_tpu.analyzers import AnalysisRunner, ApproxCountDistinct
        from deequ_tpu.data import Dataset

        rng = np.random.default_rng(34)
        n = 40_000
        vals = np.round(rng.normal(100, 5, n) * 100) / 100  # ~3.5k uniq
        ds = Dataset.from_pydict({"x": vals.astype(np.float32)})
        with __import__("deequ_tpu").config.configure(batch_size=16_384):
            ctx = AnalysisRunner.do_analysis_run(
                ds, [ApproxCountDistinct("x")]
            )
        got = ctx.metric(ApproxCountDistinct("x")).value.get()
        exact = len(np.unique(vals))
        assert abs(got - exact) / exact < 0.05, (got, exact)


class TestDedupFromSortedPool:
    """The pooled variant (dedup from the KLL group's pre-sorted keys,
    where nulls AND every non-finite value sort as +inf) must also be
    bit-identical to the per-row scatter — incl. real -inf, which the
    pool sort hides and the flag path must re-add."""

    def test_pool_variant_matches_scatter(self):
        from deequ_tpu.sketches import hll

        rng = np.random.default_rng(41)
        B = 8192
        rows = [
            np.round(rng.normal(100, 25, B) * 100).astype(np.float32)
            / 100,
            np.array(
                [np.inf, -np.inf, np.nan, -0.0, 0.0, 7.25] * (B // 6)
                + [7.25] * (B % 6),
                dtype=np.float32,
            ),
            rng.normal(0, 1, B).astype(np.float32),  # high-card
        ]
        for xc in rows:
            maskc = rng.random(B) > 0.15
            s = np.sort(
                np.where(
                    maskc & np.isfinite(xc), xc, np.float32(np.inf)
                )
            )
            got = np.asarray(
                hll.dedup_column_registers_from_sorted(
                    jnp.asarray(s), jnp.asarray(xc), jnp.asarray(maskc)
                )
            )
            h1, h2 = hll.hash_pair_numeric(jnp.asarray(xc))
            want = np.asarray(
                hll.registers_from_hash_pair(h1, h2, jnp.asarray(maskc))
            )
            np.testing.assert_array_equal(got, want)

    def test_profiler_pool_equality_end_to_end(self):
        """A profile with KLL + HLL co-planned (the pool fires) must
        report the same ApproxCountDistinct as a run with the analyzer
        alone (scatter path)."""
        from deequ_tpu.analyzers import (
            AnalysisRunner,
            ApproxCountDistinct,
            ApproxQuantiles,
        )
        from deequ_tpu.data import Dataset

        rng = np.random.default_rng(42)
        n = 30_000
        ds = Dataset.from_pydict(
            {
                "p1": (
                    np.round(rng.normal(50, 9, n) * 100) / 100
                ).astype(np.float32),
                "p2": rng.normal(0, 1, n).astype(np.float32),
            }
        )
        together = AnalysisRunner.do_analysis_run(
            ds,
            [
                ApproxCountDistinct("p1"),
                ApproxCountDistinct("p2"),
                ApproxQuantiles("p1", [0.5]),
                ApproxQuantiles("p2", [0.5]),
            ],
        )
        for col in ("p1", "p2"):
            alone = AnalysisRunner.do_analysis_run(
                ds, [ApproxCountDistinct(col)]
            )
            a = together.metric(ApproxCountDistinct(col)).value.get()
            b = alone.metric(ApproxCountDistinct(col)).value.get()
            assert a == b, (col, a, b)


class TestIntPoolDedup:
    """Range-gated integer columns ride the f32 KLL pool (r5): the
    dictionary entries cast back to the raw dtype before hashing, so
    registers stay bit-identical to the per-row integral scatter."""

    def test_int_pool_variant_matches_scatter(self):
        from deequ_tpu.sketches import hll

        rng = np.random.default_rng(51)
        B = 8192
        for vals in (
            rng.integers(1, 101, B).astype(np.int32),  # quantity-like
            rng.integers(-(1 << 24), 1 << 24, B).astype(
                np.int32
            ),  # full f32-exact range
        ):
            maskc = rng.random(B) > 0.1
            s = np.sort(
                np.where(maskc, vals.astype(np.float32), np.float32(np.inf))
            )
            got = np.asarray(
                hll.dedup_column_registers_from_sorted(
                    jnp.asarray(s),
                    jnp.asarray(vals),
                    jnp.asarray(maskc),
                )
            )
            h1, h2 = hll.hash_pair_numeric(jnp.asarray(vals))
            want = np.asarray(
                hll.registers_from_hash_pair(h1, h2, jnp.asarray(maskc))
            )
            np.testing.assert_array_equal(got, want)

    def test_end_to_end_int_pool_equality(self):
        """Quantity-style int column profiled WITH quantiles (pool
        fires) must report the same ApproxCountDistinct as alone."""
        from deequ_tpu.analyzers import (
            AnalysisRunner,
            ApproxCountDistinct,
            ApproxQuantiles,
        )
        from deequ_tpu.data import Dataset

        rng = np.random.default_rng(52)
        n = 30_000
        ds = Dataset.from_pydict(
            {
                "qty": rng.integers(1, 101, n),
                "k": rng.integers(0, 1 << 22, n),
            }
        )
        together = AnalysisRunner.do_analysis_run(
            ds,
            [
                ApproxCountDistinct("qty"),
                ApproxCountDistinct("k"),
                ApproxQuantiles("qty", [0.5]),
                ApproxQuantiles("k", [0.5]),
            ],
        )
        for col in ("qty", "k"):
            alone = AnalysisRunner.do_analysis_run(
                ds, [ApproxCountDistinct(col)]
            )
            a = together.metric(ApproxCountDistinct(col)).value.get()
            b = alone.metric(ApproxCountDistinct(col)).value.get()
            assert a == b, (col, a, b)
        assert together.metric(
            ApproxCountDistinct("qty")
        ).value.get() == pytest.approx(100, abs=2)

    def test_high_magnitude_narrow_range_not_pooled(self):
        """A narrow-RANGE int32 column at high magnitude (~2^30) must
        NOT ride the f32 pool (the cast is inexact there): its
        estimate must match the analyzer run alone (review finding)."""
        from deequ_tpu.analyzers import (
            AnalysisRunner,
            ApproxCountDistinct,
            ApproxQuantiles,
        )
        from deequ_tpu.data import Dataset

        rng = np.random.default_rng(53)
        n = 20_000
        base = 1 << 30
        vals = base + rng.integers(0, 77, n)  # width 77, magnitude 2^30
        ds = Dataset.from_pydict(
            {"a": vals, "b": rng.integers(1, 50, n)}
        )
        together = AnalysisRunner.do_analysis_run(
            ds,
            [
                ApproxCountDistinct("a"),
                ApproxCountDistinct("b"),
                ApproxQuantiles("a", [0.5]),
                ApproxQuantiles("b", [0.5]),
            ],
        )
        alone = AnalysisRunner.do_analysis_run(
            ds, [ApproxCountDistinct("a")]
        )
        a = together.metric(ApproxCountDistinct("a")).value.get()
        b = alone.metric(ApproxCountDistinct("a")).value.get()
        assert a == b, (a, b)
        assert a == pytest.approx(77, abs=2)


class TestPooledPathEdges:
    """Degenerate shapes through the r5 pooled/dedup machinery: empty
    tables, all-null pooled columns, single rows, and where-filtered
    HLL co-planned with an unfiltered KLL group (different where ->
    no pool, adaptive path)."""

    def test_empty_dataset_profiles(self):
        import pyarrow as pa

        from deequ_tpu import ColumnProfilerRunner, Dataset

        ds = Dataset.from_arrow(
            pa.table(
                {
                    "x": pa.array([], pa.float32()),
                    "q": pa.array([], pa.int64()),
                }
            )
        )
        p = ColumnProfilerRunner().on_data(ds).run()
        assert sorted(p.profiles) == ["q", "x"]

    def test_all_null_pooled_column(self):
        import pyarrow as pa

        from deequ_tpu.data import Dataset

        ds = Dataset.from_arrow(
            pa.table(
                {
                    "a": pa.array([None] * 50, pa.float32()),
                    "b": pa.array([1.5] * 50, pa.float32()),
                }
            )
        )
        ctx = AnalysisRunner.do_analysis_run(
            ds,
            [
                ApproxCountDistinct("a"),
                ApproxCountDistinct("b"),
                ApproxQuantiles("a", [0.5]),
                ApproxQuantiles("b", [0.5]),
            ],
        )
        assert ctx.metric(ApproxCountDistinct("a")).value.get() == 0.0
        assert ctx.metric(
            ApproxCountDistinct("b")
        ).value.get() == pytest.approx(1.0, rel=0.01)

    def test_single_row_pooled(self):
        from deequ_tpu.data import Dataset

        ds = Dataset.from_pydict({"x": [2.5], "y": [3]})
        ctx = AnalysisRunner.do_analysis_run(
            ds,
            [
                ApproxCountDistinct("x"),
                ApproxCountDistinct("y"),
                ApproxQuantiles("x", [0.5]),
                ApproxQuantiles("y", [0.5]),
            ],
        )
        for c in ("x", "y"):
            assert ctx.metric(
                ApproxCountDistinct(c)
            ).value.get() == pytest.approx(1.0, rel=0.01)

    def test_where_filtered_hll_beside_unfiltered_kll(self):
        from deequ_tpu.data import Dataset

        ds = Dataset.from_pydict(
            {"v": [1.0, 2.0, 2.0, 3.0] * 25, "g": [1, 0, 1, 0] * 25}
        )
        a = ApproxCountDistinct("v", where="g = 1")
        ctx = AnalysisRunner.do_analysis_run(
            ds, [a, ApproxQuantiles("v", [0.5])]
        )
        # where g=1 keeps values {1.0, 2.0}
        assert ctx.metric(a).value.get() == pytest.approx(2.0, rel=0.01)
