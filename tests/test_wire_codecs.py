"""Wire-diet streaming ingest (engine/wire.py, docs/PERF.md):
per-column wire codecs, one-pass dictionary deltas, and the staged put
pipeline.

The load-bearing assertion is DIFFERENTIAL: every metric computed over
the codec wire must equal the codecs-off oracle (today's wire) exactly
— on the resident, streaming and mesh paths alike. Codecs narrow only
where the decode provably round-trips, so equality is exact, not
approximate. The fallback leg (stats lied -> widen + retrace), the
mid-stream dictionary-growth delta, the corrupt-wire quarantine, and
the one-pass data_passes pin each get their own scenario.
"""

import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from deequ_tpu import config
from deequ_tpu.analyzers import (
    AnalysisRunner,
    ApproxCountDistinct,
    Completeness,
    DataType,
    Histogram,
    Maximum,
    Mean,
    Minimum,
    Size,
)
from deequ_tpu.data import Dataset
from deequ_tpu.engine import wire as wire_mod
from deequ_tpu.engine.resilience import RetryPolicy
from deequ_tpu.engine.scan import AnalysisEngine
from deequ_tpu.telemetry import get_telemetry
from deequ_tpu.testing.faults import FaultInjectingDataset

FAST_RETRY = RetryPolicy(max_attempts=2, sleep=lambda _s: None)


@pytest.fixture(scope="module")
def parquet_dir(tmp_path_factory):
    """Three parquet files shaped to exercise every codec family:
    - ``k_small``: int64, range [0, 90] -> i8 from stats
    - ``k_mid``:   int64, range fits i16 -> i16 from stats
    - ``k_wide``:  int64, needs the full width -> no codec
    - ``f_exact``: float64 holding f32-exact values -> f32 probe
    - ``f_lossy``: float64 with real doubles -> probe keeps f64
    - ``s_grow``:  strings whose vocabulary GROWS per file, so the
      delta protocol ships non-zero-start deltas mid-stream
    - ``s_flat``:  strings with a stable vocabulary (deltas after
      batch 1 cost zero bytes)
    - ``x``:       nullable float (masks stay on the 1-bit wire)
    """
    directory = tmp_path_factory.mktemp("wirepq")
    rng = np.random.default_rng(13)
    tables = []
    for i in range(3):
        n = 700 + i * 200
        vocab = np.array([f"w{j:03d}" for j in range((i + 1) * 6)])
        f32 = rng.normal(50.0, 9.0, n).astype(np.float32)
        x = rng.normal(0.0, 1.0, n)
        tables.append(
            pa.table(
                {
                    "k_small": pa.array(
                        rng.integers(0, 91, n, dtype=np.int64)
                    ),
                    "k_mid": pa.array(
                        rng.integers(-20_000, 20_000, n, dtype=np.int64)
                    ),
                    "k_wide": pa.array(
                        rng.integers(-(2**40), 2**40, n, dtype=np.int64)
                    ),
                    "f_exact": pa.array(f32.astype(np.float64)),
                    "f_lossy": pa.array(x * np.pi),
                    "s_grow": pa.array(
                        vocab[rng.integers(0, len(vocab), n)]
                    ),
                    "s_flat": pa.array(
                        rng.choice(["red", "green", "blue"], n)
                    ),
                    "x": pa.array(
                        x, pa.float64(), mask=(rng.random(n) < 0.1)
                    ),
                }
            )
        )
        pq.write_table(
            tables[-1], os.path.join(directory, f"part-{i}.parquet")
        )
    return str(directory), pa.concat_tables(tables)


ANALYZERS = [
    Size(),
    Completeness("x"),
    Mean("x"),
    Mean("f_exact"),
    Minimum("f_lossy"),
    Maximum("f_lossy"),
    Minimum("k_small"),
    Maximum("k_mid"),
    Mean("k_wide"),
    ApproxCountDistinct("s_grow"),
    ApproxCountDistinct("s_flat"),
    DataType("s_grow"),
    Histogram("s_flat"),
]


def _metric_values(ctx, analyzers=ANALYZERS):
    out = {}
    for a in analyzers:
        m = ctx.metric(a)
        assert m.value.is_success, (a, m.value)
        v = m.value.get()
        if hasattr(v, "values"):  # histograms compare by bucket
            v = tuple(
                (k, d.absolute) for k, d in sorted(v.values.items())
            )
        out[repr(a)] = v
    return out


def _run(dataset, wire_codecs, *, engine=None, analyzers=ANALYZERS,
         **overrides):
    with config.configure(wire_codecs=wire_codecs, **overrides):
        ctx = AnalysisRunner.do_analysis_run(
            dataset, analyzers, engine=engine
        )
    return _metric_values(ctx, analyzers)


# --------------------------------------------------------------------------
# codec unit behavior (engine/wire.py)
# --------------------------------------------------------------------------


class TestCodecTable:
    def test_narrowest_int_dtype_boundaries(self):
        assert wire_mod.narrowest_int_dtype(0, 127) == np.int8
        assert wire_mod.narrowest_int_dtype(0, 128) == np.int16
        assert wire_mod.narrowest_int_dtype(-129, 0) == np.int16
        assert wire_mod.narrowest_int_dtype(0, 2**20) == np.int32
        assert wire_mod.narrowest_int_dtype(-(2**40), 7) == np.int64

    def _int_table(self, wire=np.int8):
        table = wire_mod.CodecTable()
        table.codecs["k::values"] = wire_mod.ColumnCodec(
            "k::values", np.dtype(np.int64), np.dtype(wire), "stats"
        )
        return table

    def test_int_encode_roundtrips_and_guards(self):
        table = self._int_table()
        enc = table.encode(
            "k::values", np.array([1, 2, 127], dtype=np.int64)
        )
        assert enc.dtype == np.int8
        assert enc.astype(np.int64).tolist() == [1, 2, 127]
        with pytest.raises(wire_mod.CodecViolation) as e:
            table.encode("k::values", np.array([300], dtype=np.int64))
        assert e.value.key == "k::values"
        assert e.value.required == np.int16

    def test_widen_bumps_version_and_token(self):
        table = self._int_table()
        t0 = table.token()
        table.widen("k::values", np.dtype(np.int16))
        assert table.version == 1
        assert table.codecs["k::values"].wire == np.int16
        assert table.token() != t0
        # widening never narrows back, and hitting the canonical width
        # disables the codec entirely (identity encode)
        table.widen("k::values", np.dtype(np.int64))
        assert table.codecs["k::values"].wire == np.int64
        assert not table.codecs["k::values"].active

    def test_float_probe_narrows_only_bit_exact(self):
        table = wire_mod.CodecTable()
        for key in ("exact::values", "lossy::values"):
            table.codecs[key] = wire_mod.ColumnCodec(
                key, np.dtype(np.float64), None, "probe"
            )
        exact = np.linspace(0, 1, 64, dtype=np.float32).astype(
            np.float64
        )
        enc = table.encode("exact::values", exact)
        assert enc.dtype == np.float32
        assert np.array_equal(
            enc.astype(np.float64).view(np.int64), exact.view(np.int64)
        )
        lossy = np.array([0.1, 0.2, np.pi], dtype=np.float64)
        assert table.encode("lossy::values", lossy).dtype == np.float64

    def test_float_guard_catches_later_lossy_batch(self):
        table = wire_mod.CodecTable()
        table.codecs["f::values"] = wire_mod.ColumnCodec(
            "f::values", np.dtype(np.float64), np.dtype(np.float32),
            "probe",
        )
        with pytest.raises(wire_mod.CodecViolation):
            table.encode("f::values", np.array([0.1], dtype=np.float64))

    def test_raw_bytes_accounting(self):
        table = self._int_table()
        enc = table.encode(
            "k::values", np.arange(10, dtype=np.int64)
        )
        assert enc.nbytes == 10
        assert table.raw_bytes_of("k::values", enc) == 80
        # keys without a codec count at face value
        other = np.zeros(4, dtype=np.float32)
        assert table.raw_bytes_of("other", other) == other.nbytes

    def test_resolve_from_parquet_stats(self, parquet_dir):
        directory, _ = parquet_dir
        ds = Dataset.from_parquet(directory)
        from deequ_tpu.data.table import ColumnRequest

        requests = [
            ColumnRequest("k_small", "values"),
            ColumnRequest("k_mid", "values"),
            ColumnRequest("k_wide", "values"),
            ColumnRequest("f_lossy", "values"),
            ColumnRequest("x", "mask"),
        ]
        table = wire_mod.resolve_codecs(ds, requests, enabled=True)
        small = table.codec("k_small::values")
        assert small is not None and small.wire == np.int8
        assert small.origin == "stats"
        mid = table.codec("k_mid::values")
        assert mid is not None and mid.wire == np.int16
        # stats prove k_wide cannot narrow: no codec at all
        assert table.codec("k_wide::values") is None
        # floats defer to the first-batch probe
        lossy = table.codec("f_lossy::values")
        assert lossy is not None and lossy.wire is None
        # masks never get codecs (already 1 bit/row on the wire)
        assert table.codec("x::mask") is None
        assert wire_mod.resolve_codecs(
            ds, requests, enabled=False
        ).codecs == {}


# --------------------------------------------------------------------------
# differential identity: codec wire == codecs-off oracle, all paths
# --------------------------------------------------------------------------


class TestDifferentialIdentity:
    def test_streaming_codecs_match_oracle_and_slim_the_wire(
        self, parquet_dir
    ):
        directory, _ = parquet_dir
        tm = get_telemetry()
        raw0 = tm.counter("engine.wire_bytes_raw").value
        enc0 = tm.counter("engine.wire_bytes_encoded").value
        on = _run(
            Dataset.from_parquet(directory, read_batch_rows=512),
            True,
            device_cache_bytes=0,
            batch_size=450,
        )
        raw = tm.counter("engine.wire_bytes_raw").value - raw0
        encoded = tm.counter("engine.wire_bytes_encoded").value - enc0
        off = _run(
            Dataset.from_parquet(directory, read_batch_rows=512),
            False,
            device_cache_bytes=0,
            batch_size=450,
        )
        assert on == off
        # the diet is real: i8/i16 ints + f32 floats + narrow codes
        assert 0 < encoded < raw

    def test_streaming_matches_resident_oracle(self, parquet_dir):
        directory, full = parquet_dir
        streamed = _run(
            Dataset.from_parquet(directory, read_batch_rows=512),
            True,
            device_cache_bytes=0,
            batch_size=450,
        )
        resident = _run(Dataset.from_arrow(full), False)
        assert streamed == resident

    def test_resident_flag_is_inert(self, parquet_dir):
        """Resident plans never pack a wire; the flag must not change
        results (or anything else) there."""
        _directory, full = parquet_dir
        assert _run(Dataset.from_arrow(full), True) == _run(
            Dataset.from_arrow(full), False
        )

    def test_mesh_codecs_match_oracle(self, parquet_dir, cpu_mesh):
        """The mesh path streams unpacked (pack=False) — codecs must
        not engage, and results must match the oracle bit-for-bit."""
        directory, _ = parquet_dir
        on = _run(
            Dataset.from_parquet(directory, read_batch_rows=512),
            True,
            engine=AnalysisEngine(mesh=cpu_mesh),
            device_cache_bytes=0,
            batch_size=512,
        )
        off = _run(
            Dataset.from_parquet(directory, read_batch_rows=512),
            False,
            engine=AnalysisEngine(mesh=cpu_mesh),
            device_cache_bytes=0,
            batch_size=512,
        )
        assert on == off

    def test_dict_deltas_match_pre_pass_oracle(self, parquet_dir):
        directory, _ = parquet_dir
        deltas = _run(
            Dataset.from_parquet(directory, read_batch_rows=512),
            True,
            device_cache_bytes=0,
            batch_size=450,
            dict_deltas=True,
        )
        pre_pass = _run(
            Dataset.from_parquet(directory, read_batch_rows=512),
            True,
            device_cache_bytes=0,
            batch_size=450,
            dict_deltas=False,
        )
        assert deltas == pre_pass


# --------------------------------------------------------------------------
# dictionary deltas: mid-stream growth, one-pass pin
# --------------------------------------------------------------------------


DELTA_ANALYZERS = [
    Size(),
    Mean("x"),
    ApproxCountDistinct("s_grow"),
    ApproxCountDistinct("s_flat"),
    DataType("s_grow"),
    DataType("s_flat"),
]


class TestDictionaryDeltas:
    def test_mid_stream_growth_ships_deltas(self, parquet_dir):
        directory, full = parquet_dir
        tm = get_telemetry()
        n0 = tm.counter("engine.dict_deltas").value
        v0 = tm.counter("engine.dict_delta_values").value
        with config.configure(device_cache_bytes=0, batch_size=450):
            with tm.run("delta-growth") as cap:
                ctx = AnalysisRunner.do_analysis_run(
                    Dataset.from_parquet(directory, read_batch_rows=512),
                    DELTA_ANALYZERS,
                )
        got = _metric_values(ctx, DELTA_ANALYZERS)
        want = _metric_values(
            AnalysisRunner.do_analysis_run(
                Dataset.from_arrow(full), DELTA_ANALYZERS
            ),
            DELTA_ANALYZERS,
        )
        assert got == want
        assert tm.counter("engine.dict_deltas").value > n0
        assert tm.counter("engine.dict_delta_values").value >= v0 + 18
        events = [
            e for e in cap.final["events"]
            if e.get("event") == "dictionary_delta"
        ]
        grow = [e for e in events if e.get("column") == "s_grow"]
        # the vocabulary grows in files 2 and 3: at least one delta
        # must APPEND (start > 0) rather than re-ship from scratch
        assert any(e.get("start", 0) > 0 for e in grow)
        # the stable vocabulary ships once, then stays free
        flat_values = sum(
            e.get("count", 0)
            for e in events
            if e.get("column") == "s_flat"
        )
        assert flat_values == 3

    def test_string_suite_is_one_pass(self, parquet_dir):
        """The headline: string-code suites traverse the parquet
        source EXACTLY once — no ``_collect_uniques`` pre-pass. The
        pre-pass oracle (dict_deltas off) pays one extra traversal per
        string column."""
        directory, _ = parquet_dir
        tm = get_telemetry()
        passes = tm.counter("engine.data_passes")
        with config.configure(device_cache_bytes=0, batch_size=450):
            before = passes.value
            AnalysisRunner.do_analysis_run(
                Dataset.from_parquet(directory, read_batch_rows=512),
                DELTA_ANALYZERS,
            )
            assert passes.value - before == 1
            before = passes.value
            with config.configure(dict_deltas=False):
                AnalysisRunner.do_analysis_run(
                    Dataset.from_parquet(directory, read_batch_rows=512),
                    DELTA_ANALYZERS,
                )
            assert passes.value - before == 3  # scan + 2 dictionaries

    def test_oversized_dictionary_overflows_loudly(self, tmp_path):
        """A first-run dictionary larger than dict_delta_capacity is a
        hard error naming the knob — never a silent wrong metric."""
        rng = np.random.default_rng(3)
        n = 600
        pq.write_table(
            pa.table(
                {
                    "s": pa.array([f"u{j}" for j in range(n)]),
                    "t": pa.array(
                        [f"v{j}" for j in rng.integers(0, n, n)]
                    ),
                }
            ),
            str(tmp_path / "wide.parquet"),
        )
        with config.configure(
            device_cache_bytes=0,
            batch_size=256,
            dict_delta_capacity=64,
            scan_retry=RetryPolicy(max_attempts=1),
        ):
            ctx = AnalysisRunner.do_analysis_run(
                Dataset.from_parquet(str(tmp_path)),
                [ApproxCountDistinct("s"), ApproxCountDistinct("t")],
            )
        value = ctx.metric(ApproxCountDistinct("s")).value
        assert not value.is_success
        assert "dict_delta_capacity=64" in repr(value)


# --------------------------------------------------------------------------
# fallback: stats lied -> widen + retrace, same metrics
# --------------------------------------------------------------------------


class TestStatsFallback:
    def test_stats_violating_batch_widens_and_stays_correct(
        self, tmp_path
    ):
        """File 0 fits the (lying) i8 claim; file 1 carries values that
        don't. The guard catches the violation on the prefetch thread,
        widens the codec (one ``wire_codec_widened`` event), re-packs
        the same batch, and every metric still matches the oracle."""
        rng = np.random.default_rng(23)
        small = rng.integers(0, 90, 600, dtype=np.int64)
        big = rng.integers(200, 9_000, 600, dtype=np.int64)
        pq.write_table(
            pa.table({"k": pa.array(small)}),
            str(tmp_path / "part-0.parquet"),
        )
        pq.write_table(
            pa.table({"k": pa.array(big)}),
            str(tmp_path / "part-1.parquet"),
        )
        analyzers = [Size(), Minimum("k"), Maximum("k"), Mean("k")]
        want = _metric_values(
            AnalysisRunner.do_analysis_run(
                Dataset.from_arrow(
                    pa.table(
                        {"k": pa.array(np.concatenate([small, big]))}
                    )
                ),
                analyzers,
            ),
            analyzers,
        )
        ds = Dataset.from_parquet(str(tmp_path), read_batch_rows=512)
        ds.integral_range = lambda column: (0, 90)  # the lie
        tm = get_telemetry()
        # a listener, not ``tm.run``: the violation is caught and the
        # table widened on the PREFETCH thread, outside the main
        # thread's capture scope
        from deequ_tpu.telemetry import CollectingRunListener

        listener = tm.add_listener(CollectingRunListener())
        try:
            with config.configure(device_cache_bytes=0, batch_size=300):
                ctx = AnalysisRunner.do_analysis_run(ds, analyzers)
        finally:
            tm.remove_listener(listener)
        assert _metric_values(ctx, analyzers) == want
        widened = [
            e for e in listener.engine_events
            if e.get("event") == "wire_codec_widened"
        ]
        assert len(widened) == 1
        assert widened[0]["key"] == "k::values"
        assert widened[0]["wire_from"] == "int8"
        assert widened[0]["wire_to"] == "int16"
        assert widened[0]["origin"] == "stats"
        # no quarantine, no retry: a lost narrowing bet is not a fault
        assert ctx.degradation is None or (
            ctx.degradation.batches_quarantined == 0
        )


# --------------------------------------------------------------------------
# corrupt encoded wire -> quarantine (testing/faults.py)
# --------------------------------------------------------------------------


class TestCorruptWire:
    def test_corrupt_encoded_batch_is_quarantined(self):
        """Corruption on the ENCODED wire (truncated leaves after the
        codec engaged) is detected by the layout guard and quarantined
        — the codec layer must not turn integrity failures into wrong
        metrics or widen-loops."""
        rng = np.random.default_rng(5)
        n = 1000
        ds = FaultInjectingDataset(
            Dataset.from_pydict(
                {
                    "a": rng.normal(size=n).tolist(),
                    "k": rng.integers(0, 80, n).tolist(),
                }
            ),
            corrupt={1},
        )
        tm = get_telemetry()
        enc0 = tm.counter("engine.wire_bytes_encoded").value
        raw0 = tm.counter("engine.wire_bytes_raw").value
        with config.configure(
            device_cache_bytes=0, batch_size=104, scan_retry=FAST_RETRY
        ):
            ctx = AnalysisRunner.do_analysis_run(
                ds, [Size(), Mean("a"), Maximum("k")]
            )
        degr = ctx.degradation
        assert degr.batches_quarantined == 1
        assert degr.error_classes == ["BatchIntegrityError"]
        assert ctx.metric(Size()).value.get() == n - 104
        # the codec DID engage on the healthy batches
        raw = tm.counter("engine.wire_bytes_raw").value - raw0
        encoded = tm.counter("engine.wire_bytes_encoded").value - enc0
        assert 0 < encoded < raw


# --------------------------------------------------------------------------
# parallel ingest over the codec wire (engine/ingest.py "r10")
# --------------------------------------------------------------------------


class TestParallelIngestDifferential:
    """The r10 ordered worker pool encodes batches CONCURRENTLY but
    releases them in source order; every codec behavior above must be
    invariant under worker count, with workers=1 running the exact
    pre-pool path as the oracle."""

    def test_codec_wire_is_worker_count_invariant(self, parquet_dir):
        directory, _ = parquet_dir
        tm = get_telemetry()

        def wire(workers):
            raw0 = tm.counter("engine.wire_bytes_raw").value
            enc0 = tm.counter("engine.wire_bytes_encoded").value
            vals = _run(
                Dataset.from_parquet(directory, read_batch_rows=512),
                True,
                device_cache_bytes=0,
                batch_size=450,
                ingest_workers=workers,
            )
            return (
                vals,
                tm.counter("engine.wire_bytes_raw").value - raw0,
                tm.counter("engine.wire_bytes_encoded").value - enc0,
            )

        ref, raw1, enc1 = wire(1)
        got, raw4, enc4 = wire(4)
        assert got == ref
        # same batches, same codecs -> the same bytes cross the wire
        assert (raw4, enc4) == (raw1, enc1)
        assert 0 < enc4 < raw4

    def test_pool_deltas_match_pre_pass_oracle(self, parquet_dir):
        """Both axes at once: dictionary deltas cut at ordered release
        under 4 workers vs the pre-pass consts path under 1."""
        directory, _ = parquet_dir
        pooled = _run(
            Dataset.from_parquet(directory, read_batch_rows=512),
            True,
            device_cache_bytes=0,
            batch_size=450,
            dict_deltas=True,
            ingest_workers=4,
        )
        oracle = _run(
            Dataset.from_parquet(directory, read_batch_rows=512),
            True,
            device_cache_bytes=0,
            batch_size=450,
            dict_deltas=False,
            ingest_workers=1,
        )
        assert pooled == oracle

    def test_pool_mesh_matches_oracle(self, parquet_dir, cpu_mesh):
        directory, _ = parquet_dir
        got = _run(
            Dataset.from_parquet(directory, read_batch_rows=512),
            True,
            engine=AnalysisEngine(mesh=cpu_mesh),
            device_cache_bytes=0,
            batch_size=512,
            ingest_workers=4,
        )
        ref = _run(
            Dataset.from_parquet(directory, read_batch_rows=512),
            True,
            engine=AnalysisEngine(mesh=cpu_mesh),
            device_cache_bytes=0,
            batch_size=512,
            ingest_workers=1,
        )
        assert got == ref
