"""Scan-shareable analyzers: Size, Completeness, Compliance, Mean, Sum,
Minimum, Maximum, MinLength, MaxLength, StandardDeviation, Correlation,
RatioOfSums, PatternMatch, ColumnCount.

Reference: one file per analyzer under
``src/main/scala/com/amazon/deequ/analyzers/`` (SURVEY.md §2.2). Each
analyzer here compiles to a (init, update, merge) triple over fixed-shape
states; the runner concatenates every requested analyzer's update into ONE
jitted function per batch, so N analyzers still cost one data pass — the
TPU equivalent of the reference fusing aggregation expressions into a
single ``df.agg`` job (SURVEY.md §3.1 ★#1).

Null semantics follow the reference: per-column validity masks play the
role of SQL null-skipping aggregates (``COUNT(col)`` vs ``COUNT(*)``,
SURVEY.md §7 hard part #4).
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Callable, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from deequ_tpu.analyzers.base import (
    Analyzer,
    EmptyStateException,
    IllegalAnalyzerParameterException,
    Precondition,
    ScanOps,
    ScanShareableAnalyzer,
    has_column,
    is_numeric,
    is_string,
)
from deequ_tpu.analyzers import states as S
from deequ_tpu.data.table import ROW_MASK, ColumnRequest, Dataset
from deequ_tpu.metrics.metric import DoubleMetric, Entity
from deequ_tpu.sql.predicate import compile_predicate

_F64 = jnp.float64
_I64 = jnp.int64


def _acc_float():
    from deequ_tpu import config

    return config.options().accumulation_float()


def _compile_where(
    where: Optional[str], dataset: Dataset
) -> Tuple[Optional[Callable], List[ColumnRequest]]:
    """Compile an optional where-filter; returns (complies_fn, requests)."""
    if where is None:
        return None, []
    pred = compile_predicate(where, dataset)
    return pred.complies, list(pred.requests)


def _row_mask(batch, where_fn) -> jnp.ndarray:
    mask = batch[ROW_MASK]
    if where_fn is not None:
        mask = mask & where_fn(batch)
    return mask


def _col_mask(batch, column: str, where_fn) -> jnp.ndarray:
    mask = batch[f"{column}::mask"]
    if where_fn is not None:
        mask = mask & where_fn(batch)
    return mask


# TPU dtype discipline (VERDICT.md weak #4): float64 is software-emulated
# on TPU, so per-element work runs in the column's NATIVE dtype (XLA's
# tree reduction keeps f32 summation error ~ulp*log n) and only the
# per-batch *scalar* results are cast into the accumulation dtype —
# a handful of emulated scalar ops per batch instead of an emulated
# elementwise pass over millions of rows.


def _msum(x, mask):
    """Masked sum: elementwise in native dtype, scalar in accumulation
    dtype. Integral columns always widen per element to f64 (exactness
    over speed — int overflow/rounding must not depend on the float
    accumulation knob); only the scalar result follows the knob."""
    acc = _acc_float()
    if jnp.issubdtype(x.dtype, jnp.floating):
        return jnp.sum(jnp.where(mask, x, jnp.zeros((), x.dtype))).astype(acc)
    return jnp.sum(jnp.where(mask, x, 0).astype(_F64)).astype(acc)


def _mmin(x, mask, axis=None):
    """Masked min under Spark's ordering: NaN ranks above every value,
    so NaN values lose to any real value and win only when ALL masked
    values are NaN (SURVEY.md §2.2; pinned by tests/goldens). Scalar
    always f64 — min/max has no accumulation-error concern, and f64 is
    exact for f32 inputs and ints up to 2^53 (the reference's double
    semantics). A fixed result dtype also keeps the lax.scan carry
    stable across column types."""
    if jnp.issubdtype(x.dtype, jnp.floating):
        # no real (non-NaN) contribution -> NaN, the nan_largest_min
        # IDENTITY (states.MinState): an empty batch must not emit
        # +inf, which would beat a later all-NaN batch's NaN in the
        # carry merge. The count guard keeps identity NaN from ever
        # surfacing for truly empty columns.
        real = mask & ~jnp.isnan(x)
        m = jnp.min(
            jnp.where(real, x, jnp.array(jnp.inf, x.dtype)), axis=axis
        ).astype(_F64)
        return jnp.where(
            jnp.any(real, axis=axis), m, jnp.array(jnp.nan, _F64)
        )
    neutral = jnp.array(jnp.iinfo(x.dtype).max, x.dtype)
    return jnp.min(jnp.where(mask, x, neutral), axis=axis).astype(_F64)


def _mmax(x, mask):
    if jnp.issubdtype(x.dtype, jnp.floating):
        neutral = jnp.array(-jnp.inf, x.dtype)
    else:
        neutral = jnp.array(jnp.iinfo(x.dtype).min, x.dtype)
    return jnp.max(jnp.where(mask, x, neutral)).astype(_F64)


def _mcount(mask) -> jnp.ndarray:
    # batch counts fit i32 (batches are <2^31 rows); the cross-batch
    # carry is an exact i64 scalar add
    return jnp.sum(mask, dtype=jnp.int32).astype(_I64)


# --------------------------------------------------------------------------


@dataclass(frozen=True)
class Size(ScanShareableAnalyzer):
    """Row count (reference: analyzers/Size.scala; state NumMatches)."""

    where: Optional[str] = None

    @property
    def entity(self) -> Entity:
        return Entity.DATASET

    @property
    def instance(self) -> str:
        return "*"

    def device_requests(self, dataset: Dataset) -> List[ColumnRequest]:
        _, reqs = _compile_where(self.where, dataset)
        return reqs

    def make_ops(self, dataset: Dataset) -> ScanOps:
        where_fn, _ = _compile_where(self.where, dataset)

        def update(state: S.NumMatches, batch) -> S.NumMatches:
            return S.NumMatches(
                state.num_matches + _mcount(_row_mask(batch, where_fn))
            )

        return ScanOps(S.NumMatches.identity, update, S.NumMatches.merge)

    def compute_metric_from_state(self, state) -> DoubleMetric:
        if state is None:
            state = S.NumMatches.identity()
        return DoubleMetric.success(
            self.entity, "Size", self.instance, float(state.num_matches)
        )


@dataclass(frozen=True)
class Completeness(ScanShareableAnalyzer):
    """Fraction of non-null values (reference: analyzers/Completeness.scala;
    state NumMatchesAndCount: non-nulls over rows passing the filter)."""

    column: str
    where: Optional[str] = None

    @property
    def instance(self) -> str:
        return self.column

    def preconditions(self) -> List[Precondition]:
        return [has_column(self.column)]

    def device_requests(self, dataset: Dataset) -> List[ColumnRequest]:
        _, reqs = _compile_where(self.where, dataset)
        return [ColumnRequest(self.column, "mask")] + reqs

    def make_ops(self, dataset: Dataset) -> ScanOps:
        where_fn, _ = _compile_where(self.where, dataset)
        col = self.column

        def update(state: S.NumMatchesAndCount, batch) -> S.NumMatchesAndCount:
            rows = _row_mask(batch, where_fn)
            valid = batch[f"{col}::mask"] & rows
            return S.NumMatchesAndCount(
                state.num_matches + _mcount(valid),
                state.count + _mcount(rows),
            )

        return ScanOps(
            S.NumMatchesAndCount.identity, update, S.NumMatchesAndCount.merge
        )

    def compute_metric_from_state(self, state) -> DoubleMetric:
        if state is None or int(state.count) == 0:
            return self.to_failure_metric(
                EmptyStateException(
                    "Empty state for analyzer Completeness, all input values "
                    "were NULL or filtered."
                )
            )
        return DoubleMetric.success(
            self.entity,
            "Completeness",
            self.instance,
            float(state.num_matches) / float(state.count),
        )


@dataclass(frozen=True)
class Compliance(ScanShareableAnalyzer):
    """Fraction of rows satisfying a SQL predicate (reference:
    analyzers/Compliance.scala). The predicate compiles to JAX ops; string
    comparisons run on dictionary codes (deequ_tpu.sql.predicate)."""

    instance_name: str
    predicate: str
    where: Optional[str] = None

    @property
    def entity(self) -> Entity:
        return Entity.DATASET

    @property
    def instance(self) -> str:
        return self.instance_name

    def device_requests(self, dataset: Dataset) -> List[ColumnRequest]:
        pred = compile_predicate(self.predicate, dataset)
        _, where_reqs = _compile_where(self.where, dataset)
        return list(pred.requests) + where_reqs

    def make_ops(self, dataset: Dataset) -> ScanOps:
        pred = compile_predicate(self.predicate, dataset)
        where_fn, _ = _compile_where(self.where, dataset)

        def update(state: S.NumMatchesAndCount, batch) -> S.NumMatchesAndCount:
            rows = _row_mask(batch, where_fn)
            return S.NumMatchesAndCount(
                state.num_matches + _mcount(pred.complies(batch) & rows),
                state.count + _mcount(rows),
            )

        return ScanOps(
            S.NumMatchesAndCount.identity, update, S.NumMatchesAndCount.merge
        )

    def compute_metric_from_state(self, state) -> DoubleMetric:
        if state is None or int(state.count) == 0:
            return self.to_failure_metric(
                EmptyStateException("Empty state for analyzer Compliance.")
            )
        return DoubleMetric.success(
            self.entity,
            "Compliance",
            self.instance,
            float(state.num_matches) / float(state.count),
        )


@dataclass(frozen=True)
class PatternMatch(ScanShareableAnalyzer):
    """Fraction of rows whose value matches a regex (reference:
    analyzers/PatternMatch.scala). TPU design: the regex is evaluated
    host-side once over the column *dictionary* (small), producing a bool
    lookup table; the device pass is a gather + sum over codes — strings
    never reach the accelerator (SURVEY.md §7 hard part #3)."""

    column: str
    pattern: str
    where: Optional[str] = None

    @property
    def instance(self) -> str:
        return self.column

    def preconditions(self) -> List[Precondition]:
        return [has_column(self.column), is_string(self.column)]

    def device_requests(self, dataset: Dataset) -> List[ColumnRequest]:
        _, reqs = _compile_where(self.where, dataset)
        return [
            ColumnRequest(self.column, "codes"),
            ColumnRequest(self.column, "mask"),
        ] + reqs

    def make_ops(self, dataset: Dataset) -> ScanOps:
        from deequ_tpu.analyzers.base import pad_pow2

        where_fn, _ = _compile_where(self.where, dataset)
        col = self.column
        dictionary = dataset.dictionary(col)
        prog = re.compile(self.pattern)
        table = np.zeros(max(len(dictionary), 1), dtype=bool)
        for i, value in enumerate(dictionary):
            if value is not None and prog.search(str(value)):
                table[i] = True

        # LUT enters the scan as a runtime input (pow2-padded), so the
        # compiled program is shared across datasets — see ScanOps.consts
        def update(
            state: S.NumMatchesAndCount, batch, consts
        ) -> S.NumMatchesAndCount:
            lut = consts["lut"]
            rows = _row_mask(batch, where_fn)
            # codes arrive wire-narrowed (int16 for small dicts); the
            # LUT gather's clip bound must not overflow when a >32k
            # dictionary pads past the int16 range
            codes = batch[f"{col}::codes"].astype(jnp.int32)
            valid = batch[f"{col}::mask"] & rows
            hits = lut[jnp.clip(codes, 0, lut.shape[0] - 1)] & valid
            return S.NumMatchesAndCount(
                state.num_matches + _mcount(hits),
                state.count + _mcount(rows),
            )

        return ScanOps(
            S.NumMatchesAndCount.identity,
            update,
            S.NumMatchesAndCount.merge,
            consts={"lut": pad_pow2(table, False)},
        )

    def compute_metric_from_state(self, state) -> DoubleMetric:
        if state is None or int(state.count) == 0:
            return self.to_failure_metric(
                EmptyStateException("Empty state for analyzer PatternMatch.")
            )
        return DoubleMetric.success(
            self.entity,
            "PatternMatch",
            self.instance,
            float(state.num_matches) / float(state.count),
        )


class _NumericColumnAnalyzer(ScanShareableAnalyzer):
    """Shared plumbing for single-numeric-column analyzers."""

    column: str
    where: Optional[str]

    def preconditions(self) -> List[Precondition]:
        return [has_column(self.column), is_numeric(self.column)]

    @property
    def instance(self) -> str:
        return self.column

    def device_requests(self, dataset: Dataset) -> List[ColumnRequest]:
        _, reqs = _compile_where(self.where, dataset)
        return [
            ColumnRequest(self.column, "values"),
            ColumnRequest(self.column, "mask"),
        ] + reqs


@dataclass(frozen=True)
class Sum(_NumericColumnAnalyzer):
    """Sum of a numeric column (reference: analyzers/Sum.scala)."""

    column: str
    where: Optional[str] = None

    def make_ops(self, dataset: Dataset) -> ScanOps:
        where_fn, _ = _compile_where(self.where, dataset)
        col = self.column

        def update(state: S.SumState, batch) -> S.SumState:
            mask = _col_mask(batch, col, where_fn)
            return S.SumState(
                state.sum_value + _msum(batch[f"{col}::values"], mask),
                state.count + _mcount(mask),
            )

        return ScanOps(S.SumState.identity, update, S.SumState.merge)

    def compute_metric_from_state(self, state) -> DoubleMetric:
        if state is None or int(state.count) == 0:
            return self.to_failure_metric(
                EmptyStateException("Empty state for analyzer Sum.")
            )
        return DoubleMetric.success(
            self.entity, "Sum", self.instance, float(state.sum_value)
        )


@dataclass(frozen=True)
class Mean(_NumericColumnAnalyzer):
    """Arithmetic mean (reference: analyzers/Mean.scala; MeanState)."""

    column: str
    where: Optional[str] = None

    def make_ops(self, dataset: Dataset) -> ScanOps:
        where_fn, _ = _compile_where(self.where, dataset)
        col = self.column

        def update(state: S.MeanState, batch) -> S.MeanState:
            mask = _col_mask(batch, col, where_fn)
            return S.MeanState(
                state.total + _msum(batch[f"{col}::values"], mask),
                state.count + _mcount(mask),
            )

        return ScanOps(S.MeanState.identity, update, S.MeanState.merge)

    def compute_metric_from_state(self, state) -> DoubleMetric:
        if state is None or int(state.count) == 0:
            return self.to_failure_metric(
                EmptyStateException("Empty state for analyzer Mean.")
            )
        return DoubleMetric.success(
            self.entity,
            "Mean",
            self.instance,
            float(state.total) / float(state.count),
        )


@dataclass(frozen=True)
class Minimum(_NumericColumnAnalyzer):
    """Minimum of a numeric column (reference: analyzers/Minimum.scala)."""

    column: str
    where: Optional[str] = None

    def make_ops(self, dataset: Dataset) -> ScanOps:
        where_fn, _ = _compile_where(self.where, dataset)
        col = self.column

        def update(state: S.MinState, batch) -> S.MinState:
            mask = _col_mask(batch, col, where_fn)
            return S.MinState(
                S.nan_largest_min(
                    state.min_value, _mmin(batch[f"{col}::values"], mask)
                ),
                state.count + _mcount(mask),
            )

        return ScanOps(S.MinState.identity, update, S.MinState.merge)

    def compute_metric_from_state(self, state) -> DoubleMetric:
        if state is None or int(state.count) == 0:
            return self.to_failure_metric(
                EmptyStateException("Empty state for analyzer Minimum.")
            )
        # -0.0 normalizes to 0.0 (Spark's NormalizeFloatingNumbers; also
        # backend-independent — TPU min lowering loses the -0.0 sign
        # where CPU keeps it). Host-side add: XLA would fold it away.
        return DoubleMetric.success(
            self.entity, "Minimum", self.instance,
            float(state.min_value) + 0.0,
        )


@dataclass(frozen=True)
class Maximum(_NumericColumnAnalyzer):
    """Maximum of a numeric column (reference: analyzers/Maximum.scala)."""

    column: str
    where: Optional[str] = None

    def make_ops(self, dataset: Dataset) -> ScanOps:
        where_fn, _ = _compile_where(self.where, dataset)
        col = self.column

        def update(state: S.MaxState, batch) -> S.MaxState:
            mask = _col_mask(batch, col, where_fn)
            return S.MaxState(
                jnp.maximum(
                    state.max_value, _mmax(batch[f"{col}::values"], mask)
                ),
                state.count + _mcount(mask),
            )

        return ScanOps(S.MaxState.identity, update, S.MaxState.merge)

    def compute_metric_from_state(self, state) -> DoubleMetric:
        if state is None or int(state.count) == 0:
            return self.to_failure_metric(
                EmptyStateException("Empty state for analyzer Maximum.")
            )
        return DoubleMetric.success(
            self.entity, "Maximum", self.instance,
            float(state.max_value) + 0.0,  # -0.0 -> 0.0, see Minimum
        )


class _LengthAnalyzer(ScanShareableAnalyzer):
    column: str
    where: Optional[str]

    def preconditions(self) -> List[Precondition]:
        return [has_column(self.column), is_string(self.column)]

    @property
    def instance(self) -> str:
        return self.column

    def device_requests(self, dataset: Dataset) -> List[ColumnRequest]:
        _, reqs = _compile_where(self.where, dataset)
        return [
            ColumnRequest(self.column, "lengths"),
            ColumnRequest(self.column, "mask"),
        ] + reqs


@dataclass(frozen=True)
class MinLength(_LengthAnalyzer):
    """Minimum string length (reference: analyzers/MinLength.scala; null
    behavior = Ignore, matching the reference default)."""

    column: str
    where: Optional[str] = None

    def make_ops(self, dataset: Dataset) -> ScanOps:
        where_fn, _ = _compile_where(self.where, dataset)
        col = self.column

        def update(state: S.MinState, batch) -> S.MinState:
            mask = _col_mask(batch, col, where_fn)
            return S.MinState(
                # nan_largest_min, NOT jnp.minimum: the carry identity
                # is NaN (states.MinState), which plain minimum would
                # propagate over every real length
                S.nan_largest_min(
                    state.min_value, _mmin(batch[f"{col}::lengths"], mask)
                ),
                state.count + _mcount(mask),
            )

        return ScanOps(S.MinState.identity, update, S.MinState.merge)

    def compute_metric_from_state(self, state) -> DoubleMetric:
        if state is None or int(state.count) == 0:
            return self.to_failure_metric(
                EmptyStateException("Empty state for analyzer MinLength.")
            )
        return DoubleMetric.success(
            self.entity, "MinLength", self.instance, float(state.min_value)
        )


@dataclass(frozen=True)
class MaxLength(_LengthAnalyzer):
    """Maximum string length (reference: analyzers/MaxLength.scala)."""

    column: str
    where: Optional[str] = None

    def make_ops(self, dataset: Dataset) -> ScanOps:
        where_fn, _ = _compile_where(self.where, dataset)
        col = self.column

        def update(state: S.MaxState, batch) -> S.MaxState:
            mask = _col_mask(batch, col, where_fn)
            return S.MaxState(
                jnp.maximum(
                    state.max_value, _mmax(batch[f"{col}::lengths"], mask)
                ),
                state.count + _mcount(mask),
            )

        return ScanOps(S.MaxState.identity, update, S.MaxState.merge)

    def compute_metric_from_state(self, state) -> DoubleMetric:
        if state is None or int(state.count) == 0:
            return self.to_failure_metric(
                EmptyStateException("Empty state for analyzer MaxLength.")
            )
        return DoubleMetric.success(
            self.entity, "MaxLength", self.instance, float(state.max_value)
        )


@dataclass(frozen=True)
class StandardDeviation(_NumericColumnAnalyzer):
    """Population standard deviation via a mergeable Welford state
    (reference: analyzers/StandardDeviation.scala). The batch update
    computes (n, mean, m2) for the batch vectorized, then merges it into
    the carry with the Chan/Welford combine — numerically stable and a
    pure monoid, so the same merge is the mesh collective."""

    column: str
    where: Optional[str] = None

    def make_ops(self, dataset: Dataset) -> ScanOps:
        where_fn, _ = _compile_where(self.where, dataset)
        col = self.column

        def update(
            state: S.StandardDeviationState, batch
        ) -> S.StandardDeviationState:
            mask = _col_mask(batch, col, where_fn)
            x = batch[f"{col}::values"]
            if not jnp.issubdtype(x.dtype, jnp.floating):
                # integral columns widen to f64 regardless of the knob
                # (f32 would corrupt large ints, e.g. int64 timestamps)
                x = x.astype(_F64)
            # Welford state stays f64: n is an exact count and the
            # moments are per-batch scalars (see states.py identity)
            nb = _mcount(mask).astype(_F64)
            safe_nb = jnp.maximum(nb, 1.0)
            mean_b = _msum(x, mask).astype(_F64) / safe_nb
            # second moment: elementwise in the column dtype around the
            # batch mean; only the scalar widens to f64
            dx = jnp.where(mask, x - mean_b.astype(x.dtype), 0)
            m2_b = jnp.sum(dx * dx).astype(_F64)
            batch_state = S.StandardDeviationState(
                nb, jnp.where(nb > 0, mean_b, 0.0), jnp.where(nb > 0, m2_b, 0.0)
            )
            return S.StandardDeviationState.merge(state, batch_state)

        return ScanOps(
            S.StandardDeviationState.identity,
            update,
            S.StandardDeviationState.merge,
        )

    def compute_metric_from_state(self, state) -> DoubleMetric:
        if state is None or float(state.n) == 0:
            return self.to_failure_metric(
                EmptyStateException(
                    "Empty state for analyzer StandardDeviation."
                )
            )
        return DoubleMetric.success(
            self.entity,
            "StandardDeviation",
            self.instance,
            float(np.sqrt(float(state.m2) / float(state.n))),
        )


@dataclass(frozen=True)
class Correlation(ScanShareableAnalyzer):
    """Pearson correlation of two numeric columns (reference:
    analyzers/Correlation.scala; CorrelationState with Spark Corr-style
    mergeable co-moments). Rows where either value is null are skipped."""

    first_column: str
    second_column: str
    where: Optional[str] = None

    @property
    def entity(self) -> Entity:
        return Entity.MULTICOLUMN

    @property
    def instance(self) -> str:
        return f"{self.first_column},{self.second_column}"

    def preconditions(self) -> List[Precondition]:
        return [
            has_column(self.first_column),
            is_numeric(self.first_column),
            has_column(self.second_column),
            is_numeric(self.second_column),
        ]

    def device_requests(self, dataset: Dataset) -> List[ColumnRequest]:
        _, reqs = _compile_where(self.where, dataset)
        return [
            ColumnRequest(self.first_column, "values"),
            ColumnRequest(self.first_column, "mask"),
            ColumnRequest(self.second_column, "values"),
            ColumnRequest(self.second_column, "mask"),
        ] + reqs

    def make_ops(self, dataset: Dataset) -> ScanOps:
        where_fn, _ = _compile_where(self.where, dataset)
        ca, cb = self.first_column, self.second_column

        def update(state: S.CorrelationState, batch) -> S.CorrelationState:
            mask = batch[f"{ca}::mask"] & batch[f"{cb}::mask"]
            mask = mask & _row_mask(batch, where_fn)
            x = batch[f"{ca}::values"]
            y = batch[f"{cb}::values"]
            if not jnp.issubdtype(x.dtype, jnp.floating):
                x = x.astype(_F64)
            if not jnp.issubdtype(y.dtype, jnp.floating):
                y = y.astype(_F64)
            # co-moment state stays f64 like the Welford state
            nb = _mcount(mask).astype(_F64)
            safe_nb = jnp.maximum(nb, 1.0)
            x_avg = _msum(x, mask).astype(_F64) / safe_nb
            y_avg = _msum(y, mask).astype(_F64) / safe_nb
            dx = jnp.where(mask, x - x_avg.astype(x.dtype), 0)
            dy = jnp.where(mask, y - y_avg.astype(y.dtype), 0)
            batch_state = S.CorrelationState(
                nb,
                jnp.where(nb > 0, x_avg, 0.0),
                jnp.where(nb > 0, y_avg, 0.0),
                jnp.sum(dx * dy).astype(_F64),
                jnp.sum(dx * dx).astype(_F64),
                jnp.sum(dy * dy).astype(_F64),
            )
            return S.CorrelationState.merge(state, batch_state)

        return ScanOps(
            S.CorrelationState.identity, update, S.CorrelationState.merge
        )

    def compute_metric_from_state(self, state) -> DoubleMetric:
        if state is None or float(state.n) == 0:
            return self.to_failure_metric(
                EmptyStateException("Empty state for analyzer Correlation.")
            )
        # sqrt of the PRODUCT, like Spark's Corr (sqrt(x)*sqrt(y) is
        # not float-equivalent: exact linear dependence must yield
        # exactly 1.0); zero variance gives 0/0 = NaN as a SUCCESSFUL
        # metric value, matching Spark/deequ (r4 review + goldens).
        # The product form overflows to inf when both m_k exceed
        # ~1e154 and underflows to 0 when both sit below ~1e-162 —
        # fall back to sqrt(x)*sqrt(y) in either regime (finite
        # nonzero inputs, finite nonzero answer), keeping the product
        # form for the exact linear-dependence == 1.0 case
        # (r4 advisory + review finding).
        x_mk, y_mk = float(state.x_mk), float(state.y_mk)
        product = x_mk * y_mk
        # < tiny (not just == 0): a subnormal product carries too few
        # bits and can report |r| > 1 (review finding)
        degenerate = (not np.isfinite(product)) or (
            product < float(np.finfo(np.float64).tiny)
            and x_mk != 0.0
            and y_mk != 0.0
        )
        if degenerate and np.isfinite(x_mk) and np.isfinite(y_mk):
            denom = float(np.sqrt(x_mk) * np.sqrt(y_mk))
        else:
            denom = float(np.sqrt(product))
        with np.errstate(invalid="ignore", divide="ignore"):
            value = (
                float(np.float64(state.ck) / denom)
                if denom != 0.0
                else float("nan")
            )
        return DoubleMetric.success(
            self.entity, "Correlation", self.instance, value
        )


@dataclass(frozen=True)
class RatioOfSums(ScanShareableAnalyzer):
    """sum(numerator)/sum(denominator) (reference: analyzers/RatioOfSums.scala,
    newer upstream — SURVEY.md §2.2)."""

    numerator: str
    denominator: str
    where: Optional[str] = None

    @property
    def entity(self) -> Entity:
        return Entity.MULTICOLUMN

    @property
    def instance(self) -> str:
        return f"{self.numerator},{self.denominator}"

    def preconditions(self) -> List[Precondition]:
        return [
            has_column(self.numerator),
            is_numeric(self.numerator),
            has_column(self.denominator),
            is_numeric(self.denominator),
        ]

    def device_requests(self, dataset: Dataset) -> List[ColumnRequest]:
        _, reqs = _compile_where(self.where, dataset)
        return [
            ColumnRequest(self.numerator, "values"),
            ColumnRequest(self.numerator, "mask"),
            ColumnRequest(self.denominator, "values"),
            ColumnRequest(self.denominator, "mask"),
        ] + reqs

    def make_ops(self, dataset: Dataset) -> ScanOps:
        where_fn, _ = _compile_where(self.where, dataset)
        ca, cb = self.numerator, self.denominator

        def update(state: S.SumPairState, batch) -> S.SumPairState:
            rows = _row_mask(batch, where_fn)
            ma = batch[f"{ca}::mask"] & rows
            mb = batch[f"{cb}::mask"] & rows
            return S.SumPairState(
                state.sum_a + _msum(batch[f"{ca}::values"], ma),
                state.sum_b + _msum(batch[f"{cb}::values"], mb),
                state.count + _mcount(rows),
            )

        return ScanOps(S.SumPairState.identity, update, S.SumPairState.merge)

    def compute_metric_from_state(self, state) -> DoubleMetric:
        if state is None or int(state.count) == 0:
            return self.to_failure_metric(
                EmptyStateException("Empty state for analyzer RatioOfSums.")
            )
        if float(state.sum_b) == 0.0:
            return self.to_failure_metric(
                IllegalAnalyzerParameterException(
                    "Denominator sum is zero in RatioOfSums."
                )
            )
        return DoubleMetric.success(
            self.entity,
            "RatioOfSums",
            self.instance,
            float(state.sum_a) / float(state.sum_b),
        )


@dataclass(frozen=True)
class ColumnCount(Analyzer):
    """Number of columns (reference: analyzers/ColumnCount.scala) — a
    schema-only analyzer; the runner answers it without a scan."""

    @property
    def entity(self) -> Entity:
        return Entity.DATASET

    @property
    def instance(self) -> str:
        return "*"

    def compute_directly(self, dataset: Dataset) -> DoubleMetric:
        return DoubleMetric.success(
            self.entity, "ColumnCount", self.instance, float(dataset.num_columns)
        )

    def compute_metric_from_state(self, state) -> DoubleMetric:
        return self.to_failure_metric(
            EmptyStateException("ColumnCount has no scan state.")
        )
