from deequ_tpu.engine.deadline import (
    CancelToken,
    DeadlineExceeded,
    RunBudget,
    RunCancelled,
    ScanInterrupted,
    ScanInterruption,
    install_graceful_shutdown,
)
from deequ_tpu.engine.scan import AnalysisEngine, monoid_all_reduce
from deequ_tpu.engine.subproc import (
    BreakerOpen,
    CircuitBreaker,
    CrashLoopError,
    IsolatedRunner,
    ProcessCrashed,
    checkpoint_progress_probe,
    run_isolated,
)

__all__ = [
    "AnalysisEngine",
    "BreakerOpen",
    "CancelToken",
    "CircuitBreaker",
    "CrashLoopError",
    "DeadlineExceeded",
    "IsolatedRunner",
    "ProcessCrashed",
    "RunBudget",
    "RunCancelled",
    "ScanInterrupted",
    "ScanInterruption",
    "checkpoint_progress_probe",
    "install_graceful_shutdown",
    "monoid_all_reduce",
    "run_isolated",
]
