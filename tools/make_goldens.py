"""Regenerate the exactness-golden pack (tests/goldens/core_v1.json).

Run: ``python tools/make_goldens.py [--check]``

``--check`` diffs the current implementation against the frozen file
and exits non-zero on drift WITHOUT rewriting (what CI/the loader test
does; regeneration is a DELIBERATE act — review the diff before
committing a new golden, because the golden IS the semantic contract).

Goldens always generate on the CPU backend so the frozen values are
hardware-independent; tests/test_goldens.py additionally runs the
default backend against the same file, pinning TPU == frozen-CPU.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

GOLDEN_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "tests",
    "goldens",
    "core_v1.json",
)


def generate() -> dict:
    from deequ_tpu import Dataset, config
    from tools import goldens_spec as spec

    tables = spec.fixtures()
    out = {
        "version": spec.GOLDEN_VERSION,
        "provenance": (
            "semantics reconstructed from SURVEY.md (reference mount "
            "empty); regenerate deliberately via tools/make_goldens.py "
            "and diff against the real reference when it populates"
        ),
        "cases": [],
    }
    with config.configure(engine="cpu"):
        for fixture_name, analyzer_spec in spec.cases():
            ds = Dataset.from_arrow(tables[fixture_name])
            outcome = spec.run_case(ds, analyzer_spec)
            out["cases"].append(
                {
                    "fixture": fixture_name,
                    "analyzer": analyzer_spec,
                    "expect": outcome,
                }
            )
    return out


def main() -> int:
    check = "--check" in sys.argv
    current = generate()
    if check:
        with open(GOLDEN_PATH) as f:
            frozen = json.load(f)
        drift = []
        frozen_cases = {
            (c["fixture"], json.dumps(c["analyzer"], sort_keys=True)): c[
                "expect"
            ]
            for c in frozen["cases"]
        }
        for c in current["cases"]:
            key = (c["fixture"], json.dumps(c["analyzer"], sort_keys=True))
            want = frozen_cases.pop(key, None)
            if want is None:
                drift.append(f"NEW case (not frozen): {key}")
            elif want != c["expect"]:
                drift.append(
                    f"DRIFT {key}: frozen={want} current={c['expect']}"
                )
        for key in frozen_cases:
            drift.append(f"MISSING case (frozen but not run): {key}")
        for line in drift:
            print(line)
        print(
            f"{len(drift)} drift(s)"
            if drift
            else f"all {len(current['cases'])} cases match the golden"
        )
        return 1 if drift else 0
    os.makedirs(os.path.dirname(GOLDEN_PATH), exist_ok=True)
    with open(GOLDEN_PATH, "w") as f:
        json.dump(current, f, indent=1, sort_keys=True)
        f.write("\n")
    print(f"wrote {len(current['cases'])} cases to {GOLDEN_PATH}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
