"""Unified telemetry: spans, counters, run listeners, structured export.

The one-stop observability layer (docs/OBSERVABILITY.md):

- :mod:`spans` — nested attribute-carrying spans with thread-local
  context, each doubling as a ``jax.profiler.TraceAnnotation``
- :mod:`metrics` — always-on counters/gauges/latency histograms with a
  Prometheus text exposition
- :mod:`listeners` — Spark-listener-style run callbacks
- :mod:`runtime` — the :class:`Telemetry` object tying them together,
  with per-run captures and a JSONL event log
- :mod:`export` — summary serde/merging and JSONL reading
- :mod:`oprecords` — repository-persisted per-run operational records
  (imported lazily by the runner/serde; not re-exported here to keep
  this package importable from the data layer without cycles)
- :mod:`phases` — the scan wall-decomposition clock

``get_telemetry()`` returns the process default; ``configure(...)``
flips ``enabled``/``jsonl_path`` on it. Counters stay live even when
disabled (monotonic accounting, e.g. ``transfer.bytes``); everything
else becomes a shared no-op.
"""

from deequ_tpu.telemetry.export import (
    MetricsServer,
    SloTracker,
    merge_summaries,
    parse_slo_objectives,
    read_jsonl,
    serve_metrics,
    summarize_phases,
    summary_from_json,
    summary_to_json,
)
from deequ_tpu.telemetry.listeners import CollectingRunListener, RunListener
from deequ_tpu.telemetry.metrics import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
)
from deequ_tpu.telemetry.phases import PhaseClock
from deequ_tpu.telemetry.runtime import (
    RunCapture,
    Telemetry,
    configure,
    get_telemetry,
)
from deequ_tpu.telemetry.spans import (
    NOOP_SPAN,
    Span,
    TraceContext,
    Tracer,
    clock,
    profiler_trace,
)

__all__ = [
    "CollectingRunListener",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "MetricsServer",
    "NOOP_SPAN",
    "PhaseClock",
    "RunCapture",
    "RunListener",
    "SloTracker",
    "Span",
    "Telemetry",
    "TraceContext",
    "Tracer",
    "clock",
    "configure",
    "get_telemetry",
    "merge_summaries",
    "parse_slo_objectives",
    "profiler_trace",
    "read_jsonl",
    "serve_metrics",
    "summarize_phases",
    "summary_from_json",
    "summary_to_json",
]
