"""Cross-host high-cardinality grouping over loopback: the TPU-native
shuffle spanning PROCESSES (docs/MULTIHOST.md steps 1-4; SURVEY §7
hard part #1 extended across hosts).

Two real processes (4 virtual CPU devices each) initialize
``jax.distributed`` against a loopback coordinator and build ONE global
8-device mesh. Each process reads ITS OWN parquet shard of a 10M-row,
~10M-distinct int64 key column — no host ever sees the other's rows —
and the bucketed ``all_to_all`` shuffle + per-shard sort + segment
count (analyzers/spill.multihost_spill_frequencies) computes
CountDistinct / Uniqueness / Distinctness / Entropy / Histogram with
NO host-side Arrow fallback and no cross-host group-state merge: equal
keys land on one device wherever their rows lived, and the count
scalars psum into replicated values.

The parent process then recomputes the same metrics over the WHOLE
table with the device spill disabled (the host Arrow ground truth) and
asserts equality.

    python examples/multihost_grouping.py
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import tempfile

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

N_ROWS = 10_000_000
TOP_K = 12

WORKER = r"""
import json, sys
import numpy as np
coordinator, pid, shard_path = sys.argv[1], int(sys.argv[2]), sys.argv[3]
import os
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=4"
)
import jax
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(
    coordinator_address=coordinator, num_processes=2, process_id=int(pid)
)
from jax.sharding import Mesh

from deequ_tpu import Dataset
from deequ_tpu.analyzers.grouping import FrequencyPlan
from deequ_tpu.analyzers.spill import multihost_spill_frequencies
from deequ_tpu.analyzers import (
    CountDistinct, Distinctness, Entropy, Histogram, Uniqueness,
)

dataset = Dataset.from_parquet(shard_path)
mesh = Mesh(np.array(jax.devices()), ("dp",))

# count-family metrics share ONE shuffle (include_nulls=False);
# Histogram keeps its null bin via a second plan — exactly the
# single-host planner's split
count_state = multihost_spill_frequencies(
    dataset, FrequencyPlan(("k",), None, False), mesh
)
hist_state = multihost_spill_frequencies(
    dataset, FrequencyPlan(("k",), None, True), mesh
)
# where-filters evaluate per row on each host's OWN shard before the
# shuffle (r5): the filtered count must equal the whole-table filtered
# run too
where_state = multihost_spill_frequencies(
    dataset, FrequencyPlan(("k",), "k % 2 = 0", False), mesh
)

out = {}
for a in (CountDistinct("k"), Uniqueness("k"), Distinctness("k"),
          Entropy("k")):
    m = a.compute_metric_from_state(count_state)
    assert m.value.is_success, (a, m.value)
    out[a.name] = m.value.get()
m = CountDistinct("k", where="k % 2 = 0").compute_metric_from_state(
    where_state
)
assert m.value.is_success, m.value
out["CountDistinct_where"] = m.value.get()
hist = Histogram("k", max_detail_bins=TOPK).compute_metric_from_state(
    hist_state
)
assert hist.value.is_success, hist.value
dist = hist.value.get()
out["histogram"] = {
    str(k): v.absolute for k, v in dist.values.items()
}
out["histogram_bins"] = dist.number_of_bins
if int(pid) == 0:
    print("METRICS " + json.dumps(out), flush=True)
print(f"worker {pid} done", flush=True)
""".replace("TOPK", str(TOP_K))


def main() -> None:
    import shutil

    workdir = tempfile.mkdtemp(prefix="deequ_tpu_mh_grouping_")
    try:
        _run(workdir)
    finally:
        shutil.rmtree(workdir, ignore_errors=True)


def _run(workdir: str) -> None:
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    rng = np.random.default_rng(8)
    keys = rng.integers(0, 1 << 40, N_ROWS, dtype=np.int64).astype(object)
    keys[::101] = None  # Histogram's null bin must survive the shuffle
    # a few heavy hitters so the top-k histogram is deterministic
    for rank, (value, count) in enumerate(
        [(7, 90_000), (11, 70_000), (13, 50_000), (1 << 39, 30_000)]
    ):
        lo = 1000 + rank * 200_000
        keys[lo : lo + count] = value
    table = pa.table({"k": pa.array(list(keys), pa.int64())})

    # UNEQUAL shards: 60% / 40%
    split = int(N_ROWS * 0.6)
    shards = []
    for i, (off, length) in enumerate(
        [(0, split), (split, N_ROWS - split)]
    ):
        path = os.path.join(workdir, f"shard{i}")
        os.makedirs(path, exist_ok=True)
        pq.write_table(
            table.slice(off, length),
            os.path.join(path, "part0.parquet"),
        )
        shards.append(path)

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    coordinator = f"127.0.0.1:{port}"

    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", WORKER, coordinator, str(i), shards[i]],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            env=env,
        )
        for i in range(2)
    ]
    import time as _time

    deadline = _time.monotonic() + 600
    outputs = [b"", b""]
    try:
        for i, p in enumerate(procs):
            try:
                outputs[i], _ = p.communicate(
                    timeout=max(1.0, deadline - _time.monotonic())
                )
            except subprocess.TimeoutExpired:
                pass
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for i, p in enumerate(procs):
            if p.poll() is None or not outputs[i]:
                try:
                    extra, _ = p.communicate(timeout=10)
                    outputs[i] = outputs[i] + (extra or b"")
                except Exception:  # noqa: BLE001 — reporting only
                    pass
    failed = [i for i, p in enumerate(procs) if p.returncode != 0]
    if failed:
        report = "\n".join(
            f"--- worker {i} (rc={procs[i].returncode}) ---\n"
            + outputs[i].decode(errors="replace")
            for i in range(2)
        )
        raise RuntimeError(f"worker(s) {failed} failed:\n{report}")

    got = None
    for line in outputs[0].decode().splitlines():
        if line.startswith("METRICS "):
            got = json.loads(line[len("METRICS "):])
    assert got is not None, outputs[0].decode()

    # ground truth: whole table, device spill DISABLED (host Arrow)
    from deequ_tpu import Dataset, config
    from deequ_tpu.analyzers import (
        AnalysisRunner,
        CountDistinct,
        Distinctness,
        Entropy,
        Histogram,
        Uniqueness,
    )

    whole = Dataset.from_arrow(table)
    analyzers = [
        CountDistinct("k"),
        Uniqueness("k"),
        Distinctness("k"),
        Entropy("k"),
        Histogram("k", max_detail_bins=TOP_K),
    ]
    with config.configure(device_spill_grouping=False):
        ctx = AnalysisRunner.do_analysis_run(whole, analyzers)
    filtered = CountDistinct("k", where="k % 2 = 0")
    with config.configure(device_spill_grouping=False):
        ctx_w = AnalysisRunner.do_analysis_run(whole, [filtered])
    want_w = ctx_w.metric(filtered).value.get()
    assert abs(got["CountDistinct_where"] - want_w) <= 1e-9 * max(
        1.0, abs(want_w)
    ), (got["CountDistinct_where"], want_w)
    print(
        f"{'CountDistinct/where':>14}: multihost "
        f"{got['CountDistinct_where']:.9g} == arrow {want_w:.9g}"
    )
    for a in analyzers[:4]:
        want = ctx.metric(a).value.get()
        have = got[a.name]
        assert abs(have - want) <= 1e-9 * max(1.0, abs(want)), (
            a.name, have, want,
        )
        print(f"{a.name:>14}: multihost {have:.9g} == arrow {want:.9g}")
    dist = ctx.metric(analyzers[4]).value.get()
    want_hist = {str(k): v.absolute for k, v in dist.values.items()}
    assert got["histogram_bins"] == dist.number_of_bins
    # tie-breaking at the k-th bin may pick different equal-count
    # keys; counts multiset and all common keys must agree exactly
    assert sorted(got["histogram"].values()) == sorted(
        want_hist.values()
    ), (got["histogram"], want_hist)
    for k in set(got["histogram"]) & set(want_hist):
        assert got["histogram"][k] == want_hist[k], k
    print(f"{'Histogram':>14}: multihost top-{TOP_K} == arrow")
    print(
        "multi-host grouping (2 processes, loopback, device shuffle): "
        "metrics == whole-table Arrow"
    )


if __name__ == "__main__":
    main()
