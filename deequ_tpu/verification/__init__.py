from deequ_tpu.verification.suite import (
    VerificationResult,
    VerificationRunBuilder,
    VerificationSuite,
)

__all__ = [
    "VerificationResult",
    "VerificationRunBuilder",
    "VerificationSuite",
]
