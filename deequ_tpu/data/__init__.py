from deequ_tpu.data.table import (
    ROW_MASK,
    ColumnRequest,
    Dataset,
    Field,
    Kind,
    Schema,
)

__all__ = [
    "ColumnRequest",
    "Dataset",
    "Field",
    "Kind",
    "ROW_MASK",
    "Schema",
]
