"""Anomaly-detection strategies.

Reference (one file per strategy under ``anomalydetection/``, SURVEY.md
§2.5): SimpleThresholdStrategy, AbsoluteChangeStrategy (nth-order
differences), RelativeRateOfChangeStrategy, BaseChangeStrategy (the
shared diffing base), OnlineNormalStrategy (incremental mean/variance
that can ignore detected anomalies in its estimate),
BatchNormalStrategy. Each is a small numeric algorithm over a series.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import numpy as np

from deequ_tpu.anomalydetection.base import Anomaly, AnomalyDetectionStrategy


def _resolve_interval(
    n: int, search_interval: Optional[Tuple[int, int]]
) -> Tuple[int, int]:
    if search_interval is None:
        return 0, n
    lo, hi = search_interval
    return max(0, lo), min(n, hi)


@dataclass
class SimpleThresholdStrategy(AnomalyDetectionStrategy):
    """Anomalous iff outside [lower_bound, upper_bound]."""

    lower_bound: float = -math.inf
    upper_bound: float = math.inf

    def __post_init__(self):
        if self.lower_bound > self.upper_bound:
            raise ValueError("lower_bound must be <= upper_bound")

    def detect(self, values, search_interval=None):
        values = np.asarray(values, dtype=float)
        lo, hi = _resolve_interval(len(values), search_interval)
        out: List[Tuple[int, Anomaly]] = []
        for i in range(lo, hi):
            v = values[i]
            if v < self.lower_bound or v > self.upper_bound:
                out.append(
                    (
                        i,
                        Anomaly(
                            float(v),
                            1.0,
                            f"[SimpleThresholdStrategy]: {v} not in "
                            f"[{self.lower_bound}, {self.upper_bound}]",
                        ),
                    )
                )
        return out


@dataclass
class _BaseChangeStrategy(AnomalyDetectionStrategy):
    """Shared base for difference/rate strategies (reference:
    BaseChangeStrategy)."""

    max_rate_decrease: float = -math.inf
    max_rate_increase: float = math.inf
    order: int = 1

    def __post_init__(self):
        if self.max_rate_decrease >= self.max_rate_increase:
            raise ValueError(
                "max_rate_decrease must be below max_rate_increase"
            )
        if self.order < 1:
            raise ValueError("order must be >= 1")

    def _transform(self, values: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def detect(self, values, search_interval=None):
        values = np.asarray(values, dtype=float)
        lo, hi = _resolve_interval(len(values), search_interval)
        if len(values) <= self.order:
            return []
        changes = self._transform(values)  # aligned: changes[i] at value i
        out: List[Tuple[int, Anomaly]] = []
        for i in range(max(lo, self.order), hi):
            change = changes[i - self.order]
            if not (self.max_rate_decrease <= change <= self.max_rate_increase):
                out.append(
                    (
                        i,
                        Anomaly(
                            float(values[i]),
                            1.0,
                            f"[{type(self).__name__}]: change {change} not "
                            f"in [{self.max_rate_decrease}, "
                            f"{self.max_rate_increase}]",
                        ),
                    )
                )
        return out


@dataclass
class AbsoluteChangeStrategy(_BaseChangeStrategy):
    """nth-order differences outside the allowed band."""

    def _transform(self, values: np.ndarray) -> np.ndarray:
        return np.diff(values, n=self.order)


@dataclass
class RelativeRateOfChangeStrategy(_BaseChangeStrategy):
    """value[i] / value[i-order] outside the allowed band."""

    def _transform(self, values: np.ndarray) -> np.ndarray:
        denom = values[: len(values) - self.order]
        num = values[self.order :]
        with np.errstate(divide="ignore", invalid="ignore"):
            return num / denom


@dataclass
class OnlineNormalStrategy(AnomalyDetectionStrategy):
    """Incremental (Welford) mean/variance; a point is anomalous if it
    deviates more than factor * stddev; anomalies can be excluded from
    the running estimate (reference: OnlineNormalStrategy)."""

    lower_deviation_factor: Optional[float] = 3.0
    upper_deviation_factor: Optional[float] = 3.0
    ignore_start_percentage: float = 0.1
    ignore_anomalies: bool = True

    def __post_init__(self):
        for f in (self.lower_deviation_factor, self.upper_deviation_factor):
            if f is not None and f < 0:
                raise ValueError("deviation factors must be >= 0")
        if not 0.0 <= self.ignore_start_percentage <= 1.0:
            raise ValueError("ignore_start_percentage must be in [0, 1]")

    def detect(self, values, search_interval=None):
        values = np.asarray(values, dtype=float)
        n = len(values)
        lo, hi = _resolve_interval(n, search_interval)
        warmup = int(math.ceil(n * self.ignore_start_percentage))
        mean, m2, count = 0.0, 0.0, 0
        out: List[Tuple[int, Anomaly]] = []
        for i, v in enumerate(values):
            stddev = math.sqrt(m2 / count) if count > 0 else 0.0
            is_anomaly = False
            if i >= max(warmup, 1) and count > 0:
                upper = (
                    mean + self.upper_deviation_factor * stddev
                    if self.upper_deviation_factor is not None
                    else math.inf
                )
                lower = (
                    mean - self.lower_deviation_factor * stddev
                    if self.lower_deviation_factor is not None
                    else -math.inf
                )
                is_anomaly = v < lower or v > upper
                if is_anomaly and lo <= i < hi:
                    out.append(
                        (
                            i,
                            Anomaly(
                                float(v),
                                1.0,
                                f"[OnlineNormalStrategy]: {v} not in "
                                f"[{lower}, {upper}] (mean={mean}, "
                                f"stdDev={stddev})",
                            ),
                        )
                    )
            if not (is_anomaly and self.ignore_anomalies):
                count += 1
                delta = v - mean
                mean += delta / count
                m2 += delta * (v - mean)
        return out


@dataclass
class BatchNormalStrategy(AnomalyDetectionStrategy):
    """Mean/stddev estimated from the points OUTSIDE the search interval
    (reference: BatchNormalStrategy requires a training split)."""

    lower_deviation_factor: Optional[float] = 3.0
    upper_deviation_factor: Optional[float] = 3.0
    include_interval: bool = False

    def detect(self, values, search_interval=None):
        values = np.asarray(values, dtype=float)
        n = len(values)
        lo, hi = _resolve_interval(n, search_interval)
        if self.include_interval:
            training = values
        else:
            training = np.concatenate([values[:lo], values[hi:]])
        if training.size < 2:
            raise ValueError(
                "BatchNormalStrategy needs at least 2 training points "
                "outside the search interval"
            )
        mean = float(np.mean(training))
        stddev = float(np.std(training))
        upper = (
            mean + self.upper_deviation_factor * stddev
            if self.upper_deviation_factor is not None
            else math.inf
        )
        lower = (
            mean - self.lower_deviation_factor * stddev
            if self.lower_deviation_factor is not None
            else -math.inf
        )
        out: List[Tuple[int, Anomaly]] = []
        for i in range(lo, hi):
            v = values[i]
            if v < lower or v > upper:
                out.append(
                    (
                        i,
                        Anomaly(
                            float(v),
                            1.0,
                            f"[BatchNormalStrategy]: {v} not in "
                            f"[{lower}, {upper}]",
                        ),
                    )
                )
        return out
