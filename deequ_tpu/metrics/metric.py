"""Metric model: named, entity-scoped results wrapping ``Try`` values.

Reference: ``src/main/scala/com/amazon/deequ/metrics/Metric.scala``
(SURVEY.md §2.1) — a metric is (entity, name, instance, Try[value]);
failures are values, never exceptions thrown at the user.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import Generic, Sequence, TypeVar

from deequ_tpu.utils.trylike import Failure, Success, Try

T = TypeVar("T")


class Entity(enum.Enum):
    """What a metric describes (reference: ``Entity`` in Metric.scala)."""

    DATASET = "Dataset"
    COLUMN = "Column"
    MULTICOLUMN = "Multicolumn"


@dataclass(frozen=True)
class Metric(Generic[T]):
    """A named, entity-scoped metric result.

    ``instance`` is the column name (or ``*`` for dataset-level metrics);
    ``value`` is a ``Try`` so failed computations travel as data.
    """

    entity: Entity
    name: str
    instance: str
    value: Try[T]

    def flatten(self) -> Sequence["DoubleMetric"]:
        """Expand into scalar double metrics (identity for DoubleMetric)."""
        raise NotImplementedError

    @property
    def is_success(self) -> bool:
        return self.value.is_success


@dataclass(frozen=True)
class DoubleMetric(Metric[float]):
    """A single scalar metric (the common case)."""

    def flatten(self) -> Sequence["DoubleMetric"]:
        return (self,)

    @staticmethod
    def success(
        entity: Entity, name: str, instance: str, value: float
    ) -> "DoubleMetric":
        return DoubleMetric(entity, name, instance, Success(float(value)))

    @staticmethod
    def failure(
        entity: Entity, name: str, instance: str, exception: BaseException
    ) -> "DoubleMetric":
        return DoubleMetric(entity, name, instance, Failure(exception))


@dataclass(frozen=True)
class KeyedDoubleMetric(Metric[dict]):
    """A map of named doubles under one metric (used by row-level stats)."""

    def flatten(self) -> Sequence[DoubleMetric]:
        if self.value.is_success:
            return tuple(
                DoubleMetric(
                    self.entity, f"{self.name}.{k}", self.instance, Success(v)
                )
                for k, v in sorted(self.value.get().items())
            )
        return (
            DoubleMetric(self.entity, self.name, self.instance, self.value),
        )
