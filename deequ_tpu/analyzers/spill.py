"""Device-side high-cardinality grouping: sort + segment counting.

Reference context: the reference's grouping analyzers run a cluster
shuffle (``groupBy().count()``, SURVEY.md §2.6); deequ_tpu's dense
scatter-add path (analyzers/grouping.py) covers key spaces that fit a
device count vector, and historically spilled anything larger to the
host CPU's Arrow ``group_by`` — the one remaining Spark-job-shaped hole
in the engine (SURVEY.md §7 hard part #1; VERDICT r2 missing #1).

This module closes it for the common shape — ONE high-cardinality
numeric grouping column (an id/key column under CountDistinct /
Uniqueness / Distinctness / Entropy / Histogram): the TPU-native
equivalent of the shuffle is a device **sort + segment-boundary count**.

The sort uses a SINGLE u64 key lane — TPU sort compile time scales
brutally with operand count (measured on v5e: 1-operand ~25s,
3-operand 60-135s, both nearly flat in array length), so instead of
carrying drop/null flags as extra sort keys:

- int keys are XOR-biased into u64 (order-preserving, reversible);
  rejected rows (padding, where-filter, nulls) map to the u64 sentinel
  ``0xFFFF...`` and their EXACT count is kept as a scalar — after
  counting, the sentinel-sharing segment is corrected by subtracting
  that scalar, so even an int64.max key stays exact;
- float32 keys are their RAW BITS (``bitcast f32->u32``, the one
  bitcast width TPUs lower) widened to u64 — bit-grouping matches
  Arrow's dictionary semantics exactly (-0.0 != +0.0; NaN payloads
  canonicalized so NaN == NaN) and can never reach the sentinel;
- float64 keys bitcast to u64 directly on backends whose X64 rewriter
  lowers 64-bit bitcasts (CPU); on TPU the rewriter refuses the
  bitcast (verified r4: "X64 element types ... rewriting is not
  implemented: bitcast-convert u64"), so f64 keys are packed into u64
  ON THE HOST (numpy bit view + the same NaN/-0.0 canonicalization)
  and the u64 keys ship instead of the values — one numpy pass,
  identical wire bytes, bit-identical groups to the CPU device path;
- joint key spaces past one u64 lane (> 2^62) sort on TWO u64 lanes
  via ``lax.sort(num_keys=2)`` — measured on v5e: ~32s one-time
  compile (vs ~15s single-lane, persistent-cached), warm cost within
  2x of single-lane at 4M rows; the mixed-radix digits split across
  the lanes, covering joints to 2^124;
- the null group (Histogram's ``include_nulls``) is a separate scalar
  count, re-inserted host-side — it never needs a key lane at all.

Sorting by bits rather than value order is fine: grouping only needs
EQUAL keys adjacent, and bit-equality is the grouping relation itself.

Count-shaped metrics then finalize from ON-DEVICE scalars (#groups,
#count==1, entropy, #rows) — a 10M-group state never crosses the
tunnel; Histogram fetches only its top-K bins via ``lax.top_k``. The
full (keys, counts) arrays stay device-resident and are fetched lazily
only if something actually needs the values (persistence, incremental
merge).

No dictionary is built: unlike the dense path (host Arrow
dictionary_encode) the keys here are the column's own 64-bit values, so
a 1B-row id column never materializes a host-side distinct set at all.

Execution has two forms. The DEFAULT is the one-pass COLLECTOR form
(``single_collector_spec`` / ``joint_collector_spec``): key extraction
is packaged as a ``ScanOps`` whose update appends each batch's u64
keys into a preallocated device-resident buffer at a carried offset,
so spill plans ride the SAME shared fused scan as the scalar and
dense-grouping analyzers — a whole mixed suite costs one traversal of
the source, and the per-plan sort + segment-count finalizes are
dispatched async afterwards so they overlap on device. The older
per-plan form (``device_spill_frequencies`` /
``device_spill_joint_frequencies``, a full re-read of the source per
plan) remains as the ``one_pass_spill=False`` escape hatch, the
fallback when the shared scan fails, and the differential-test oracle;
both forms produce bit-identical metrics (same batches, same order,
same pow2 sentinel padding in front of the same sort).
"""

from __future__ import annotations

import functools
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deequ_tpu.analyzers.grouping import FrequenciesAndNumRows
from deequ_tpu.data.table import (
    ColumnRequest,
    Dataset,
    Kind,
    ROW_MASK,
    f64_canonical_u64_bits,
)

# an INTEGRAL column whose (max - min) spans less than this stays on
# the dense fused-scan path: its host dictionary is bounded by the
# range, which a single O(1)-memory min/max probe establishes
DENSE_DOMAIN_RANGE = 4096

_SENTINEL = np.uint64(0xFFFFFFFFFFFFFFFF)
_BIAS = np.uint64(1) << np.uint64(63)
# test hook: force the host f64-bit packing path on CPU backends
# (where the device bitcast also works) so the mesh variant is
# exercisable under the virtual CPU mesh
_FORCE_HOST_F64_BITS = False


@functools.lru_cache(maxsize=None)
def _joint_chunk_key_fn(n_columns: int):
    """Jitted: one scan chunk's per-column codes + masks -> flat u64
    JOINT keys (code+1 digits in mixed radix ``sizes``, null -> slot 0,
    exactly the dense path's joint-code math) with sentinel for
    non-contributing rows. Multi-column plans exclude only rows where
    ALL grouping columns are null (the reference's
    atLeastOneNonNullGroupingColumn)."""

    def build(codes, masks, rows, sizes):
        any_non_null = jnp.zeros_like(rows)
        for m in masks:
            any_non_null = any_non_null | m
        contributes = rows & any_non_null
        keys = jnp.zeros(rows.shape, dtype=jnp.uint64)
        for j in range(n_columns):
            shifted = (codes[j].astype(jnp.int64) + 1).astype(jnp.uint64)
            keys = keys * sizes[j].astype(jnp.uint64) + shifted
        keys = jnp.where(contributes, keys, _SENTINEL)
        n_sentinel = jnp.sum(~contributes, dtype=jnp.int64)
        return keys.ravel(), n_sentinel

    return jax.jit(build)


def _finish_keys(keys, mask, rows, include_nulls: bool):
    """Traced: the ONE copy of the sentinel/null bookkeeping every key
    builder shares — ``keys`` are the already-canonicalized u64 key
    bits; non-contributing rows map to the sentinel, null rows are
    counted when the plan keeps a null group."""
    if include_nulls:
        null = rows & ~mask
        contributes = rows & mask
    else:
        null = jnp.zeros_like(rows)
        contributes = rows & mask
    keys = jnp.where(contributes, keys, _SENTINEL)
    return (
        keys.ravel(),
        jnp.sum(~contributes, dtype=jnp.int64),
        jnp.sum(null, dtype=jnp.int64),
    )


@functools.lru_cache(maxsize=None)
def _chunk_key_fn(key_kind: str, include_nulls: bool):
    """Jitted: one scan chunk -> (flat u64 keys with sentinel for
    non-contributing rows, #sentinel rows, #null rows kept).
    ``key_kind``: "int" | "f32" | "f64" (see module docstring)."""

    def build(values, mask, rows):
        if key_kind == "f32":
            x = values.astype(jnp.float32)
            bits = jax.lax.bitcast_convert_type(x, jnp.uint32)
            # canonical NaN bits: Arrow dictionary_encode groups NaN==NaN
            bits = jnp.where(
                jnp.isnan(x), jnp.uint32(0x7FC00000), bits
            )
            # -0.0 groups with 0.0 (Spark key normalization; goldens
            # neg_zero) — mapped at the BIT level because XLA's
            # simplifier folds the `x + 0.0` formulation away
            bits = jnp.where(
                bits == jnp.uint32(0x80000000), jnp.uint32(0), bits
            )
            keys = bits.astype(jnp.uint64)
        elif key_kind == "f64":
            x = values.astype(jnp.float64)
            bits = jax.lax.bitcast_convert_type(x, jnp.uint64)
            bits = jnp.where(
                jnp.isnan(x),
                jnp.uint64(0x7FF8000000000000),
                bits,
            )
            keys = jnp.where(
                bits == jnp.uint64(0x8000000000000000),
                jnp.uint64(0),
                bits,
            )
        else:
            keys = values.astype(jnp.int64).astype(jnp.uint64) ^ _BIAS
        return _finish_keys(keys, mask, rows, include_nulls)

    return jax.jit(build)


@functools.lru_cache(maxsize=None)
def _joint_chunk_key2_fn(n1: int, n2: int):
    """Two-lane variant of _joint_chunk_key_fn for joint key spaces
    past one u64 lane: columns [0:n1] pack lane 1, [n1:n1+n2] lane 2
    (each lane's radix product < 2^62). Sentinel = both lanes max."""

    def build(codes, masks, rows, sizes1, sizes2):
        any_non_null = jnp.zeros_like(rows)
        for m in masks:
            any_non_null = any_non_null | m
        contributes = rows & any_non_null

        def radix(cs, szs):
            keys = jnp.zeros(rows.shape, dtype=jnp.uint64)
            for j in range(len(cs)):
                shifted = (cs[j].astype(jnp.int64) + 1).astype(jnp.uint64)
                keys = keys * szs[j].astype(jnp.uint64) + shifted
            return keys

        k1 = radix(codes[:n1], sizes1)
        k2 = radix(codes[n1:], sizes2)
        k1 = jnp.where(contributes, k1, _SENTINEL)
        k2 = jnp.where(contributes, k2, _SENTINEL)
        n_sentinel = jnp.sum(~contributes, dtype=jnp.int64)
        return k1.ravel(), k2.ravel(), n_sentinel

    return jax.jit(build)


# HOST twin of the f64 key canonicalization in _chunk_key_fn; now
# lives in data.table (it backs the "u64bits" column repr the one-pass
# collector requests), re-exported here for its historical callers
f64_canonical_bits = f64_canonical_u64_bits


@functools.lru_cache(maxsize=None)
def _finish_keys_jit(include_nulls: bool):
    """Cached jitted _finish_keys wrapper (a fresh per-call lambda
    would defeat jit's cache and recompile every invocation)."""
    return jax.jit(
        lambda b, m, r: _finish_keys(b, m, r, include_nulls)
    )


def host_f64_u64_keys(
    values: np.ndarray, mask: np.ndarray, rows: np.ndarray,
    include_nulls: bool,
):
    """f64_canonical_bits plus the sentinel bookkeeping of
    _chunk_key_fn — the single-device host packing path."""
    bits = f64_canonical_bits(values)
    if include_nulls:
        null = rows & ~mask
        contributes = rows & mask
    else:
        null = np.zeros_like(rows)
        contributes = rows & mask
    keys = np.where(contributes, bits, _SENTINEL)
    return (
        keys.ravel(),
        int(np.sum(~contributes)),
        int(np.sum(null)),
    )


def _segment_count_lanes(lanes, correction):
    """Traced: sort flat u64 key LANES lexicographically, count segment
    boundaries (a boundary wherever ANY lane changes), subtract
    ``correction`` sentinel-valued entries from the trailing segment.
    This is the ONE copy of the exactness-critical bookkeeping — the
    single-device finalize (1 or 2 lanes) and the per-shard half of
    the sharded shuffle all run it. Output arrays have length N+1
    (slot N absorbs non-boundary scatter writes); segments occupy
    [0, num_segments) and ``gmask`` marks those with a positive
    corrected count. Counts are i32 (a chip processes < 2^31 rows per
    state; merges widen). The sentinel is max on EVERY lane, so it
    still sorts last regardless of lane count."""
    n = lanes[0].shape[0]
    if len(lanes) == 1:
        sorted_lanes = (jnp.sort(lanes[0]),)
    else:
        sorted_lanes = jax.lax.sort(tuple(lanes), num_keys=len(lanes))
    changed = jnp.zeros(n - 1, dtype=bool)
    for k in sorted_lanes:
        changed = changed | (k[1:] != k[:-1])
    boundary = jnp.concatenate([jnp.ones(1, dtype=bool), changed])
    seg = jnp.cumsum(boundary.astype(jnp.int32)) - 1
    num_segments = seg[-1] + 1
    counts = jnp.zeros(n + 1, dtype=jnp.int32).at[seg].add(1)
    # sentinel-valued entries all sort to the end and share the last
    # segment; the caller knows exactly how many don't belong
    has_sentinel = jnp.ones((), dtype=bool)
    for k in sorted_lanes:
        has_sentinel = has_sentinel & (k[-1] == _SENTINEL)
    counts = counts.at[seg[-1]].add(
        -jnp.where(has_sentinel, correction, 0).astype(jnp.int32)
    )
    scatter_idx = jnp.where(boundary, seg, n)
    group_lanes = tuple(
        jnp.zeros(n + 1, dtype=k.dtype).at[scatter_idx].set(k)
        for k in sorted_lanes
    )
    in_range = jnp.arange(n + 1, dtype=jnp.int32) < num_segments
    gmask = in_range & (counts > 0)
    return num_segments, counts, group_lanes, gmask


def _segment_count(keys, correction):
    """Single-lane wrapper over _segment_count_lanes (the sharded
    shuffle and the single-column finalize use this shape)."""
    num_segments, counts, group_lanes, gmask = _segment_count_lanes(
        (keys,), correction
    )
    return num_segments, counts, group_lanes[0], gmask


def _entropy_term(counts, gmask, total):
    """Traced: -sum(p log p) over masked groups against a GLOBAL total
    (partial term for psum in the sharded path; the whole sum in the
    single-device path)."""
    c = jnp.where(gmask, counts, 0).astype(jnp.float64)
    tot_f = jnp.maximum(total, 1).astype(jnp.float64)
    p = c / tot_f
    return -jnp.sum(jnp.where(c > 0, p * jnp.log(p), 0.0))


def _spill_scalars(num_segments, counts, gmask, total):
    """The on-device scalar summary every finalize shape shares."""
    return {
        "num_segments": num_segments.astype(jnp.int64),
        "num_groups": jnp.sum(gmask, dtype=jnp.int64),
        "total": total,
        "unique": jnp.sum((counts == 1) & gmask, dtype=jnp.int64),
        "entropy": _entropy_term(counts, gmask, total),
    }


@functools.lru_cache(maxsize=None)
def _finalize_fn():
    """Jitted: flat u64 keys + sentinel count -> per-group arrays and
    scalars (single-device path)."""

    def run(keys, n_sentinel):
        num_segments, counts, group_keys, gmask = _segment_count(
            keys, n_sentinel
        )
        total = (keys.shape[0] - n_sentinel).astype(jnp.int64)
        scalars = _spill_scalars(num_segments, counts, gmask, total)
        return scalars, group_keys, counts

    return jax.jit(run)


@functools.lru_cache(maxsize=None)
def _finalize2_fn():
    """Jitted two-lane finalize (joint keys past one u64 lane)."""

    def run(hi, lo, n_sentinel):
        num_segments, counts, group_lanes, gmask = _segment_count_lanes(
            (hi, lo), n_sentinel
        )
        total = (hi.shape[0] - n_sentinel).astype(jnp.int64)
        scalars = _spill_scalars(num_segments, counts, gmask, total)
        return scalars, group_lanes[0], group_lanes[1], counts

    return jax.jit(run)


@functools.partial(jax.jit, static_argnums=(3,))
def _topk_fn(counts, group_keys, num_segments, k):
    # equal-count ties at the k-boundary resolve in ascending
    # PACKED-KEY order here (segments are key-sorted) vs first-seen
    # order on the dense/Arrow path — a documented divergence; see
    # FrequenciesAndNumRows.top_groups (ADVICE r3)
    in_range = (
        jnp.arange(counts.shape[0], dtype=jnp.int32) < num_segments
    )
    tc, ti = jax.lax.top_k(jnp.where(in_range, counts, -1), k)
    return tc, jnp.take(group_keys, ti)


def _pack_top_pairs(pairs, k: int, null_rows: int):
    """Shared top-k tail: merge in the null bin (a host scalar) and
    pack (keys, counts) arrays."""
    if null_rows > 0:
        pairs = list(pairs) + [(None, np.int64(null_rows))]
        pairs.sort(key=lambda kv: -kv[1])
        pairs = pairs[:k]
    if not pairs:
        return np.zeros(0, dtype=object), np.zeros(0, dtype=np.int64)
    keys_out = np.empty(len(pairs), dtype=object)
    keys_out[:] = [p[0] for p in pairs]
    return keys_out, np.asarray([p[1] for p in pairs], dtype=np.int64)


def _count_data_pass() -> None:
    """Every full traversal of the source bumps ``engine.data_passes``
    (run_scan counts its own) — the deferred re-scan paths below each
    cost one; the collector form costs zero beyond the shared scan."""
    from deequ_tpu.telemetry import get_telemetry

    get_telemetry().counter("engine.data_passes").inc()


class SpillOverflow(Exception):
    """A sharded spill bucket exceeded its static capacity; the caller
    falls back to the host Arrow path (exactness over speed)."""


def _fmix64(x):
    """murmur3 64-bit finalizer: avalanches sequential ids into uniform
    bucket assignments (a plain ``key % ndev`` would send stride-ndev
    id ranges all to one shard)."""
    x = x ^ (x >> np.uint64(33))
    x = x * np.uint64(0xFF51AFD7ED558CCD)
    x = x ^ (x >> np.uint64(33))
    x = x * np.uint64(0xC4CEB9FE1A85EC53)
    x = x ^ (x >> np.uint64(33))
    return x


def _fmix64_int(x: int) -> int:
    """Host-side _fmix64 over Python ints (no numpy overflow warnings);
    used for trace-time constants like the sentinel's bucket."""
    m = (1 << 64) - 1
    x &= m
    x ^= x >> 33
    x = (x * 0xFF51AFD7ED558CCD) & m
    x ^= x >> 33
    x = (x * 0xC4CEB9FE1A85EC53) & m
    x ^= x >> 33
    return x


@functools.lru_cache(maxsize=None)
def _sharded_spill_fn(mesh, axis: str, cap: int):
    """Jitted shard_map: the TPU shuffle (SURVEY.md §2.6, §7 hard part
    #1). Each shard hash-buckets its local u64 keys, ``all_to_all``
    re-shards them so EQUAL keys land on the same device, then each
    device runs the SAME sort + segment-count as the single-device path
    (_segment_count) over its disjoint key range; scalars psum into
    global metrics. Per-device memory is O(rows/ndev): group arrays
    come back SHARDED (out_specs P(axis)), never replicated.

    Sentinel-valued rows (dropped rows AND any legit int64.max keys —
    indistinguishable by value) never enter the shuffle at all: their
    global count minus the known dropped count is exactly the
    int64.max group's count, reconstructed analytically. The only
    sentinel-valued entries a shard receives are therefore all_to_all
    PADDING, whose count derives from the communicated per-bucket
    counts. A bucket overflow (static ``cap`` exceeded) is reported as
    a scalar; the host falls back to the Arrow path rather than
    dropping rows."""
    import jax
    from deequ_tpu.engine.shard_map_compat import shard_map
    from jax.sharding import PartitionSpec as P

    ndev = mesh.shape[axis]

    def per_shard(keys, n_sentinel_global, n_null_global):
        is_sent = keys == _SENTINEL
        sv_local = jnp.sum(is_sent, dtype=jnp.int64)
        bucket = (_fmix64(keys) % np.uint64(ndev)).astype(jnp.int32)
        # sentinel-valued rows are excluded from the shuffle (their
        # count is bookkept in scalars); bucket ndev scatters to drop
        bucket = jnp.where(is_sent, ndev, bucket)
        (recv,), padding_received, overflow = _bucketed_all_to_all(
            axis, ndev, cap, bucket, (keys,)
        )

        # the shared exactness-critical bookkeeping (spill.py's one copy)
        num_segments, counts, group_keys, gmask = _segment_count(
            recv, padding_received.astype(jnp.int64)
        )

        # the analytic int64.max group: sentinel-VALUED rows globally,
        # minus the known dropped-row count
        legit_max = (
            jax.lax.psum(sv_local, axis) - n_sentinel_global
        )
        scalars = _sharded_scalar_block(
            axis, num_segments, counts, gmask, legit_max
        )
        return (
            scalars,
            group_keys,  # sharded out: (ndev*(L+1),) global
            counts,
            num_segments.astype(jnp.int32)[None],  # (ndev,) global
            overflow,
            n_null_global,
        )

    sharded = shard_map(
        per_shard,
        mesh=mesh,
        in_specs=(P(axis), P(), P()),
        out_specs=(P(), P(axis), P(axis), P(axis), P(), P()),
        check_vma=False,
    )
    return jax.jit(sharded)


def _bucketed_all_to_all(axis: str, ndev: int, cap: int, bucket, lanes):
    """The shuffle core every lane-width shares: stable-sort the local
    rows by bucket, pack per-destination (ndev, cap) send buffers for
    EACH key lane with one shared position layout, all_to_all them,
    and derive the received-padding count from the communicated
    per-bucket real counts. bucket == ndev drops the row."""
    import jax

    m = bucket.shape[0]
    order = jnp.argsort(bucket, stable=True)
    sorted_bucket = bucket[order]
    bcounts = jnp.zeros(ndev, jnp.int32).at[bucket].add(1, mode="drop")
    offsets = jnp.concatenate(
        [jnp.zeros(1, jnp.int32), jnp.cumsum(bcounts)[:-1]]
    )
    pos = jnp.arange(m, dtype=jnp.int32) - offsets[
        jnp.clip(sorted_bucket, 0, ndev - 1)
    ]
    in_cap = (pos < cap) & (sorted_bucket < ndev)
    recv_lanes = []
    for lane in lanes:
        send = (
            jnp.full((ndev, cap), _SENTINEL, dtype=lane.dtype)
            .at[
                jnp.where(in_cap, sorted_bucket, ndev),
                jnp.clip(pos, 0, cap - 1),
            ]
            .set(lane[order], mode="drop")
        )
        recv_lanes.append(
            jax.lax.all_to_all(
                send, axis, split_axis=0, concat_axis=0
            ).ravel()
        )
    overflow = jax.lax.psum(
        jnp.sum(jnp.maximum(bcounts - cap, 0)), axis
    )
    # real (non-padding) entry counts per (sender, my bucket)
    sent_real = jnp.minimum(bcounts, cap)  # (ndev,) what I sent
    recv_real = jax.lax.all_to_all(
        sent_real[:, None], axis, split_axis=0, concat_axis=0
    )  # (ndev, 1): shard s's real count for MY bucket
    padding_received = ndev * cap - jnp.sum(recv_real)
    return recv_lanes, padding_received, overflow


def _sharded_scalar_block(axis, num_segments, counts, gmask, legit_max):
    """The psum'd scalar summary every sharded spill shape shares
    (single-lane with its analytic int64.max group; two-lane joints
    pass legit_max = 0, as joint codes can never reach the sentinel)."""
    import jax

    local_total = jnp.sum(jnp.where(gmask, counts, 0), dtype=jnp.int64)
    total = jax.lax.psum(local_total, axis) + legit_max
    num_groups = (
        jax.lax.psum(jnp.sum(gmask, dtype=jnp.int64), axis)
        + (legit_max > 0).astype(jnp.int64)
    )
    unique = (
        jax.lax.psum(
            jnp.sum((counts == 1) & gmask, dtype=jnp.int64), axis
        )
        + (legit_max == 1).astype(jnp.int64)
    )
    pm = legit_max.astype(jnp.float64) / jnp.maximum(total, 1).astype(
        jnp.float64
    )
    entropy = jax.lax.psum(
        _entropy_term(counts, gmask, total), axis
    ) + jnp.where(
        legit_max > 0, -pm * jnp.log(jnp.maximum(pm, 1e-300)), 0.0
    )
    return {
        # replicated upper bound; per-shard true values ride the
        # sharded num_segments vector (sliced at fetch time)
        "num_segments": jax.lax.pmax(num_segments, axis).astype(
            jnp.int64
        ),
        "num_groups": num_groups,
        "total": total,
        "unique": unique,
        "entropy": entropy,
        "legit_max": legit_max,
    }


@functools.lru_cache(maxsize=None)
def _sharded_spill2_fn(mesh, axis: str, cap: int):
    """Two-lane variant of _sharded_spill_fn for joint key spaces past
    one u64 lane (> 2^62): the bucket hashes BOTH lanes so equal
    (hi, lo) pairs land on one device, both lanes ride the shared
    send-buffer layout, and the per-shard count is the same two-lane
    sort (_segment_count_lanes) the single-device path uses. Joint
    codes never reach the sentinel, so legit_max degenerates to 0."""
    import jax
    from deequ_tpu.engine.shard_map_compat import shard_map
    from jax.sharding import PartitionSpec as P

    ndev = mesh.shape[axis]

    def per_shard(k1, k2):
        # no sentinel scalar: joint codes can never reach the
        # sentinel, so there is no analytic max-group to reconstruct
        is_sent = k1 == _SENTINEL
        bucket = (
            _fmix64(k1 ^ _fmix64(k2)) % np.uint64(ndev)
        ).astype(jnp.int32)
        bucket = jnp.where(is_sent, ndev, bucket)
        (r1, r2), padding_received, overflow = _bucketed_all_to_all(
            axis, ndev, cap, bucket, (k1, k2)
        )
        num_segments, counts, group_lanes, gmask = _segment_count_lanes(
            (r1, r2), padding_received.astype(jnp.int64)
        )
        scalars = _sharded_scalar_block(
            axis, num_segments, counts, gmask, jnp.int64(0)
        )
        return (
            scalars,
            group_lanes[0],
            group_lanes[1],
            counts,
            num_segments.astype(jnp.int32)[None],  # (ndev,) global
            overflow,
        )

    sharded = shard_map(
        per_shard,
        mesh=mesh,
        in_specs=(P(axis), P(axis)),
        out_specs=(P(), P(axis), P(axis), P(axis), P(axis), P()),
        check_vma=False,
    )
    return jax.jit(sharded)


class DeviceFrequencies(FrequenciesAndNumRows):
    """FrequenciesAndNumRows whose groups live ON DEVICE.

    Count metrics read precomputed scalars; ``keys``/``counts`` fetch
    and decode lazily (only persistence, incremental merge, and
    MutualInformation ever need the values). The null group, if any, is
    a host scalar appended on access."""

    def __init__(
        self,
        columns: Tuple[str, ...],
        values_dtype: np.dtype,
        scalars: Dict[str, object],
        group_keys,
        counts,
        null_rows: int,
        include_nulls: bool,
        joint=None,  # (dictionaries, sizes): multi-column joint codes
    ):
        self.columns = tuple(columns)
        self._values_dtype = np.dtype(values_dtype)
        self._is_float = self._values_dtype.kind == "f"
        self._joint = joint
        # base-class lazy-decode slots (joint mode feeds _lazy after
        # fetch and inherits keys/non_null_group_mask, incl. caching)
        self._keys = None
        self._lazy = None
        self._num_segments = int(scalars["num_segments"])
        self._value_groups = int(scalars["num_groups"])
        self._unique = int(scalars["unique"])
        self._entropy = float(scalars["entropy"])
        self._null_rows = int(null_rows) if include_nulls else 0
        self._include_nulls = include_nulls
        self.num_rows = int(scalars["total"]) + self._null_rows
        # sharded path only: analytically-reconstructed int64.max group
        self._legit_max = int(scalars.get("legit_max", 0))
        self._dev = (group_keys, counts)
        self._keys_host: Optional[np.ndarray] = None
        self._counts_host: Optional[np.ndarray] = None

    # -- FrequenciesAndNumRows surface ---------------------------------

    @property
    def _has_null_group(self) -> bool:
        return self._null_rows > 0

    @property
    def num_groups(self) -> int:
        return self._value_groups + (1 if self._has_null_group else 0)

    def _fetch(self) -> None:
        if self._counts_host is None:
            from deequ_tpu.engine.pack import packed_device_get

            gk, c = packed_device_get(self._dev)
            s = self._num_segments
            raw_keys = np.asarray(gk)[:s]
            raw_counts = np.asarray(c)[:s]
            live = raw_counts > 0  # drops a zeroed sentinel segment
            self._keys_host = raw_keys[live]
            self._counts_host = raw_counts[live].astype(np.int64)
        self._set_joint_lazy()

    def _set_joint_lazy(self) -> None:
        """Arm the base class's cached joint decode over the fetched
        keys (shared by the single-device and sharded fetches)."""
        if self._joint is not None and self._lazy is None:
            dictionaries, sizes = self._joint
            self._lazy = (
                self._keys_host.astype(np.int64),
                list(dictionaries),
                list(sizes),
            )

    def _decode_keys(self, raw: np.ndarray) -> np.ndarray:
        """(K,) raw u64 keys -> (K,) object values in the column's OWN
        dtype — a float32 column's keys must decode to np.float32, or
        Histogram labels and persisted keys would diverge from the
        dense dictionary path (str(np.float64(1.1)) !=
        str(np.float32(1.1))). Float keys are raw bits; ints unbias."""
        if self._values_dtype == np.float32:
            vals = raw.astype(np.uint32).view(np.float32)
        elif self._values_dtype == np.float64:
            vals = raw.view(np.float64)
        elif self._is_float:  # f16 materialized as f32 on the wire
            vals = raw.astype(np.uint32).view(np.float32).astype(
                self._values_dtype
            )
        else:
            vals = (raw ^ _BIAS).view(np.int64)
        return vals.astype(object)

    @property
    def counts(self) -> np.ndarray:
        self._fetch()
        if self._has_null_group:
            return np.concatenate(
                [self._counts_host, [np.int64(self._null_rows)]]
            )
        return self._counts_host

    @property
    def keys(self) -> np.ndarray:
        self._fetch()
        if self._joint is not None:
            # inherit the base class's cached lazy decode (ONE radix
            # walk however many times merge/persistence read .keys)
            return FrequenciesAndNumRows.keys.fget(self)
        n = self.num_groups
        out = np.empty((n, 1), dtype=object)
        out[: len(self._keys_host), 0] = self._decode_keys(self._keys_host)
        if self._has_null_group:
            out[-1, 0] = None
        return out

    def non_null_group_mask(self) -> np.ndarray:
        if self._joint is not None:
            self._fetch()
            return FrequenciesAndNumRows.non_null_group_mask(self)
        mask = np.ones(self.num_groups, dtype=bool)
        if self._has_null_group:
            mask[-1] = False
        return mask

    # -- fast paths (no device->host group transfer) -------------------

    def count_unique_groups(self) -> int:
        return self._unique + (1 if self._null_rows == 1 else 0)

    def entropy_nats(self) -> float:
        from deequ_tpu.analyzers.base import EmptyStateException

        if self._joint is not None:
            # joint plans can hold PARTIALLY-null groups, which entropy
            # excludes — the on-device scalar summed all groups, so fall
            # back to the host fold over the fetched distribution
            return FrequenciesAndNumRows.entropy_nats(self)
        if self.num_rows - self._null_rows == 0:
            raise EmptyStateException("Entropy over empty distribution.")
        return self._entropy

    def top_groups(self, k: int) -> Tuple[np.ndarray, np.ndarray]:
        if self._joint is not None:  # multi-column: host decode path
            return FrequenciesAndNumRows.top_groups(self, k)
        gk, c = self._dev
        kk = min(k, self._num_segments)
        pairs = []
        if kk > 0:
            from deequ_tpu.engine.pack import packed_device_get

            tc, tkeys = packed_device_get(
                _topk_fn(c, gk, np.int32(self._num_segments), kk)
            )
            tc = np.asarray(tc)
            live = tc > 0  # zeroed sentinel segment never bins
            decoded = self._decode_keys(np.asarray(tkeys)[live])
            pairs = list(zip(decoded, tc[live].astype(np.int64)))
        return _pack_top_pairs(
            pairs, k, self._null_rows if self._has_null_group else 0
        )


class TwoLaneDeviceFrequencies(DeviceFrequencies):
    """DeviceFrequencies for joint keys on TWO u64 lanes (joint space
    past 2^62): group identity is the (hi, lo) pair; decoding walks
    each lane's own mixed radix over its own column slice."""

    def __init__(
        self,
        columns,
        scalars,
        group_hi,
        group_lo,
        counts,
        dictionaries,
        sizes,
        split: int,
    ):
        super().__init__(
            columns,
            np.dtype(np.int64),
            scalars,
            (group_hi, group_lo),
            counts,
            0,
            False,
            joint=(list(dictionaries), list(sizes)),
        )
        self._split = split
        self._keys_host2: Optional[np.ndarray] = None

    def _fetch(self) -> None:
        if self._counts_host is None:
            from deequ_tpu.engine.pack import packed_device_get

            # one packed fetch for all three arrays
            gh, gl, c = packed_device_get(
                (self._dev[0][0], self._dev[0][1], self._dev[1])
            )
            s = self._num_segments
            raw_hi = np.asarray(gh)[:s]
            raw_lo = np.asarray(gl)[:s]
            raw_counts = np.asarray(c)[:s]
            live = raw_counts > 0
            self._keys_host = raw_hi[live]
            self._keys_host2 = raw_lo[live]
            self._counts_host = raw_counts[live].astype(np.int64)

    @property
    def keys(self) -> np.ndarray:
        self._fetch()
        if self._keys is None:
            from deequ_tpu.analyzers.grouping import _decode_joint_codes

            dictionaries, sizes = self._joint
            split = self._split
            left = _decode_joint_codes(
                split,
                self._keys_host.astype(np.int64),
                dictionaries[:split],
                sizes[:split],
            )
            right = _decode_joint_codes(
                len(self.columns) - split,
                self._keys_host2.astype(np.int64),
                dictionaries[split:],
                sizes[split:],
            )
            self._keys = np.hstack([left, right])
        return self._keys

    def non_null_group_mask(self) -> np.ndarray:
        self._fetch()
        mask = np.ones(len(self._keys_host), dtype=bool)
        for lane, lane_sizes in (
            (self._keys_host, self._joint[1][: self._split]),
            (self._keys_host2, self._joint[1][self._split:]),
        ):
            remaining = lane.astype(np.int64).copy()
            for j in range(len(lane_sizes) - 1, -1, -1):
                slot = remaining % lane_sizes[j]
                remaining = remaining // lane_sizes[j]
                mask &= slot > 0
        return mask

    # entropy_nats / top_groups: the inherited DeviceFrequencies
    # methods already take the joint (host-fold) branch for any
    # instance with _joint set, which this class always has


class ShardedTwoLaneDeviceFrequencies(TwoLaneDeviceFrequencies):
    """TwoLaneDeviceFrequencies whose groups live SHARDED across a
    mesh (joint key spaces > 2^62 under a mesh): both key lanes fetch
    per shard, sliced at each shard's true segment count."""

    def _fetch(self) -> None:
        if self._counts_host is None:
            (gh_flat, gl_flat), gc_flat = self._dev[0], self._dev[1]
            gh = np.asarray(gh_flat)
            gl = np.asarray(gl_flat)
            gc = np.asarray(gc_flat)
            segs = np.asarray(self._segs)
            ndev = len(segs)
            gh = gh.reshape(ndev, -1)
            gl = gl.reshape(ndev, -1)
            gc = gc.reshape(ndev, -1)
            hi_parts, lo_parts, count_parts = [], [], []
            for shard in range(ndev):
                s = int(segs[shard])
                live = gc[shard][:s] > 0
                hi_parts.append(gh[shard][:s][live])
                lo_parts.append(gl[shard][:s][live])
                count_parts.append(gc[shard][:s][live])
            self._keys_host = np.concatenate(hi_parts)
            self._keys_host2 = np.concatenate(lo_parts)
            self._counts_host = np.concatenate(count_parts).astype(
                np.int64
            )


def _sharded_spill_joint2_frequencies(
    dataset: Dataset, plan, engine, dictionaries, sizes, split, pred
) -> "ShardedTwoLaneDeviceFrequencies":
    """Meshed TWO-LANE joint spill (joint key spaces > 2^62 under a
    mesh — docs/COVERAGE.md known-gap, VERDICT r4 next #4): the same
    hash-bucket all_to_all shuffle with BOTH lanes riding the shared
    send layout, then the per-shard two-lane sort + segment count."""
    columns = list(plan.columns)
    needed = {
        r
        for c in columns
        for r in (ColumnRequest(c, "codes"), ColumnRequest(c, "mask"))
    }
    if pred is not None:
        needed.update(pred.requests)

    key2_fn = _joint_chunk_key2_fn(split, len(columns) - split)
    sizes1 = jnp.asarray(np.asarray(sizes[:split], dtype=np.int64))
    sizes2 = jnp.asarray(np.asarray(sizes[split:], dtype=np.int64))

    def build(batch):
        rows = batch[ROW_MASK]
        if pred is not None:
            rows = rows & pred.complies(batch)
        return key2_fn(
            tuple(batch[f"{c}::codes"] for c in columns),
            tuple(batch[f"{c}::mask"] for c in columns),
            rows,
            sizes1,
            sizes2,
        )

    scalars, g_hi, g_lo, g_counts, segs_host = _sharded_shuffle2(
        dataset, engine, needed, build, label=f"joint2 {columns!r}"
    )
    state = ShardedTwoLaneDeviceFrequencies(
        plan.columns,
        scalars,
        g_hi,
        g_lo,
        g_counts,
        list(dictionaries),
        list(sizes),
        split,
    )
    state._segs = segs_host
    return state


def split_joint_lanes(sizes) -> Optional[int]:
    """First-fit split index: columns [0:i] on lane 1, [i:] on lane 2,
    each lane's radix product < 2^62. None when even two lanes cannot
    hold the joint space (or a single column's radix already overflows
    a lane — impossible for dictionaries bounded by row count)."""
    cap = 2**62
    prod = 1
    i = 0
    for s in sizes:
        if prod * s >= cap:
            break
        prod *= s
        i += 1
    if i == 0:
        return None
    prod2 = 1
    for s in sizes[i:]:
        prod2 *= s
        if prod2 >= cap:
            return None
    return i


class ShardedDeviceFrequencies(DeviceFrequencies):
    """DeviceFrequencies whose groups live SHARDED across a mesh: each
    device holds the (keys, counts, num_segments) of its disjoint hash
    range (nothing is replicated); fetching is a filtered concatenation
    plus the analytically-reconstructed int64.max group, if any."""

    def _fetch(self) -> None:
        if self._counts_host is None:
            gk_flat, gc_flat, segs = (
                np.asarray(x) for x in self._dev
            )
            ndev = len(segs)
            gk = gk_flat.reshape(ndev, -1)
            gc = gc_flat.reshape(ndev, -1)
            keys_parts, count_parts = [], []
            for shard in range(ndev):
                s = int(segs[shard])
                raw_k = gk[shard][:s]
                raw_c = gc[shard][:s]
                live = raw_c > 0
                keys_parts.append(raw_k[live])
                count_parts.append(raw_c[live])
            if self._legit_max > 0:
                keys_parts.append(np.array([_SENTINEL], dtype=np.uint64))
                count_parts.append(
                    np.array([self._legit_max], dtype=np.int64)
                )
            self._keys_host = np.concatenate(keys_parts)
            self._counts_host = np.concatenate(count_parts).astype(
                np.int64
            )
        self._set_joint_lazy()

    def top_groups(self, k: int):
        if self._joint is not None:  # multi-column: host decode path
            return FrequenciesAndNumRows.top_groups(self, k)
        # host-side top-k over the fetched union (a per-shard device
        # top_k + gather would cut the fetch further; at histogram's
        # k<=1000 the union fetch is the simpler exact path)
        self._fetch()
        order = np.argsort(-self._counts_host, kind="stable")[:k]
        pairs = list(
            zip(
                self._decode_keys(self._keys_host[order]),
                self._counts_host[order],
            )
        )
        return _pack_top_pairs(
            pairs, k, self._null_rows if self._has_null_group else 0
        )


def device_spill_eligible(dataset: Dataset, plan, engine=None) -> bool:
    """True when a frequency plan should run the device sort path:
    a single INTEGRAL/FRACTIONAL grouping column whose flat sort fits
    the device budget. Strings keep the dense/Arrow path (their keys
    are dictionary codes); booleans and timestamps keep it too so
    decoded key VALUES (True/False, datetime64) stay merge-compatible
    with dense-path states; uint64 can't widen to the i64 key lane.

    Note the asymmetry with the dense path: dense must first build a
    host-side dictionary (an Arrow hash pass over every row) just to
    LEARN the cardinality; the sort path needs no dictionary at all,
    so for FRACTIONAL and unbounded-domain integer columns it wins
    even at low cardinality. Bounded-domain integers are the
    exception (the DENSE_DOMAIN_RANGE gate below): a single O(1)
    min/max probe bounds their dictionary up front, and the dense
    fused scan then beats one device sort per column."""
    from deequ_tpu import config

    opts = config.options()
    if not opts.device_spill_grouping:
        return False
    if not opts.device_cache_bytes:
        return False  # chunked device path needs the resident cache
    if opts.engine == "cpu":
        return False  # honor the engine-selection flag's placement
    if dataset.num_rows >= 2**31:
        return False  # i32 segment counts; the dense path widens, we gate
    if len(plan.columns) != 1:
        return False
    column = plan.columns[0]
    kind = dataset.schema.kind_of(column)
    if kind not in (Kind.INTEGRAL, Kind.FRACTIONAL):
        return False
    try:
        dt = dataset.request_dtype(ColumnRequest(column, "values"))
    except Exception:  # noqa: BLE001 — odd column: use the host path
        return False
    if dt.kind == "u" and dt.itemsize == 8:
        return False
    if kind == Kind.INTEGRAL:
        # bounded-domain integers (TPC-DS quantity-style): one O(1)-
        # memory min/max probe (free from parquet row-group stats)
        # detects them, the host dictionary is then bounded by the
        # range, and ALL such columns ride the shared fused dense scan
        # — while the sort path costs a sequential device sort per
        # column (r5: 5 qty columns = 2.75 s/run steady + a one-time
        # ~60 s sort-plan compile vs milliseconds dense)
        rng = dataset.integral_range(column)
        if rng is not None and (rng[1] - rng[0]) < DENSE_DOMAIN_RANGE:
            return False
    # f64 keys: CPU-class backends bitcast on device; elsewhere (TPU)
    # the canonical u64 bits pack on the HOST (f64_canonical_bits —
    # the X64 rewriter cannot lower the f64 bitcast, measured r4) and
    # the same device sort runs, single-device and meshed alike
    # headroom gate: the pass pins values+mask chunks in the cache
    # (~9 B/row) AND allocates sort transients outside cache accounting
    # (u64 keys + sorted copy + group keys + counts ~ 30 B/row, pow2
    # padded); 64 B/row keeps the whole pass clear of HBM even when the
    # budget is sized close to the device memory
    return dataset.num_rows * 64 <= opts.device_cache_bytes


def joint_spill_config_ok(dataset: Dataset, plan, engine=None) -> bool:
    """The SIZE-INDEPENDENT gates of the joint spill — callers must
    check these BEFORE probing full per-column cardinalities: the
    probe can stream a whole distinct set into host memory, which must
    never happen for a plan the config would reject anyway."""
    from deequ_tpu import config

    opts = config.options()
    if not opts.device_spill_grouping or not opts.device_cache_bytes:
        return False
    if opts.engine == "cpu":
        return False
    if plan.include_nulls:
        # the joint kernel drops all-null rows; include_nulls plans
        # (Histogram's null bin) keep the dense/Arrow paths
        return False
    if dataset.num_rows >= 2**31:
        return False
    return dataset.num_rows * 64 <= opts.device_cache_bytes


def joint_spill_eligible(
    dataset: Dataset, plan, sizes, engine=None
) -> bool:
    """Multi-column variant: config gates pass AND the joint
    mixed-radix key space fits the sort lanes (one u64 lane below
    2^62; past that, TWO lanes cover up to ~2^124 provided the digits
    split across lanes — single-device AND meshed since r5, via
    _sharded_spill_joint2_frequencies)."""
    if not joint_spill_config_ok(dataset, plan, engine):
        return False
    return split_joint_lanes(tuple(sizes)) is not None


def joint_fits_one_lane(sizes) -> bool:
    """True when the mixed-radix joint space fits ONE u64 sort lane
    (< 2^62): the shape the sharded shuffle can re-use unchanged.
    Defined via split_joint_lanes so there is exactly one copy of the
    lane-capacity rule."""
    return split_joint_lanes(tuple(sizes)) == len(tuple(sizes))


def _stage_mesh_columns(dataset, engine, needed, extra_arrays=None):
    """Mesh staging every sharded spill shares: pow2/mesh-multiple
    padding (so the per-shard sort's expensive-to-compile program is
    shared across datasets whose row counts round the same way) and
    column placement. Returns (flat, mesh, axis, ndev, cap)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh, axis = engine.mesh, engine.dp_axis
    ndev = mesh.shape[axis]
    n = dataset.num_rows
    _count_data_pass()  # materializes every needed column: one pass
    pow2 = 1 << max(1, int(max(n, 1) - 1).bit_length())
    padded = max(1, -(-pow2 // ndev)) * ndev
    sharding = NamedSharding(mesh, P(axis))

    def pad_to(host: np.ndarray) -> np.ndarray:
        if len(host) < padded:
            host = np.concatenate(
                [host, np.zeros(padded - len(host), dtype=host.dtype)]
            )
        return host

    flat = {
        r.key: jax.device_put(pad_to(dataset.materialize(r)), sharding)
        for r in needed
    }
    for key, host in (extra_arrays or {}).items():
        # caller-prepared arrays (e.g. host-packed f64 key bits) stage
        # like any column
        flat[key] = jax.device_put(pad_to(host), sharding)
    rows_host = np.zeros(padded, dtype=bool)
    rows_host[:n] = True
    flat[ROW_MASK] = jax.device_put(rows_host, sharding)

    m_local = padded // ndev
    # pow2 capacity (shared compiles); 4x the uniform expectation is
    # comfortable headroom for hashed buckets — dropped rows never
    # enter the shuffle, so nulls/filters cannot skew a bucket
    cap = 1 << max(8, ((4 * m_local) // ndev - 1).bit_length())
    return flat, mesh, axis, ndev, cap


def _sharded_shuffle(
    dataset, engine, needed, build, label: str, extra_arrays=None
):
    """Shared single-lane mesh-spill scaffolding: staging, the
    bucketed all_to_all shuffle, and the overflow check.
    ``build(flat)`` -> (keys, n_sentinel, n_null).

    Returns (scalars, g_keys, g_counts, segs_host, n_null_host);
    raises SpillOverflow when a hash bucket exceeds its static
    capacity (the caller falls back to Arrow)."""
    import jax

    from deequ_tpu.engine.pack import packed_device_get

    flat, mesh, axis, ndev, cap = _stage_mesh_columns(
        dataset, engine, needed, extra_arrays
    )
    keys, n_sentinel, n_null = jax.jit(build)(flat)
    out = _sharded_spill_fn(mesh, axis, cap)(keys, n_sentinel, n_null)
    scalars, g_keys, g_counts, g_segs, overflow, n_null_global = out
    scalars, overflow_host, n_null_host, segs_host = packed_device_get(
        (scalars, overflow, n_null_global, np.asarray(g_segs))
    )
    if int(overflow_host) > 0:
        raise SpillOverflow(
            f"hash bucket exceeded capacity {cap} on {label}"
        )
    return scalars, g_keys, g_counts, segs_host, int(n_null_host)


def _sharded_shuffle2(dataset, engine, needed, build, label: str):
    """Two-lane twin of _sharded_shuffle: ``build(flat)`` ->
    (k1, k2, n_sentinel). Returns (scalars, g_hi, g_lo, g_counts,
    segs_host)."""
    import jax

    from deequ_tpu.engine.pack import packed_device_get

    flat, mesh, axis, ndev, cap = _stage_mesh_columns(
        dataset, engine, needed
    )
    k1, k2, _ = jax.jit(build)(flat)
    out = _sharded_spill2_fn(mesh, axis, cap)(k1, k2)
    scalars, g_hi, g_lo, g_counts, g_segs, overflow = out
    scalars, overflow_host, segs_host = packed_device_get(
        (scalars, overflow, np.asarray(g_segs))
    )
    if int(overflow_host) > 0:
        raise SpillOverflow(
            f"hash bucket exceeded capacity {cap} on {label}"
        )
    return scalars, g_hi, g_lo, g_counts, segs_host


def _sharded_spill_joint_frequencies(
    dataset: Dataset, plan, engine, dictionaries, sizes, pred
) -> "ShardedDeviceFrequencies":
    """Meshed multi-column joint spill (SURVEY §2.6, closing the
    'meshed multi-column spills use the host path' gap): the joint
    mixed-radix codes pack into ONE u64 lane (< 2^62 — two-lane joints
    stay single-device), after which the bucketed all_to_all shuffle,
    per-shard sort + segment count, and scalar psums are EXACTLY the
    single-column sharded machinery (_sharded_shuffle) — joint keys
    can never collide with the sentinel, so the analytic int64.max
    group reconstruction degenerates to zero."""
    columns = list(plan.columns)
    needed = {
        r
        for c in columns
        for r in (ColumnRequest(c, "codes"), ColumnRequest(c, "mask"))
    }
    if pred is not None:
        needed.update(pred.requests)

    key_fn = _joint_chunk_key_fn(len(columns))
    sizes_dev = jnp.asarray(np.asarray(sizes, dtype=np.int64))

    def build(batch):
        rows = batch[ROW_MASK]
        if pred is not None:
            rows = rows & pred.complies(batch)
        keys, n_sentinel = key_fn(
            tuple(batch[f"{c}::codes"] for c in columns),
            tuple(batch[f"{c}::mask"] for c in columns),
            rows,
            sizes_dev,
        )
        return keys, n_sentinel, jnp.int64(0)  # no null group (gated)

    scalars, g_keys, g_counts, segs_host, _ = _sharded_shuffle(
        dataset, engine, needed, build, label=f"joint {columns!r}"
    )
    state = ShardedDeviceFrequencies(
        plan.columns,
        np.dtype(np.int64),
        scalars,
        g_keys,
        g_counts,
        0,
        False,
        joint=(list(dictionaries), list(sizes)),
    )
    state._dev = (g_keys, g_counts, segs_host)
    return state


def device_spill_joint_frequencies(
    dataset: Dataset, plan, engine, dictionaries, sizes
) -> "DeviceFrequencies":
    """Multi-column high-cardinality frequencies on device: joint codes
    (the dense path's mixed-radix math) packed into ONE u64 sort lane —
    covers joint key spaces past the dense scatter budget but within
    2^62 (e.g. two 100k-cardinality columns under Uniqueness)."""
    from deequ_tpu import config
    from deequ_tpu.engine.scan import CHUNK_BATCHES

    columns = list(plan.columns)
    requests = [ColumnRequest(c, "codes") for c in columns] + [
        ColumnRequest(c, "mask") for c in columns
    ]
    pred = None
    if plan.where is not None:
        from deequ_tpu.sql.predicate import compile_predicate

        pred = compile_predicate(plan.where, dataset)
        requests += list(pred.requests)

    if engine is not None and getattr(engine, "mesh", None) is not None:
        if joint_fits_one_lane(sizes):
            return _sharded_spill_joint_frequencies(
                dataset, plan, engine, dictionaries, sizes, pred
            )
        split_at = split_joint_lanes(tuple(sizes))
        if split_at is None:
            raise SpillOverflow("joint key space exceeds two u64 lanes")
        # r5: joint spaces past one u64 lane ride the same shuffle on
        # TWO lanes (lax.sort num_keys=2 per shard)
        return _sharded_spill_joint2_frequencies(
            dataset, plan, engine, dictionaries, sizes, split_at, pred
        )

    batch_size = engine._resolve_batch_size(dataset.num_rows)
    nb = dataset.num_batches(batch_size)
    chunk_batches = min(CHUNK_BATCHES, nb)
    _count_data_pass()  # deferred re-scan: one traversal per plan
    split = split_joint_lanes(tuple(sizes))
    if split is None:  # planner should have gated; double-check
        raise SpillOverflow("joint key space exceeds two u64 lanes")
    two_lane = split < len(columns)
    if two_lane:
        key2_fn = _joint_chunk_key2_fn(split, len(columns) - split)
        sizes1 = jnp.asarray(np.asarray(sizes[:split], dtype=np.int64))
        sizes2 = jnp.asarray(np.asarray(sizes[split:], dtype=np.int64))
    else:
        key_fn = _joint_chunk_key_fn(len(columns))
        sizes_dev = jnp.asarray(np.asarray(sizes, dtype=np.int64))

    keys_parts = []
    keys2_parts = []
    n_sentinel = jnp.int64(0)
    for chunk in dataset.device_scan_chunks(
        requests,
        batch_size,
        chunk_batches=chunk_batches,
        budget_bytes=config.options().device_cache_bytes,
    ):
        rows = chunk[ROW_MASK]
        if pred is not None:
            flat = {k: v.reshape(-1) for k, v in chunk.items()}
            rows = rows & pred.complies(flat).reshape(rows.shape)
        codes = tuple(chunk[f"{c}::codes"] for c in columns)
        masks = tuple(chunk[f"{c}::mask"] for c in columns)
        if two_lane:
            k1, k2, ns = key2_fn(codes, masks, rows, sizes1, sizes2)
            keys_parts.append(k1)
            keys2_parts.append(k2)
        else:
            k, ns = key_fn(codes, masks, rows, sizes_dev)
            keys_parts.append(k)
        n_sentinel = n_sentinel + ns

    def _joined(parts):
        return jnp.concatenate(parts) if len(parts) > 1 else parts[0]

    keys = _joined(keys_parts)
    n = keys.shape[0]
    padded = 1 << max(1, int(n - 1).bit_length()) if n > 1 else 1
    pad = padded - n
    if pad:
        keys = jnp.concatenate(
            [keys, jnp.full(pad, _SENTINEL, dtype=keys.dtype)]
        )
        n_sentinel = n_sentinel + pad

    from deequ_tpu.engine.pack import packed_device_get

    if two_lane:
        keys2 = _joined(keys2_parts)
        if pad:
            keys2 = jnp.concatenate(
                [keys2, jnp.full(pad, _SENTINEL, dtype=keys2.dtype)]
            )
        scalars, group_hi, group_lo, counts = _finalize2_fn()(
            keys, keys2, n_sentinel
        )
        scalars = packed_device_get(scalars)
        return TwoLaneDeviceFrequencies(
            plan.columns,
            scalars,
            group_hi,
            group_lo,
            counts,
            list(dictionaries),
            list(sizes),
            split,
        )

    scalars, group_keys, counts = _finalize_fn()(keys, n_sentinel)
    scalars = packed_device_get(scalars)
    return DeviceFrequencies(
        plan.columns,
        np.dtype(np.int64),
        scalars,
        group_keys,
        counts,
        0,
        False,
        joint=(list(dictionaries), list(sizes)),
    )


def device_spill_frequencies(
    dataset: Dataset, plan, engine
) -> "DeviceFrequencies":
    """One high-cardinality frequency pass fully on device (sharded
    across the engine's mesh when one is set)."""
    from deequ_tpu import config
    from deequ_tpu.engine.scan import CHUNK_BATCHES
    from deequ_tpu.sql.predicate import compile_predicate

    column = plan.columns[0]
    values_dtype = dataset.request_dtype(ColumnRequest(column, "values"))
    if values_dtype.kind != "f":
        key_kind = "int"
    elif np.dtype(values_dtype).itemsize == 8:
        key_kind = "f64"
    else:
        key_kind = "f32"
    requests = [
        ColumnRequest(column, "values"),
        ColumnRequest(column, "mask"),
    ]
    pred = None
    if plan.where is not None:
        pred = compile_predicate(plan.where, dataset)
        requests += list(pred.requests)

    import jax as _jax

    host_f64 = key_kind == "f64" and _jax.default_backend() != "cpu"

    if engine is not None and getattr(engine, "mesh", None) is not None:
        # f64 on non-CPU meshes rides host-packed bits inside the
        # sharded build — see _sharded_spill_frequencies
        return _sharded_spill_frequencies(
            dataset, plan, engine, column, values_dtype, key_kind, pred
        )

    batch_size = engine._resolve_batch_size(dataset.num_rows)
    nb = dataset.num_batches(batch_size)
    chunk_batches = min(CHUNK_BATCHES, nb)
    _count_data_pass()  # deferred re-scan: one traversal per plan

    if host_f64:
        # u64 keys packed on the HOST (host_f64_u64_keys; the TPU X64
        # rewriter cannot lower the f64->u64 bitcast — measured r4),
        # shipped instead of the values: same wire bytes, and the
        # device sort/segment path below is shared untouched
        parts, n_sent, n_nul = [], 0, 0
        for batch in dataset.device_batches(requests, batch_size):
            rows = np.asarray(batch[ROW_MASK], dtype=bool)
            if pred is not None:
                rows = rows & np.asarray(pred.complies(batch), dtype=bool)
            k, ns, nn = host_f64_u64_keys(
                batch[f"{column}::values"],
                np.asarray(batch[f"{column}::mask"], dtype=bool),
                rows,
                bool(plan.include_nulls),
            )
            parts.append(k)
            n_sent += ns
            n_nul += nn
        host_keys = (
            np.concatenate(parts) if len(parts) > 1 else parts[0]
        )
        from deequ_tpu.data.table import add_transfer_bytes

        add_transfer_bytes(host_keys.nbytes)
        keys = _jax.device_put(host_keys)
        n_sentinel = jnp.int64(n_sent)
        n_null = jnp.int64(n_nul)
    else:
        key_fn = _chunk_key_fn(key_kind, bool(plan.include_nulls))

        keys_parts = []
        n_sentinel = jnp.int64(0)
        n_null = jnp.int64(0)
        for chunk in dataset.device_scan_chunks(
            requests,
            batch_size,
            chunk_batches=chunk_batches,
            budget_bytes=config.options().device_cache_bytes,
        ):
            rows = chunk[ROW_MASK]
            if pred is not None:
                flat = {k: v.reshape(-1) for k, v in chunk.items()}
                rows = rows & pred.complies(flat).reshape(rows.shape)
            k, ns, nn = key_fn(
                chunk[f"{column}::values"], chunk[f"{column}::mask"], rows
            )
            keys_parts.append(k)
            n_sentinel = n_sentinel + ns
            n_null = n_null + nn

        keys = (
            jnp.concatenate(keys_parts)
            if len(keys_parts) > 1
            else keys_parts[0]
        )
    # pad to pow2 so the (expensive-to-compile) sort program is shared
    # across datasets whose row counts round the same way
    n = keys.shape[0]
    padded = 1 << max(1, int(n - 1).bit_length()) if n > 1 else 1
    if padded != n:
        keys = jnp.concatenate(
            [keys, jnp.full(padded - n, _SENTINEL, dtype=keys.dtype)]
        )
        n_sentinel = n_sentinel + (padded - n)

    scalars, group_keys, counts = _finalize_fn()(keys, n_sentinel)
    from deequ_tpu.engine.pack import packed_device_get

    fetched = packed_device_get((scalars, n_null))
    scalars, n_null_host = fetched
    return DeviceFrequencies(
        plan.columns,
        values_dtype,
        scalars,
        group_keys,
        counts,
        int(n_null_host),
        bool(plan.include_nulls),
    )


def _sharded_spill_frequencies(
    dataset: Dataset,
    plan,
    engine,
    column: str,
    values_dtype: np.dtype,
    key_kind: str,
    pred,
) -> "ShardedDeviceFrequencies":
    """Mesh variant: build the global u64 key vector (row-sharded over
    the dp axis), then run the hash-bucket all_to_all re-shard + local
    sort (see _sharded_spill_fn). Raises SpillOverflow when a bucket
    exceeds its static capacity; the caller falls back to Arrow."""
    import jax as _jax

    needed = {ColumnRequest(column, "values"), ColumnRequest(column, "mask")}
    if pred is not None:
        needed.update(pred.requests)
    include_nulls = bool(plan.include_nulls)
    host_bits = key_kind == "f64" and (
        _jax.default_backend() != "cpu" or _FORCE_HOST_F64_BITS
    )
    extra = None
    if host_bits:
        # the TPU X64 rewriter can't lower the f64 bitcast, so the
        # canonical u64 bits pack on the HOST and stage like a column;
        # the jitted build only applies mask/sentinel bookkeeping
        if pred is None or ColumnRequest(column, "values") not in set(
            pred.requests
        ):  # the predicate may still need the raw values
            needed.discard(ColumnRequest(column, "values"))
        extra = {
            "__f64bits__": f64_canonical_bits(
                dataset.materialize(ColumnRequest(column, "values"))
            )
        }
    key_fn = (
        None if host_bits else _chunk_key_fn(key_kind, include_nulls)
    )

    def build(batch):
        rows = batch[ROW_MASK]
        if pred is not None:
            rows = rows & pred.complies(batch)
        if host_bits:  # bits pre-canonicalized on the host; shared
            # sentinel/null bookkeeping (_finish_keys, the one copy)
            return _finish_keys(
                batch["__f64bits__"],
                batch[f"{column}::mask"],
                rows,
                include_nulls,
            )
        return key_fn(
            batch[f"{column}::values"], batch[f"{column}::mask"], rows
        )

    scalars, g_keys, g_counts, segs_host, n_null_host = _sharded_shuffle(
        dataset, engine, needed, build, label=repr(column),
        extra_arrays=extra,
    )
    state = ShardedDeviceFrequencies(
        plan.columns,
        values_dtype,
        scalars,
        g_keys,
        g_counts,
        n_null_host,
        bool(plan.include_nulls),
    )
    state._dev = (g_keys, g_counts, segs_host)
    return state


# --------------------------------------------------------------------------
# one-pass collectors: spill key extraction riding the SHARED fused scan
# --------------------------------------------------------------------------


class CollectorSpec:
    """One spill plan's ride on the shared fused scan.

    ``requests`` + ``ops`` slot into ``engine.run_scan`` next to the
    scalar/dense ops; the ops' state is the device-resident key buffer
    (``ScanOps.device_result`` keeps it out of the epilogue fetch).
    After the scan, ``dispatch(final_state)`` launches this plan's
    sort + segment-count finalize ASYNC and returns
    ``(pending, build)``: the caller dispatches EVERY plan first —
    overlapping the per-plan sorts on device — then fetches all
    pendings in one packed transfer and calls ``build(fetched)`` to
    construct the FrequenciesAndNumRows state. ``build`` may raise
    :class:`SpillOverflow` (sharded hash bucket past capacity); the
    planner attaches ``overflow_fallback`` (host Arrow) and
    ``scan_fallback`` (the deferred per-plan re-scan, for when the
    shared scan itself fails) plus ``on_success`` telemetry."""

    def __init__(self, plan, requests, ops, path, dispatch):
        self.plan = plan
        self.requests = list(requests)
        self.ops = ops
        self.path = path  # telemetry label ("device-sort"[-joint])
        self._dispatch = dispatch
        # wired by the planner (grouping.plan_frequency_passes)
        self.on_success = lambda: None
        self.overflow_fallback = None
        self.scan_fallback = None

    def dispatch(self, state):
        return self._dispatch(state)


def _pow2_len(n: int) -> int:
    """The key-vector padding rule the deferred path uses (pad to pow2
    so the expensive-to-compile sort program is shared across datasets
    whose row counts round the same way) — collector buffers MUST use
    the identical rule for bit-identical finalize inputs."""
    return 1 << max(1, int(n - 1).bit_length()) if n > 1 else 1


def _collector_geometry(dataset: Dataset, engine):
    """(mesh, axis, ndev, local_cap): buffer geometry for a collector.

    ``local_cap`` is the pow2-padded per-device key capacity derived
    from the shared scan's exact row feed (``engine.scan_row_capacity``
    — every batch row including the zero-padded tail lands in the
    buffer; padding rows key to the sentinel like any dropped row).
    Single-device this equals the deferred path's padded key length
    exactly; under a mesh it matches _stage_mesh_columns' per-shard
    ``m_local`` whenever the default batch geometry is in effect."""
    capacity = engine.scan_row_capacity(dataset)
    mesh = getattr(engine, "mesh", None)
    if mesh is None:
        return None, None, 1, _pow2_len(capacity)
    axis = engine.dp_axis
    ndev = mesh.shape[axis]
    # batch_size is rounded to an ndev multiple, so this divides evenly
    return mesh, axis, ndev, _pow2_len(max(1, capacity // ndev))


def _mesh_bucket_cap(m_local: int, ndev: int) -> int:
    """The sharded shuffle's per-(sender, bucket) capacity — the SAME
    formula as _stage_mesh_columns so compiled shuffle programs are
    shared between the collector and deferred forms."""
    return 1 << max(8, ((4 * m_local) // ndev - 1).bit_length())


def _collector_ops(batch_keys, mesh, axis, ndev, local_cap, n_lanes,
                   cache_token):
    """Build the collector ``ScanOps``: state is ``(buffers, offset,
    n_sentinel, n_null)`` where each buffer is a sentinel-filled u64
    key lane — flat ``(local_cap,)`` single-device, or
    ``(ndev, local_cap)`` sharded ``P(axis, None)`` under a mesh so
    each shard appends its own rows and the dynamic write offset lives
    on the replicated dim. ``batch_keys(batch, consts)`` -> (lanes
    tuple, n_sentinel, n_null) per batch; every batch appends exactly
    its row count, so the final offset is statically full — unwritten
    pow2-padding slots stay sentinel and are added to the correction
    at dispatch time, exactly like the deferred path's explicit pad."""
    from deequ_tpu.analyzers.base import ScanOps

    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P

        sharding = NamedSharding(mesh, P(axis, None))

        def make_buffer():
            return jax.device_put(
                jnp.full((ndev, local_cap), _SENTINEL, dtype=jnp.uint64),
                sharding,
            )
    else:

        def make_buffer():
            return jnp.full(local_cap, _SENTINEL, dtype=jnp.uint64)

    def init():
        return (
            tuple(make_buffer() for _ in range(n_lanes)),
            jnp.int64(0),  # rows written (per shard under a mesh)
            jnp.int64(0),  # sentinel (non-contributing) rows so far
            jnp.int64(0),  # null rows kept (include_nulls plans)
        )

    def update(state, batch, consts=None):
        buffers, offset, ns, nn = state
        lanes, s, null = batch_keys(batch, consts)
        if mesh is not None:
            written = lanes[0].shape[0] // ndev
            new_buffers = tuple(
                jax.lax.dynamic_update_slice(
                    buf,
                    lane.reshape(ndev, written),
                    (jnp.int32(0), offset.astype(jnp.int32)),
                )
                for buf, lane in zip(buffers, lanes)
            )
        else:
            written = lanes[0].shape[0]
            new_buffers = tuple(
                jax.lax.dynamic_update_slice(
                    buf, lane, (offset.astype(jnp.int32),)
                )
                for buf, lane in zip(buffers, lanes)
            )
        return (new_buffers, offset + written, ns + s, nn + null)

    def merge(a, b):
        raise NotImplementedError(
            "collector states accumulate through ONE shared scan; "
            "they never merge across scans"
        )

    return ScanOps(
        init, update, merge, cache_token=cache_token, device_result=True
    )


def single_collector_spec(
    dataset: Dataset, plan, engine
) -> "CollectorSpec":
    """The one-pass twin of device_spill_frequencies: a CollectorSpec
    whose ops accumulate the single grouping column's u64 keys through
    the shared scan, and whose dispatch runs the identical finalize
    (single-device sort or sharded shuffle) over the buffer."""
    import jax as _jax
    from deequ_tpu.sql.predicate import compile_predicate

    column = plan.columns[0]
    values_dtype = dataset.request_dtype(ColumnRequest(column, "values"))
    if values_dtype.kind != "f":
        key_kind = "int"
    elif np.dtype(values_dtype).itemsize == 8:
        key_kind = "f64"
    else:
        key_kind = "f32"
    include_nulls = bool(plan.include_nulls)
    # f64 on backends whose X64 rewriter can't lower the bitcast (TPU):
    # the canonical u64 bits pack on the HOST as the "u64bits" column
    # repr and ride the normal batch pipeline — still one pass
    host_bits = key_kind == "f64" and (
        _jax.default_backend() != "cpu" or _FORCE_HOST_F64_BITS
    )
    value_req = ColumnRequest(column, "u64bits" if host_bits else "values")
    requests = [value_req, ColumnRequest(column, "mask")]
    pred = None
    if plan.where is not None:
        pred = compile_predicate(plan.where, dataset)
        requests += list(pred.requests)

    mesh, axis, ndev, local_cap = _collector_geometry(dataset, engine)
    key_fn = None if host_bits else _chunk_key_fn(key_kind, include_nulls)

    def batch_keys(batch, _consts):
        rows = batch[ROW_MASK]
        if pred is not None:
            rows = rows & pred.complies(batch)
        if host_bits:
            k, s, null = _finish_keys(
                batch[value_req.key], batch[f"{column}::mask"], rows,
                include_nulls,
            )
        else:
            k, s, null = key_fn(
                batch[value_req.key], batch[f"{column}::mask"], rows
            )
        return (k,), s, null

    token = None
    if pred is None or getattr(pred, "dataset_independent", False):
        token = (
            "spill-collector", (column,), key_kind, host_bits,
            include_nulls, plan.where, local_cap, ndev,
        )
    ops = _collector_ops(
        batch_keys, mesh, axis, ndev, local_cap, 1, token
    )

    if mesh is None:

        def dispatch(state):
            (buf,), off, ns, nn = state
            # unwritten pow2 tail slots hold the sentinel from init
            ns_total = ns + (jnp.int64(local_cap) - off)
            scalars, group_keys, counts = _finalize_fn()(buf, ns_total)

            def build(fetched):
                scalars_h, n_null_h = fetched
                return DeviceFrequencies(
                    plan.columns, values_dtype, scalars_h, group_keys,
                    counts, int(n_null_h), include_nulls,
                )

            return (scalars, nn), build

    else:
        cap = _mesh_bucket_cap(local_cap, ndev)

        def dispatch(state):
            (buf,), off, ns, nn = state
            # per-shard unwritten slots x ndev shards
            ns_total = ns + (jnp.int64(ndev * local_cap) - off * ndev)
            out = _sharded_spill_fn(mesh, axis, cap)(
                buf.reshape(-1), ns_total, nn
            )
            scalars, g_keys, g_counts, g_segs, overflow, n_null_g = out

            def build(fetched):
                scalars_h, overflow_h, n_null_h, segs_h = fetched
                if int(overflow_h) > 0:
                    raise SpillOverflow(
                        f"hash bucket exceeded capacity {cap} on "
                        f"{column!r}"
                    )
                st = ShardedDeviceFrequencies(
                    plan.columns, values_dtype, scalars_h, g_keys,
                    g_counts, int(n_null_h), include_nulls,
                )
                st._dev = (g_keys, g_counts, segs_h)
                return st

            return (scalars, overflow, n_null_g, g_segs), build

    return CollectorSpec(plan, requests, ops, "device-sort", dispatch)


def joint_collector_spec(
    dataset: Dataset, plan, engine, dictionaries, sizes
) -> "CollectorSpec":
    """The one-pass twin of device_spill_joint_frequencies: joint
    mixed-radix codes on one u64 lane (or two past 2^62) accumulate
    through the shared scan; dispatch runs the matching finalize."""
    from deequ_tpu.sql.predicate import compile_predicate

    columns = list(plan.columns)
    split = split_joint_lanes(tuple(sizes))
    if split is None:  # eligibility should have gated; double-check
        raise SpillOverflow("joint key space exceeds two u64 lanes")
    two_lane = split < len(columns)
    requests = [ColumnRequest(c, "codes") for c in columns] + [
        ColumnRequest(c, "mask") for c in columns
    ]
    pred = None
    if plan.where is not None:
        pred = compile_predicate(plan.where, dataset)
        requests += list(pred.requests)

    mesh, axis, ndev, local_cap = _collector_geometry(dataset, engine)

    # per-column radix sizes ride ScanOps.consts (runtime inputs, like
    # the dense ops' LUTs) so compiled plans stay shareable
    if two_lane:
        consts = {
            "sizes1": np.asarray(sizes[:split], dtype=np.int64),
            "sizes2": np.asarray(sizes[split:], dtype=np.int64),
        }
        key2_fn = _joint_chunk_key2_fn(split, len(columns) - split)
    else:
        consts = {"sizes": np.asarray(sizes, dtype=np.int64)}
        key_fn = _joint_chunk_key_fn(len(columns))

    def batch_keys(batch, c):
        rows = batch[ROW_MASK]
        if pred is not None:
            rows = rows & pred.complies(batch)
        codes = tuple(batch[f"{col}::codes"] for col in columns)
        masks = tuple(batch[f"{col}::mask"] for col in columns)
        if two_lane:
            k1, k2, s = key2_fn(
                codes, masks, rows, c["sizes1"], c["sizes2"]
            )
            return (k1, k2), s, jnp.int64(0)
        k, s = key_fn(codes, masks, rows, c["sizes"])
        return (k,), s, jnp.int64(0)  # no null group (gated)

    token = None
    if pred is None or getattr(pred, "dataset_independent", False):
        token = (
            "spill-collector-joint", tuple(columns), two_lane, split,
            plan.where, local_cap, ndev,
        )
    ops = _collector_ops(
        batch_keys, mesh, axis, ndev, local_cap,
        2 if two_lane else 1, token,
    )
    ops.consts = consts
    joint = (list(dictionaries), list(sizes))

    if mesh is None:
        if two_lane:

            def dispatch(state):
                (b1, b2), off, ns, _nn = state
                ns_total = ns + (jnp.int64(local_cap) - off)
                scalars, g_hi, g_lo, counts = _finalize2_fn()(
                    b1, b2, ns_total
                )

                def build(fetched):
                    return TwoLaneDeviceFrequencies(
                        plan.columns, fetched, g_hi, g_lo, counts,
                        joint[0], joint[1], split,
                    )

                return scalars, build

        else:

            def dispatch(state):
                (buf,), off, ns, _nn = state
                ns_total = ns + (jnp.int64(local_cap) - off)
                scalars, group_keys, counts = _finalize_fn()(
                    buf, ns_total
                )

                def build(fetched):
                    return DeviceFrequencies(
                        plan.columns, np.dtype(np.int64), fetched,
                        group_keys, counts, 0, False, joint=joint,
                    )

                return scalars, build

    else:
        cap = _mesh_bucket_cap(local_cap, ndev)
        if two_lane:

            def dispatch(state):
                (b1, b2), _off, _ns, _nn = state
                # the 2-lane shuffle drops sentinel rows itself; no
                # correction scalar enters (matching _sharded_shuffle2)
                out = _sharded_spill2_fn(mesh, axis, cap)(
                    b1.reshape(-1), b2.reshape(-1)
                )
                scalars, g_hi, g_lo, g_counts, g_segs, overflow = out

                def build(fetched):
                    scalars_h, overflow_h, segs_h = fetched
                    if int(overflow_h) > 0:
                        raise SpillOverflow(
                            f"hash bucket exceeded capacity {cap} on "
                            f"joint2 {columns!r}"
                        )
                    st = ShardedTwoLaneDeviceFrequencies(
                        plan.columns, scalars_h, g_hi, g_lo, g_counts,
                        joint[0], joint[1], split,
                    )
                    st._segs = segs_h
                    return st

                return (scalars, overflow, g_segs), build

        else:

            def dispatch(state):
                (buf,), off, ns, _nn = state
                ns_total = ns + (
                    jnp.int64(ndev * local_cap) - off * ndev
                )
                out = _sharded_spill_fn(mesh, axis, cap)(
                    buf.reshape(-1), ns_total, jnp.int64(0)
                )
                scalars, g_keys, g_counts, g_segs, overflow, _nng = out

                def build(fetched):
                    scalars_h, overflow_h, segs_h = fetched
                    if int(overflow_h) > 0:
                        raise SpillOverflow(
                            f"hash bucket exceeded capacity {cap} on "
                            f"joint {columns!r}"
                        )
                    st = ShardedDeviceFrequencies(
                        plan.columns, np.dtype(np.int64), scalars_h,
                        g_keys, g_counts, 0, False, joint=joint,
                    )
                    st._dev = (g_keys, g_counts, segs_h)
                    return st

                return (scalars, overflow, g_segs), build

    return CollectorSpec(
        plan, requests, ops, "device-sort-joint", dispatch
    )


# --------------------------------------------------------------------------
# cross-host (multi-process) spill — docs/MULTIHOST.md steps 1-4
# --------------------------------------------------------------------------


class MultihostDeviceFrequencies(ShardedDeviceFrequencies):
    """ShardedDeviceFrequencies whose shards span PROCESSES: count
    metrics read the replicated psum scalars (fetchable on every
    host); Histogram's top-k merges per-shard candidates gathered
    across processes; the full (keys, counts) union is gathered only
    if something actually reads ``.keys``/``.counts`` (persistence).

    COLLECTIVE CONTRACT: ``top_groups`` / ``.keys`` / ``.counts``
    issue ``process_allgather`` collectives lazily — EVERY process
    must reach them together (SPMD), exactly like the call that built
    this state. Reading them from one process only (e.g. inside an
    ``if process_index() == 0:`` block) strands the peers in the
    collective. The scalar count metrics (CountDistinct/Uniqueness/
    Distinctness/Entropy) are replicated and safe to read anywhere."""

    def _local_live_pairs(self):
        """(keys, counts) concatenated over THIS process's shards."""
        g_keys, g_counts, g_segs = self._dev
        segs_by_dev = {
            s.device: int(np.asarray(s.data)[0])
            for s in g_segs.addressable_shards
        }
        counts_by_dev = {
            s.device: np.asarray(s.data)
            for s in g_counts.addressable_shards
        }
        keys_parts, count_parts = [], []
        for s in g_keys.addressable_shards:
            seg = segs_by_dev[s.device]
            raw_k = np.asarray(s.data)[:seg]
            raw_c = counts_by_dev[s.device][:seg]
            live = raw_c > 0
            keys_parts.append(raw_k[live])
            count_parts.append(raw_c[live].astype(np.int64))
        if not keys_parts:
            return (
                np.zeros(0, np.uint64),
                np.zeros(0, np.int64),
            )
        return (
            np.concatenate(keys_parts),
            np.concatenate(count_parts),
        )

    @staticmethod
    def _allgather_varlen(keys: np.ndarray, counts: np.ndarray):
        """Gather variable-length (keys, counts) from every process:
        sizes first, pad to the max, one fixed-shape allgather."""
        import jax.numpy as jnp
        from jax.experimental import multihost_utils

        n = len(keys)
        sizes = np.asarray(
            multihost_utils.process_allgather(
                jnp.asarray([n], dtype=jnp.int64)
            )
        ).reshape(-1)
        cap = int(sizes.max()) if len(sizes) else 0
        if cap == 0:
            return np.zeros(0, np.uint64), np.zeros(0, np.int64)
        pk = np.zeros(cap, np.uint64)
        pk[:n] = keys
        pc = np.zeros(cap, np.int64)
        pc[:n] = counts
        gk = np.asarray(
            multihost_utils.process_allgather(
                jnp.asarray(pk.view(np.int64))
            )
        ).reshape(-1, cap)
        gc = np.asarray(
            multihost_utils.process_allgather(jnp.asarray(pc))
        ).reshape(-1, cap)
        out_k, out_c = [], []
        for p, sz in enumerate(sizes):
            out_k.append(gk[p, : int(sz)].view(np.uint64))
            out_c.append(gc[p, : int(sz)])
        return np.concatenate(out_k), np.concatenate(out_c)

    def _fetch(self) -> None:
        if self._counts_host is None:
            keys, counts = self._allgather_varlen(
                *self._local_live_pairs()
            )
            if self._legit_max > 0:
                keys = np.concatenate(
                    [keys, np.array([_SENTINEL], dtype=np.uint64)]
                )
                counts = np.concatenate(
                    [counts, np.array([self._legit_max], np.int64)]
                )
            self._keys_host = keys
            self._counts_host = counts
        self._set_joint_lazy()

    def top_groups(self, k: int):
        # per-process top-k candidates (shards own disjoint key
        # ranges, so the global top-k is within the union of
        # per-process top-k when each contributes k candidates)
        keys, counts = self._local_live_pairs()
        if len(counts) > k:
            order = np.argsort(-counts, kind="stable")[:k]
            keys, counts = keys[order], counts[order]
        g_keys, g_counts = self._allgather_varlen(keys, counts)
        if self._legit_max > 0:
            g_keys = np.concatenate(
                [g_keys, np.array([_SENTINEL], dtype=np.uint64)]
            )
            g_counts = np.concatenate(
                [g_counts, np.array([self._legit_max], np.int64)]
            )
        order = np.argsort(-g_counts, kind="stable")[:k]
        pairs = list(
            zip(self._decode_keys(g_keys[order]), g_counts[order])
        )
        return _pack_top_pairs(
            pairs, k, self._null_rows if self._has_null_group else 0
        )


def multihost_spill_frequencies(
    dataset: Dataset, plan, mesh, axis: str = "dp"
) -> "MultihostDeviceFrequencies":
    """High-cardinality frequencies across PROCESSES (docs/MULTIHOST.md
    'High-cardinality grouping across hosts', steps 1-4): every process
    holds ITS OWN shard-table; u64 keys build locally, assemble into
    one globally-sharded array (``make_array_from_process_local_data``),
    and the SAME bucketed ``all_to_all`` shuffle + per-shard sort +
    segment count (_sharded_spill_fn) runs SPMD across hosts — equal
    keys land on one device wherever their rows lived, key ranges end
    up disjoint, and the count metrics psum into replicated scalars no
    host ever re-merges. The 10M-group state never crosses hosts;
    Histogram fetches only per-shard top-k candidates.

    ``where`` predicates evaluate PER ROW on each host's own shard
    (compiled against that shard's dictionaries) before the key build,
    so any supported predicate works — the shuffle only ever sees the
    surviving keys. Scope: single grouping column. Raises
    SpillOverflow exactly like the single-host path when a hash bucket
    exceeds its static capacity."""
    import jax
    from jax.experimental import multihost_utils
    from jax.sharding import NamedSharding, PartitionSpec as P

    column = plan.columns[0]
    values_dtype = dataset.request_dtype(ColumnRequest(column, "values"))
    if values_dtype.kind != "f":
        key_kind = "int"
    elif np.dtype(values_dtype).itemsize == 8:
        key_kind = "f64"
    else:
        key_kind = "f32"
    host_bits = key_kind == "f64" and (
        jax.default_backend() != "cpu" or _FORCE_HOST_F64_BITS
    )

    pred = None
    pred_error: Optional[BaseException] = None
    if plan.where is not None:
        from deequ_tpu.sql.predicate import compile_predicate

        # compile BEFORE any collective — and make the outcome
        # UNIFORM: plan-budget checks depend on each shard's own
        # dictionaries, so one host can fail where another succeeds;
        # raising on only one host would strand its peers in the next
        # allgather forever (review finding). The first collective is
        # therefore a success-flag exchange every host participates in.
        try:
            pred = compile_predicate(plan.where, dataset)
        except Exception as exc:  # noqa: BLE001 — exchanged below
            pred_error = exc
    ok_flags = np.asarray(
        multihost_utils.process_allgather(
            jax.numpy.asarray(
                [0 if pred_error is not None else 1],
                dtype=jax.numpy.int32,
            )
        )
    ).reshape(-1)
    if not ok_flags.all():
        bad = [int(i) for i in np.nonzero(ok_flags == 0)[0]]
        raise ValueError(
            f"where-predicate compilation failed on host(s) {bad}"
            + (f": {pred_error!r}" if pred_error is not None else "")
        )

    ndev = mesh.shape[axis]
    local_devices = [
        d for d in mesh.devices.flat
        if d.process_index == jax.process_index()
    ]
    n_local_dev = len(local_devices)
    n_local = dataset.num_rows

    # globally agreed per-device capacity: every process computes the
    # same pow2 from the allgathered (rows, devices) pairs
    shape_info = np.asarray(
        multihost_utils.process_allgather(
            jax.numpy.asarray([n_local, n_local_dev], dtype=jax.numpy.int64)
        )
    ).reshape(-1, 2)
    per_dev_needed = int(
        max(-(-int(r) // max(int(d), 1)) for r, d in shape_info)
    )
    per_dev = 1 << max(1, (max(per_dev_needed, 1) - 1).bit_length())
    padded_local = per_dev * n_local_dev

    def pad_to(host: np.ndarray) -> np.ndarray:
        if len(host) < padded_local:
            host = np.concatenate(
                [host, np.zeros(padded_local - len(host), host.dtype)]
            )
        return host

    _count_data_pass()  # materializes the shard's columns: one pass
    values = pad_to(dataset.materialize(ColumnRequest(column, "values")))
    mask = pad_to(dataset.materialize(ColumnRequest(column, "mask")))
    rows = np.zeros(padded_local, dtype=bool)
    rows[:n_local] = True
    if pred is not None:
        batch = {
            r.key: pad_to(
                np.asarray(dataset.materialize(r))
            )
            for r in pred.requests
        }
        # one-shot eager eval (like the host_f64 key path): a fresh
        # jit wrapper here would recompile per call (review finding)
        complies = np.asarray(
            jax.device_get(pred.complies(batch)), dtype=bool
        )
        rows = rows & complies

    if host_bits:
        bits = pad_to(f64_canonical_bits(values[:n_local]))
        keys_local, n_sent_l, n_null_l = _finish_keys_jit(
            plan.include_nulls
        )(bits, mask, rows)
    else:
        keys_local, n_sent_l, n_null_l = _chunk_key_fn(
            key_kind, plan.include_nulls
        )(values, mask, rows)

    # global scalar bookkeeping: one tiny allgather
    sums = np.asarray(
        multihost_utils.process_allgather(
            jax.numpy.asarray(
                [int(n_sent_l), int(n_null_l)], dtype=jax.numpy.int64
            )
        )
    ).reshape(-1, 2)
    n_sent = int(sums[:, 0].sum())
    n_null = int(sums[:, 1].sum())

    sharding = NamedSharding(mesh, P(axis))
    g_keys = jax.make_array_from_process_local_data(
        sharding, np.asarray(keys_local)
    )
    cap = 1 << max(8, ((4 * per_dev) // ndev - 1).bit_length())
    out = _sharded_spill_fn(mesh, axis, cap)(
        g_keys,
        jax.numpy.int64(n_sent),
        jax.numpy.int64(n_null),
    )
    scalars, gk, gc, g_segs, overflow, _ = out
    host_scalars = {
        k: np.asarray(jax.device_get(v)) for k, v in scalars.items()
    }
    if int(np.asarray(jax.device_get(overflow))) > 0:
        raise SpillOverflow(
            f"hash bucket exceeded capacity {cap} on {column!r} "
            "(multihost)"
        )
    state = MultihostDeviceFrequencies(
        plan.columns,
        values_dtype,
        host_scalars,
        gk,
        gc,
        n_null,
        bool(plan.include_nulls),
    )
    state._dev = (gk, gc, g_segs)
    return state
