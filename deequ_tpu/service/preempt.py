"""Checkpoint-conserving preemption: interactive latency over BATCH.

When an INTERACTIVE ticket finds every worker busy or the
``DevicePool`` exhausted, the :class:`PreemptionController` picks the
YOUNGEST running solo BATCH group and fires its per-attempt preempt
token. The engine already knows how to die well: the cancel tunnels in
as ``ScanInterrupted``, the scan exits cleanly at the next batch
boundary, and — with a checkpointer attached — persists a final
``ScanCursor`` (``interruption.checkpointed=True``). The worker that
owns the victim then takes the preemption path instead of the terminal
one: journal a ``preempted`` record, revoke the placement lease, and
requeue the ticket at its ORIGINAL sequence number, so the victim
resumes ahead of any batch work submitted after it — on whatever slice
frees up, with zero recompute (cursor resume is keyed to the source
fingerprint + plan token, not the slice) and zero recompile (the
shape-keyed plan cache replays the compiled plan on any same-shape
slice).

The conservation invariant (docs/SERVICE.md "Preemption and
autoscaling"): no preemption may lose or duplicate a batch. Every
requeue/revoke call site is therefore required — structurally, by the
``preempt-discipline`` staticcheck rule — to first extract the
checkpoint-bearing cancel evidence via
:func:`preempt_checkpoint_evidence`; a ticket with no such evidence
(it completed before the cancel landed, or the USER's own token fired)
takes the normal terminal path and is never requeued.

Token layering: the preempt token is a CHILD of the handle's cancel
token (``CancelToken(parent=...)``), so a client cancel still
propagates into a running victim, while a preemption never marks the
handle cancelled — the run is QUEUED again, not terminal.

Everything here is allocated only when ``config.service_preemption``
is on; off (the default) the scheduler holds no controller, tickets
carry no preempt token, and ``run_cancel_token`` degrades to the
handle token the executor always used.
"""

from __future__ import annotations

import threading
from typing import Any, List, Optional

from deequ_tpu.engine.deadline import (
    CancelToken,
    RunCancelled,
    ScanInterruption,
)
from deequ_tpu.service.queue import Priority, RunTicket
from deequ_tpu.telemetry import get_telemetry

#: every preemption cancel reason starts with this — it is how the
#: evidence extractor tells a preemption apart from a client cancel
PREEMPT_REASON_PREFIX = "preempted:"

_UNSET = object()


def preempt_reason(run_id: str, demand: str) -> str:
    return f"{PREEMPT_REASON_PREFIX} run {run_id} yielded to {demand}"


def is_preempt_reason(reason: Any) -> bool:
    return isinstance(reason, str) and reason.startswith(
        PREEMPT_REASON_PREFIX
    )


def run_cancel_token(ticket: RunTicket) -> CancelToken:
    """The token the executor hands the engine for this attempt: the
    per-attempt preempt token when preemption armed one, else the
    handle's own token (bit-for-bit today's behavior)."""
    token = getattr(ticket, "preempt_token", None)
    return token if token is not None else ticket.handle.cancel_token


def preempt_checkpoint_evidence(
    ticket: RunTicket, outcome: Any = _UNSET
) -> Optional[ScanInterruption]:
    """The checkpoint-bearing cancel evidence licensing a requeue.

    Called WITH an outcome (a result or the exception the execution
    raised) it computes the evidence and caches it on the ticket;
    called without, it returns the cached evidence — so a later call
    site (lease revocation) reads the same verdict the finish path
    established. Returns ``None`` — take the normal terminal path —
    unless ALL of:

    - a preemption was actually requested for this attempt,
    - the client's own cancel token did NOT fire (a user cancel always
      wins: the run terminates CANCELLED with its partial result, it
      is not silently requeued), and
    - the outcome carries a cancel interruption whose reason is a
      preemption reason (or IS the ``RunCancelled`` the preempt token
      raised before the scan started — then the evidence is a
      synthetic un-checkpointed interruption: nothing ran, nothing to
      conserve, the requeue restarts from the last durable cursor).
    """
    if outcome is _UNSET:
        return getattr(ticket, "preempt_evidence", None)
    evidence: Optional[ScanInterruption] = None
    if getattr(ticket, "preempt_requested", False) and not (
        ticket.handle.cancel_token.cancelled
    ):
        if isinstance(outcome, BaseException):
            if isinstance(outcome, RunCancelled) and is_preempt_reason(
                str(outcome)
            ):
                evidence = ScanInterruption(
                    kind="cancelled",
                    reason=str(outcome),
                    checkpointed=False,
                )
        else:
            interruption = getattr(outcome, "interruption", None)
            if (
                interruption is not None
                and getattr(interruption, "kind", "") == "cancelled"
                and is_preempt_reason(getattr(interruption, "reason", ""))
            ):
                evidence = interruption
    ticket.preempt_evidence = evidence
    return evidence


class _RunningGroup:
    """One executing group as the controller sees it."""

    __slots__ = ("tickets", "started_at", "eligible", "requested")

    def __init__(
        self, tickets: List[RunTicket], started_at: float, eligible: bool
    ):
        self.tickets = tickets
        self.started_at = started_at
        self.eligible = eligible
        self.requested = False


class PreemptionController:
    """Registry of running groups + the victim-selection policy.

    Victims are SOLO all-BATCH groups only: a coalesced superset scan
    checkpoints under the GROUP's merged plan token, which a member
    resuming solo could not load — preempting one would recompute every
    member's work and break conservation. Queued or window-held BATCH
    tickets are never victims either: they hold no capacity, and the
    queue already yields them by skip (preemption-aware ``pop_group``),
    which costs nothing.
    """

    def __init__(
        self,
        clock: Any,
        max_preemptions_per_run: int = 3,
        durable_egress: bool = False,
    ):
        self.clock = clock
        self.max_preemptions_per_run = max(1, int(max_preemptions_per_run))
        # sink-carrying runs are admissible victims only when their
        # egress is DURABLE (the service runs with a checkpoint path,
        # so the writer's span cursor survives the cancel and the
        # re-execution resumes mid-artifact). Preempting a sink run
        # without that would restart its egress from row zero — worse
        # than making the demand wait.
        self.durable_egress = bool(durable_egress)
        self._lock = threading.Lock()
        self._running: List[_RunningGroup] = []

    # -- scheduler-side bookkeeping ----------------------------------

    def register(self, group: List[RunTicket]) -> _RunningGroup:
        """Arm a group about to execute: every member gets a fresh
        per-attempt preempt token (child of its handle token) and a
        clean evidence slate. Returns the record to ``deregister``."""
        for ticket in group:
            ticket.preempt_token = CancelToken(
                parent=ticket.handle.cancel_token
            )
            ticket.preempt_requested = False
            ticket.preempt_evidence = None
        eligible = len(group) == 1 and all(
            t.handle.priority >= Priority.BATCH
            and t.preemptions < self.max_preemptions_per_run
            and (
                self.durable_egress
                or getattr(
                    getattr(t, "payload", None), "row_level_sink", None
                )
                is None
            )
            for t in group
        )
        record = _RunningGroup(group, self.clock.now(), eligible)
        with self._lock:
            self._running.append(record)
        return record

    def deregister(self, record: _RunningGroup) -> None:
        with self._lock:
            try:
                self._running.remove(record)
            except ValueError:
                pass

    # -- the preemption decision -------------------------------------

    def preempt_for(self, demand: str) -> bool:
        """Preempt the youngest eligible running BATCH group on behalf
        of ``demand`` (an interactive run id). Returns True when a
        victim was cancelled; False when nothing is preemptible (the
        demand then waits its turn like today)."""
        with self._lock:
            candidates = [
                r
                for r in self._running
                if r.eligible and not r.requested
            ]
            if not candidates:
                return False
            victim = max(
                candidates,
                key=lambda r: (r.started_at, r.tickets[0].seq),
            )
            victim.requested = True
        tm = get_telemetry()
        for ticket in victim.tickets:
            ticket.preempt_requested = True
            ticket.preemptions += 1
            reason = preempt_reason(ticket.handle.run_id, demand)
            ticket.preempt_token.cancel(reason)
            tm.counter("service.preemptions").inc()
            tm.event(
                "service_run_preempt_requested",
                run_id=ticket.handle.run_id,
                tenant=ticket.handle.tenant,
                demand=demand,
                preemptions=ticket.preemptions,
            )
        return True

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "running_groups": len(self._running),
                "eligible_victims": sum(
                    1
                    for r in self._running
                    if r.eligible and not r.requested
                ),
            }
