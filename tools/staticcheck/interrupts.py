"""Interrupt-safety analyzer: protect the BaseException tunnel.

``ScanInterrupted`` (engine/deadline.py) and ``ScanKilled``
(engine/resilience.py) derive from ``BaseException`` ON PURPOSE: they
must tunnel through the ``except Exception`` retry/quarantine
machinery untouched (docs/RESILIENCE.md). Two handler shapes can break
that contract:

- ``interrupt-swallow``: a bare ``except:`` or an ``except
  BaseException`` handler with no ``raise`` anywhere in its body. Such
  a handler eats a deadline/cancel/kill signal and keeps running — the
  exact bug class the tunnel exists to rule out. A handler that
  re-raises (even conditionally) is fine; a handler that forwards the
  exception into an error channel instead of raising needs a waiver
  naming that channel.
- ``interrupt-named``: a handler that names a member of the interrupt
  family (``ScanInterrupted``/``ScanKilled``) without re-raising.
  Catching the family is reserved for the engine's sanctioned
  clean-exit sites (checkpoint + partial result in engine/scan.py);
  anywhere else must re-raise or carry a waiver explaining why this
  site is allowed to terminate the tunnel.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Sequence

from tools.staticcheck.core import (
    Analyzer,
    Finding,
    SourceFile,
    dotted_name,
    register,
)

SCOPE_PREFIX = "deequ_tpu/"

INTERRUPT_NAMES = frozenset({"ScanInterrupted", "ScanKilled"})


def _handler_type_names(node: Optional[ast.AST]) -> List[str]:
    """Class names a handler catches ('' for a bare ``except:``)."""
    if node is None:
        return [""]
    if isinstance(node, ast.Tuple):
        out: List[str] = []
        for elt in node.elts:
            out.extend(_handler_type_names(elt))
        return out
    name = dotted_name(node)
    if name is None:
        return []
    return [name.split(".")[-1]]


def _reraises(handler: ast.ExceptHandler) -> bool:
    """True when any ``raise`` appears in the handler body (including
    conditional re-raise — flow-insensitive by design: a handler that
    CAN re-raise was written with the tunnel in mind)."""
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
    return False


class InterruptSafetyAnalyzer(Analyzer):
    name = "interrupts"
    rules = ("interrupt-swallow", "interrupt-named")
    description = (
        "broad exception handlers that can swallow the "
        "ScanInterrupted/ScanKilled BaseException tunnel"
    )

    def analyze(
        self, files: Sequence[SourceFile], root: str
    ) -> Iterable[Finding]:
        for sf in files:
            if not sf.rel.startswith(SCOPE_PREFIX) or sf.tree is None:
                continue
            for node in ast.walk(sf.tree):
                if not isinstance(node, ast.ExceptHandler):
                    continue
                caught = _handler_type_names(node.type)
                reraises = _reraises(node)
                if ("" in caught or "BaseException" in caught) and (
                    not reraises
                ):
                    what = (
                        "bare 'except:'"
                        if "" in caught
                        else "'except BaseException'"
                    )
                    yield Finding(
                        rule="interrupt-swallow",
                        path=sf.rel,
                        line=node.lineno,
                        message=(
                            f"{what} without re-raise can swallow the "
                            "ScanInterrupted/ScanKilled tunnel "
                            "(docs/RESILIENCE.md)"
                        ),
                        symbol="BaseException" if "" not in caught else "",
                    )
                named = sorted(set(caught) & INTERRUPT_NAMES)
                if named and not reraises:
                    yield Finding(
                        rule="interrupt-named",
                        path=sf.rel,
                        line=node.lineno,
                        message=(
                            f"handler catches {'/'.join(named)} without "
                            "re-raising — terminating the interrupt "
                            "tunnel is reserved for the engine's "
                            "sanctioned clean-exit sites"
                        ),
                        symbol=named[0],
                    )


register(InterruptSafetyAnalyzer())
