"""Data-parallel mesh execution: the same analysis sharded over devices.

No reference analog (Spark owns distribution there — SURVEY.md §2.6);
this is the TPU-native story: shard batches over the ``dp`` axis of a
``jax.sharding.Mesh`` and let XLA insert the collectives. On a machine
without multiple accelerators, run with:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/mesh_execution.py
"""

import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)  # allow running from a source checkout without installing

import numpy as np

import jax
from jax.sharding import Mesh

from deequ_tpu import (
    AnalysisEngine,
    Check,
    CheckLevel,
    Dataset,
    VerificationSuite,
)


def main():
    devices = np.array(jax.devices())
    if len(devices) == 1:
        # images that pre-import jax consume JAX_PLATFORMS before this
        # script runs; fall back to the config override (must happen
        # before the backend is initialized to take effect)
        print(
            "NOTE: only one device visible — a single-device mesh "
            "demonstrates no sharding. Re-run with the env vars from "
            "the module docstring, or on an image that pre-imports "
            "jax, set jax.config.update('jax_platforms', 'cpu') plus "
            "the XLA_FLAGS device-count flag before ANY jax use."
        )
    mesh = Mesh(devices, ("dp",))
    print(f"mesh: {len(devices)} x {devices[0].platform}")

    rng = np.random.default_rng(4)
    data = Dataset.from_pydict(
        {"v": rng.normal(10.0, 2.0, 1_000_000), "k": rng.integers(0, 9, 1_000_000)}
    )
    result = (
        VerificationSuite()
        .on_data(data)
        .with_engine(AnalysisEngine(mesh=mesh, batch_size=250_000))
        .add_check(
            Check(CheckLevel.ERROR, "sharded")
            .has_size(lambda s: s == 1_000_000)
            .has_mean("v", lambda m: 9.9 < m < 10.1)
            .has_number_of_distinct_values("k", lambda n: n == 9)
        )
        .run()
    )
    print(f"sharded verification: {result.status}")
    for rec in (result.run_metadata.as_records() if result.run_metadata else []):
        print(f"  [pass {rec['pass']}] {rec['wall_s']:.2f}s")


if __name__ == "__main__":
    main()
