"""Crash isolation: run a scan/verification in a child process.

The PR 3–5 resilience stack (retry, quarantine, checkpoint, watchdog,
OOM backoff) all lives INSIDE the process — a hard crash (SIGSEGV in
XLA, the OOM killer, a stray SIGKILL) tunnels past every layer of it
and takes the whole daemon down. ROADMAP item 1 documents exactly this
failure mode as seed-reproducible on ≥1M-row streamed runs in the CI
container. This module supplies the missing fault domain: the PROCESS.

- :class:`IsolatedRunner` — executes a picklable callable in a
  spawn-started child (JAX env inherited; the child re-applies the
  parent's ``jax_platforms`` before touching a backend), streams the
  result plus the child's telemetry run-summary back over a pipe, and
  classifies child death by exit status: death by signal (negative
  ``exitcode``) or a 128+N shell-convention status becomes
  :class:`ProcessCrashed`, a :class:`TransientScanError` subclass.
- relaunch-from-checkpoint — ``ScanCheckpointer`` cursors already
  persist to durable storage, so the runner simply relaunches the same
  callable: the scan resumes from the last cursor and the completed run
  is bit-identical to an uninterrupted one (monoid states, ordered host
  folds). A crash costs one checkpoint window, nothing more.
- crash-loop bound — ``config.crash_max_relaunches`` child launches
  WITHOUT checkpoint progress (an injectable ``progress_probe``
  observes cursor advancement between launches) declare the run a
  poison batch: :class:`CrashLoopError` is raised, which the
  verification layer floors through ``config.degradation_policy``.
- :class:`CircuitBreaker` — per-plan-key breaker registry. A declared
  crash loop trips the key's breaker OPEN; further launches for that
  key fail fast (:class:`BreakerOpen` with a retry-after hint) until
  ``crash_breaker_cooldown_s`` elapses, then ONE half-open probe is
  admitted — success closes the breaker, another crash loop re-opens
  it. Clocks are injectable (tests use ``ManualClock``).

Children are always joined and reaped — no zombies, enforced both by
``finally`` blocks here and by the ``subprocess-discipline`` static
rule (tools/staticcheck/procs.py). See docs/RESILIENCE.md "Crash
isolation and recovery".
"""

from __future__ import annotations

import multiprocessing
import os
import pickle
import signal as _signal
import threading
from typing import Any, Callable, Dict, Optional, Tuple

from deequ_tpu.engine.deadline import CancelToken, MonotonicClock
from deequ_tpu.engine.resilience import TransientScanError

#: env var the parent sets so the spawned child pins the same jax
#: platform BEFORE its backend initializes (the parent may have set
#: jax_platforms programmatically — children do not inherit jax.config)
CHILD_PLATFORM_ENV = "DEEQU_TPU_CHILD_JAX_PLATFORM"

#: env var carrying the parent's ``TraceContext.encode()`` across the
#: spawn boundary (trace_id : parent span id : process label) — the
#: only channel that survives spawn. When present, the child roots its
#: spans under the parent's span and STREAMS each finished span back
#: over the pipe as a ``("span", record)`` message ahead of the final
#: result tuple, so the parent still sees where a crashed child died.
CHILD_TRACE_ENV = "DEEQU_TPU_CHILD_TRACE"

#: env var carrying the parent replica's fleet epoch guard across the
#: spawn boundary (JSON: fleet_dir / replica / epoch, built by
#: ``FleetSupervisor.child_guard()``). A child re-reads the named lease
#: chain before each durable persist: if the chain has moved past the
#: shipped epoch the PARENT was fenced — a survivor adopted its runs —
#: and the child must stop persisting too (``child_epoch_fenced``),
#: or the zombie pair would rewind the adopter's cursors.
CHILD_EPOCH_ENV = "DEEQU_TPU_CHILD_EPOCH"


class ProcessCrashed(TransientScanError):
    """The child process died without delivering a result — killed by a
    signal or exited with a crash status. Transient ON PURPOSE: the
    checkpoint survives the crash, so a relaunch resumes the scan."""

    def __init__(
        self,
        message: str,
        *,
        exitcode: Optional[int] = None,
        signal_name: Optional[str] = None,
        launches: int = 1,
    ):
        super().__init__(message)
        self.exitcode = exitcode
        self.signal_name = signal_name
        self.launches = launches


class CrashLoopError(Exception):
    """The same work crashed the child ``crash_max_relaunches`` times
    without checkpoint progress — a poison batch / poison plan. The run
    fails cleanly (floored through ``config.degradation_policy``); the
    plan's circuit breaker is tripped."""

    def __init__(
        self,
        message: str,
        *,
        launches: int,
        last_exitcode: Optional[int] = None,
        last_signal: Optional[str] = None,
    ):
        super().__init__(message)
        self.launches = launches
        self.last_exitcode = last_exitcode
        self.last_signal = last_signal


class BreakerOpen(Exception):
    """The plan's crash-loop breaker is OPEN — the launch is rejected
    fast, without spawning a child. ``retry_after_s`` hints when the
    next half-open probe will be admitted."""

    def __init__(self, message: str, *, retry_after_s: float, key: str):
        super().__init__(message)
        self.retry_after_s = max(0.0, float(retry_after_s))
        self.key = key


class _ChildError(RuntimeError):
    """Carrier for a child exception that did not survive pickling —
    the class name and traceback text ride back instead."""

    def __init__(self, error_class: str, message: str, traceback_text: str):
        super().__init__(f"{error_class}: {message}")
        self.error_class = error_class
        self.traceback_text = traceback_text


# --------------------------------------------------------------------------
# Circuit breaker
# --------------------------------------------------------------------------

CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"


class CircuitBreaker:
    """Crash-loop breaker for ONE plan key: closed → (crash loop) open
    → (cooldown) half-open probe → closed on success / open on failure.
    ``clock`` is anything with ``.now() -> float`` (monotonic)."""

    def __init__(self, cooldown_s: float, clock: Optional[Any] = None):
        self.cooldown_s = float(cooldown_s)
        self._clock = clock or MonotonicClock()
        self._lock = threading.Lock()
        self._state = CLOSED
        self._opened_at = 0.0
        self._probing = False

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def admit(self, key: str = "") -> None:
        """Raise :class:`BreakerOpen` unless a launch may proceed. An
        OPEN breaker past its cooldown admits exactly one HALF_OPEN
        probe; concurrent launches during the probe are rejected."""
        from deequ_tpu.telemetry import get_telemetry

        with self._lock:
            if self._state == CLOSED:
                return
            now = self._clock.now()
            elapsed = now - self._opened_at
            if self._state == OPEN and elapsed >= self.cooldown_s:
                self._state = HALF_OPEN
                self._probing = True
                get_telemetry().event("crash_breaker_half_open", key=key)
                return
            if self._state == HALF_OPEN and not self._probing:
                self._probing = True
                return
            retry_after = max(0.0, self.cooldown_s - elapsed)
            raise BreakerOpen(
                f"crash-loop breaker open for {key or 'plan'} "
                f"(retry in {retry_after:.1f}s)",
                retry_after_s=retry_after,
                key=key,
            )

    def record_success(self, key: str = "") -> None:
        from deequ_tpu.telemetry import get_telemetry

        with self._lock:
            was = self._state
            self._state = CLOSED
            self._probing = False
        if was != CLOSED:
            get_telemetry().event("crash_breaker_closed", key=key)

    def record_crash_loop(self, key: str = "") -> None:
        from deequ_tpu.telemetry import get_telemetry

        with self._lock:
            self._state = OPEN
            self._opened_at = self._clock.now()
            self._probing = False
        get_telemetry().counter("engine.breaker_trips").inc()
        get_telemetry().event(
            "crash_breaker_open", key=key, cooldown_s=self.cooldown_s
        )


_breakers: Dict[str, CircuitBreaker] = {}
_breakers_lock = threading.Lock()


def breaker_for(
    key: str,
    cooldown_s: Optional[float] = None,
    clock: Optional[Any] = None,
) -> Optional[CircuitBreaker]:
    """The process-wide breaker for a plan key (created on first use).
    None when breakers are disabled (``crash_breaker_cooldown_s <= 0``)."""
    from deequ_tpu import config

    if cooldown_s is None:
        cooldown_s = config.options().crash_breaker_cooldown_s
    if cooldown_s is None or cooldown_s <= 0:
        return None
    with _breakers_lock:
        breaker = _breakers.get(key)
        if breaker is None:
            breaker = CircuitBreaker(cooldown_s, clock=clock)
            _breakers[key] = breaker
        return breaker


def reset_breakers() -> None:
    """Drop every registered breaker (test isolation)."""
    with _breakers_lock:
        _breakers.clear()


def breaker_states() -> Dict[str, str]:
    """The state of every registered crash-loop breaker, keyed by plan
    key — surfaced in the service's ``/healthz`` payload so a fleet
    dashboard sees tripped plans without scraping events."""
    with _breakers_lock:
        items = list(_breakers.items())
    return {key: breaker.state for key, breaker in items}


# --------------------------------------------------------------------------
# Checkpoint progress probe
# --------------------------------------------------------------------------


def checkpoint_progress_probe(path: str) -> Callable[[], Tuple]:
    """A progress probe over a ``ScanCheckpointer`` directory: returns a
    callable whose value changes whenever any checkpoint cursor under
    ``path`` advances. The runner compares probe values across child
    launches — a crash that happened LATER than the previous one is
    forward progress, not a loop, and resets the relaunch budget."""

    def probe() -> Tuple:
        from deequ_tpu.io.storage import storage_for

        storage = storage_for(path)
        out = []
        for key in sorted(storage.list_keys("scan-ckpt-")):
            raw = storage.read_bytes(key)
            if raw is None:
                continue
            try:
                payload = pickle.loads(raw)
            except Exception:  # noqa: BLE001 — torn blob = no progress info
                continue
            cursor = payload.get("cursor") if isinstance(payload, dict) else None
            batch_index = getattr(cursor, "batch_index", None)
            if batch_index is not None:
                # an egress cursor advancing (new durable span segment,
                # spool bytes) is forward progress even within one
                # batch_index — include it in the probe value
                eg = getattr(cursor, "egress", None)
                if eg is not None:
                    out.append(
                        (
                            key,
                            int(batch_index),
                            int(
                                getattr(
                                    eg,
                                    "last_durably_flushed_span_seq",
                                    -1,
                                )
                            ),
                            int(getattr(eg, "plane_spool_offset", 0)),
                        )
                    )
                else:
                    out.append((key, int(batch_index)))
        return tuple(out)

    return probe


# module-global hook: QuarantineWriter.flush_durable calls this after
# every durable rotation; in a spawned child it streams an
# ``("egress", record)`` frame to the parent (installed by
# ``_child_main``), everywhere else it is a no-op
_egress_notify: Optional[Callable[[Dict[str, Any]], None]] = None


def notify_egress_progress(record: Dict[str, Any]) -> None:
    """Report a durable egress flush to whoever is listening (the
    isolation parent, via the child's pipe). Best-effort: no listener,
    no cost; a torn pipe never fails the flush."""
    hook = _egress_notify
    if hook is not None:
        hook(record)


# --------------------------------------------------------------------------
# Child side
# --------------------------------------------------------------------------


def _apply_child_platform() -> None:
    """Pin the parent's jax platform in the child BEFORE any backend
    initialization (``jax.config`` does not cross the spawn boundary;
    only the environment does)."""
    platform = os.environ.get(CHILD_PLATFORM_ENV)
    if not platform:
        return
    try:
        import jax

        jax.config.update("jax_platforms", platform)
    except Exception:  # noqa: BLE001 — missing/initialized jax: run as-is
        pass


#: the cancel token for THIS process when it runs as an isolated child
#: — ``_child_main`` mints it fresh per child and a watcher thread
#: fires it when the parent sends a cancel message down the control
#: pipe. The child's work (``service._isolated_execute``) threads it
#: into the engine as ``cancel=``, so a preemption reaches a spawned
#: scan exactly like an in-process one: clean exit at the next batch
#: boundary, final checkpoint persisted, partial result shipped back
#: over the result pipe — never a SIGKILL.
_child_cancel: Optional[CancelToken] = None


def child_cancel_token() -> CancelToken:
    """The process-global cancel token a spawned child's work observes
    (a fresh, never-fired token outside a child)."""
    global _child_cancel
    if _child_cancel is None:
        _child_cancel = CancelToken()
    return _child_cancel


def child_epoch_fenced() -> bool:
    """True when this process carries a fleet epoch guard
    (``CHILD_EPOCH_ENV``) whose lease chain has moved past the shipped
    epoch — the parent replica was fenced, so this child must drop its
    durable persists too. False when no guard is set (no fleet) or the
    guard cannot be evaluated (an unreadable fleet dir must not stall a
    healthy child: the parent-side fence still protects the journal).

    Imports the storage layer lazily and re-reads the chain on every
    call — callers sit on checkpoint-interval cadence, not the batch
    hot path."""
    raw = os.environ.get(CHILD_EPOCH_ENV, "")
    if not raw:
        return False
    try:
        import json

        guard = json.loads(raw)
        fleet_dir = guard["fleet_dir"]
        replica = guard["replica"]
        epoch = int(guard["epoch"])
        from deequ_tpu.io.storage import storage_for

        storage = storage_for(fleet_dir)
        # mirrors service/fleet.py's lease layout (LEASE_DIR/_lease_key)
        # without importing service machinery into the child
        prefix = f"leases/lease-{replica}-"
        for key in storage.list_keys(prefix):
            blob = storage.read_bytes(key)
            if blob is None:
                continue
            body = json.loads(blob)
            if (
                body.get("replica") == replica
                and int(body.get("epoch", 0)) > epoch
            ):
                return True
        return False
    except Exception:  # noqa: BLE001 — unevaluable guard: stay open
        return False


def _child_trace(tm: Any) -> Optional[Any]:
    """Decode the parent's shipped trace (``CHILD_TRACE_ENV``) into the
    child's ambient context, re-tagged with a ``/child`` process label
    so a merged fleet timeline tells the two processes apart."""
    from deequ_tpu.telemetry.spans import TraceContext

    ctx = TraceContext.decode(os.environ.get(CHILD_TRACE_ENV, ""))
    if ctx is None or not tm.enabled:
        return None
    label = f"{ctx.process}/child" if ctx.process else "child"
    return TraceContext(ctx.trace_id, ctx.span_id, process=label)


def _watch_parent_cancel(cancel_conn: Any, token: CancelToken) -> None:
    """Child-side watcher: one blocking recv on the control pipe; a
    ``("cancel", reason)`` message fires the child's token. EOF (parent
    closed the pipe, i.e. the run ended without a cancel) just ends the
    watcher."""
    try:
        msg = cancel_conn.recv()
    except Exception:  # noqa: BLE001 — EOF/torn pipe: no cancel came
        return
    if isinstance(msg, tuple) and msg and msg[0] == "cancel":
        reason = msg[1] if len(msg) > 1 else "cancelled by parent"
        token.cancel(str(reason))


def _child_main(
    conn: Any,
    cancel_conn: Any,
    fn: Callable[[Any], Any],
    payload: Any,
) -> None:
    """Spawn entry point: run ``fn(payload)`` and ship ``("ok", result,
    telemetry_summary)`` or ``("err", exception, telemetry_summary)``
    back over the pipe. Anything that cannot pickle degrades to a
    :class:`_ChildError` carrier; a crash ships nothing and the parent
    classifies the exit status instead — though every span finished
    BEFORE the crash has already streamed out as a ``("span", record)``
    message, so the parent's trace still shows where the child died."""
    import traceback

    _apply_child_platform()
    from deequ_tpu.telemetry import get_telemetry

    tm = get_telemetry()
    global _child_cancel
    _child_cancel = CancelToken()
    # lint-ok: thread-discipline: child-process watcher, daemon by
    # design — it blocks on the control pipe for the child's whole
    # life and dies with the process; it never touches a scan
    threading.Thread(
        target=_watch_parent_cancel,
        args=(cancel_conn, _child_cancel),
        daemon=True,
        name="deequ-tpu-child-cancel",
    ).start()
    ctx = _child_trace(tm)
    send_lock = threading.Lock()
    if ctx is not None:

        def _stream_span(record: Dict[str, Any]) -> None:
            try:
                with send_lock:
                    conn.send(("span", record))
            except Exception:  # noqa: BLE001 — parent gone/pipe torn:
                # span streaming is best-effort, never fails the run
                pass

        tm.add_span_sink(_stream_span)

    # durable-egress progress frames are NOT gated on tracing: the
    # parent's crash-loop accounting needs them whenever a sink run is
    # isolated, traced or not (notify_egress_progress)
    def _stream_egress(record: Dict[str, Any]) -> None:
        try:
            with send_lock:
                conn.send(("egress", record))
        except Exception:  # noqa: BLE001 — best-effort, like spans
            pass

    global _egress_notify
    _egress_notify = _stream_egress
    try:
        with tm.trace_scope(ctx):
            with tm.run("isolated_child") as cap:
                result = fn(payload)
        message = ("ok", result, cap.final)
    except BaseException as exc:  # lint-ok: interrupt-swallow: child-side boundary — the exception (interrupts included) is pickled and shipped to the parent, which re-raises it; swallowing here IS the delivery
        summary = None
        try:
            summary = cap.final  # noqa: F821 — set when the run opened
        except Exception:  # noqa: BLE001
            pass
        try:
            pickle.dumps(exc)
            message = ("err", exc, summary)
        except Exception:  # noqa: BLE001 — unpicklable exception
            message = (
                "err",
                _ChildError(
                    type(exc).__name__, str(exc), traceback.format_exc()
                ),
                summary,
            )
    try:
        with send_lock:
            conn.send(message)
    except Exception:  # noqa: BLE001 — unpicklable RESULT: report, not crash
        with send_lock:
            conn.send(
                (
                    "err",
                    _ChildError(
                        "UnpicklableResult",
                        f"child result of type "
                        f"{type(message[1]).__name__} cannot cross the pipe",
                        "",
                    ),
                    None,
                )
            )
    finally:
        conn.close()


# --------------------------------------------------------------------------
# Parent side
# --------------------------------------------------------------------------


def _classify_exit(exitcode: Optional[int]) -> Tuple[str, Optional[str]]:
    """(description, signal_name) for a child that died without a
    message. Negative exitcode = killed by signal (multiprocessing
    convention); 128+N = the shell convention some runtimes re-raise."""
    if exitcode is None:
        return "child vanished without an exit status", None
    signum = None
    if exitcode < 0:
        signum = -exitcode
    elif exitcode >= 128:
        signum = exitcode - 128
    if signum is not None:
        try:
            name = _signal.Signals(signum).name
        except ValueError:
            name = f"signal {signum}"
        return f"child killed by {name} (exitcode {exitcode})", name
    return f"child exited with status {exitcode} before replying", None


class IsolatedRunner:
    """Run picklable work in spawn-started children, resuming across
    crashes from durable checkpoints.

    ``run(fn, payload)`` launches ``fn(payload)`` in a child and returns
    its result. On a crash the child is relaunched — ``fn`` must be
    resumable (checkpointer-backed scans are, by construction). Launches
    without observable progress are bounded by ``max_relaunches``; the
    breaker for ``key`` (when enabled) rejects work fast after a
    declared crash loop.
    """

    def __init__(
        self,
        *,
        key: str = "",
        max_relaunches: Optional[int] = None,
        timeout_s: Optional[float] = None,
        progress_probe: Optional[Callable[[], Any]] = None,
        breaker: Optional[CircuitBreaker] = None,
        use_breaker: bool = True,
        clock: Optional[Any] = None,
        cancel_token: Optional[CancelToken] = None,
        epoch_guard: Optional[str] = None,
    ):
        from deequ_tpu import config

        opts = config.options()
        self.key = key
        self.max_relaunches = (
            int(opts.crash_max_relaunches)
            if max_relaunches is None
            else int(max_relaunches)
        )
        self.timeout_s = timeout_s
        self.progress_probe = progress_probe
        if breaker is None and use_breaker and key:
            breaker = breaker_for(key, clock=clock)
        self.breaker = breaker
        # cooperative cancel across the process boundary: when this
        # token fires (client cancel OR a preemption), the parent sends
        # one ("cancel", reason) message down the child's control pipe
        # and keeps WAITING — the child exits cleanly through its
        # checkpoint path and ships its partial result; the runner
        # never escalates a cancel to terminate()/kill() (that is the
        # deadline path's job)
        self.cancel_token = cancel_token
        # last ("egress", record) frame streamed by any child: durable
        # egress advancement between scan checkpoints also counts as
        # forward progress for the crash-loop budget (run())
        self._last_egress_frame: Optional[Dict[str, Any]] = None
        # fleet epoch guard (CHILD_EPOCH_ENV): shipped to every child
        # this runner launches so a child of a fenced parent stops
        # persisting too (FleetSupervisor.child_guard() JSON, or None
        # when the parent is not a fleet member)
        self.epoch_guard = epoch_guard
        self._ctx = multiprocessing.get_context("spawn")

    # -- single launch ---------------------------------------------------

    def _launch_once(
        self, fn: Callable[[Any], Any], payload: Any, launches: int
    ) -> Any:
        from deequ_tpu.telemetry import get_telemetry

        tm = get_telemetry()
        parent_conn, child_conn = self._ctx.Pipe(duplex=False)
        # control pipe, parent -> child: carries at most one
        # ("cancel", reason) message (see _watch_parent_cancel)
        cancel_recv, cancel_send = self._ctx.Pipe(duplex=False)
        proc = self._ctx.Process(
            target=_child_main,
            args=(child_conn, cancel_recv, fn, payload),
            daemon=False,
        )
        platform = _parent_platform()
        if platform:
            os.environ[CHILD_PLATFORM_ENV] = platform
        # ship the ambient trace, re-anchored at the parent's CURRENT
        # open span, so child spans nest where the launch happened. The
        # env var is restored right after start() — spawn snapshots the
        # environment at launch, and a stale context must never leak
        # into a later untraced child.
        shipped_parent: Optional[int] = None
        shipped = None
        ctx = tm.current_trace()
        if ctx is not None:
            current = tm.tracer.current()
            shipped_parent = (
                current.span_id if current is not None else ctx.span_id
            )
            shipped = ctx.child(shipped_parent)
        prev_trace_env = os.environ.get(CHILD_TRACE_ENV)
        if shipped is not None:
            os.environ[CHILD_TRACE_ENV] = shipped.encode()
        else:
            os.environ.pop(CHILD_TRACE_ENV, None)
        # same snapshot-and-restore discipline for the fleet epoch
        # guard: spawn captures the environment at start(), and a stale
        # guard must never leak into a later fleet-less child
        prev_epoch_env = os.environ.get(CHILD_EPOCH_ENV)
        if self.epoch_guard:
            os.environ[CHILD_EPOCH_ENV] = self.epoch_guard
        else:
            os.environ.pop(CHILD_EPOCH_ENV, None)
        try:
            proc.start()
        finally:
            if prev_trace_env is None:
                os.environ.pop(CHILD_TRACE_ENV, None)
            else:
                os.environ[CHILD_TRACE_ENV] = prev_trace_env
            if prev_epoch_env is None:
                os.environ.pop(CHILD_EPOCH_ENV, None)
            else:
                os.environ[CHILD_EPOCH_ENV] = prev_epoch_env
        child_conn.close()  # parent's copy; the child holds the real end
        cancel_recv.close()  # ditto for the control pipe's read end
        message = None
        poll_expired = False
        timed_out = False
        cancel_sent = False
        spans: list = []
        clk = MonotonicClock()
        deadline = (
            clk.now() + self.timeout_s if self.timeout_s is not None else None
        )
        try:
            try:
                # drain ("span", record) streaming messages until the
                # final ("ok"|"err", value, summary) 3-tuple, EOF, or
                # the deadline. Spans collected here survive a crash —
                # they are replayed below even when no final message
                # ever arrives, so the trace shows where the child died.
                # With a cancel token the wait is sliced so a cancel
                # firing mid-run reaches the child promptly.
                while True:
                    if (
                        self.cancel_token is not None
                        and not cancel_sent
                        and self.cancel_token.cancelled
                    ):
                        cancel_sent = True
                        try:
                            cancel_send.send(
                                (
                                    "cancel",
                                    self.cancel_token.reason
                                    or "cancelled",
                                )
                            )
                        except Exception:  # noqa: BLE001 — child gone:
                            pass  # the result loop classifies that
                    remaining = (
                        None
                        if deadline is None
                        else max(0.0, deadline - clk.now())
                    )
                    if remaining is not None and remaining <= 0.0:
                        poll_expired = True
                        break
                    if self.cancel_token is not None:
                        wait = (
                            0.05
                            if remaining is None
                            else min(0.05, remaining)
                        )
                    else:
                        wait = remaining
                    if not parent_conn.poll(wait):
                        if self.cancel_token is not None:
                            continue  # slice over; re-check the token
                        poll_expired = True
                        break
                    msg = parent_conn.recv()
                    if (
                        isinstance(msg, tuple)
                        and len(msg) == 2
                        and msg[0] == "span"
                    ):
                        if isinstance(msg[1], dict):
                            spans.append(msg[1])
                        continue
                    if (
                        isinstance(msg, tuple)
                        and len(msg) == 2
                        and msg[0] == "egress"
                    ):
                        if isinstance(msg[1], dict):
                            self._last_egress_frame = msg[1]
                        continue
                    message = msg
                    break
            except (EOFError, OSError):
                message = None  # pipe torn by a crashing child
            # timeout means poll() genuinely expired. An EOF wakes poll()
            # while the dying child may still show is_alive() for a
            # moment — that is a CRASH to classify by exit status, and
            # must never be misread as a timeout.
            if (
                message is None
                and poll_expired
                and self.timeout_s is not None
                and proc.is_alive()
            ):
                timed_out = True
                proc.terminate()
        finally:
            try:
                cancel_send.close()
            except Exception:  # noqa: BLE001 — already torn
                pass
            proc.join(self.timeout_s)
            if proc.is_alive():  # terminate() ignored — escalate
                proc.kill()
                proc.join()
            parent_conn.close()
            exitcode = proc.exitcode
            proc.close()

        # replay streamed child spans into the parent's telemetry on
        # EVERY outcome — success, error, crash, timeout. Ids remap onto
        # the parent's counter; parentage re-roots under the span the
        # launch shipped.
        if spans:
            tm.replay_spans(spans, root_parent_id=shipped_parent)

        if timed_out:
            tm.counter("engine.child_crashes").inc()
            tm.event(
                "child_crashed",
                key=self.key,
                exitcode=exitcode,
                signal="timeout",
                launches=launches,
                spans_streamed=len(spans),
            )
            raise ProcessCrashed(
                f"child exceeded {self.timeout_s}s and was terminated",
                exitcode=exitcode,
                signal_name="timeout",
                launches=launches,
            )
        if message is None:
            description, signal_name = _classify_exit(exitcode)
            tm.counter("engine.child_crashes").inc()
            tm.event(
                "child_crashed",
                key=self.key,
                exitcode=exitcode,
                signal=signal_name,
                launches=launches,
                spans_streamed=len(spans),
            )
            raise ProcessCrashed(
                description,
                exitcode=exitcode,
                signal_name=signal_name,
                launches=launches,
            )

        status, value, child_summary = message
        _merge_child_telemetry(tm, child_summary)
        if status == "ok":
            return value
        raise value

    # -- relaunch loop ---------------------------------------------------

    def run(self, fn: Callable[[Any], Any], payload: Any = None) -> Any:
        """Execute ``fn(payload)`` in a child, relaunching across
        crashes until it completes, errors in-band, or the relaunch
        budget for a single stuck position is exhausted."""
        from deequ_tpu.telemetry import get_telemetry

        tm = get_telemetry()
        if self.breaker is not None:
            self.breaker.admit(self.key)
        last_progress = (
            self.progress_probe() if self.progress_probe is not None else None
        )
        last_egress = self._last_egress_frame
        crashes_here = 0  # crashes since the last observed progress
        launches = 0
        last_crash: Optional[ProcessCrashed] = None
        while True:
            launches += 1
            try:
                result = self._launch_once(fn, payload, launches)
            except ProcessCrashed as crash:
                last_crash = crash
                crashes_here += 1
                if self.progress_probe is not None:
                    progress = self.progress_probe()
                    if progress != last_progress:
                        last_progress = progress
                        crashes_here = 1  # this crash, at the new position
                # a durable egress flush streamed by the child is
                # progress too (span segments advance between scan
                # checkpoints) — a sink run inching forward is never a
                # crash loop
                if self._last_egress_frame != last_egress:
                    last_egress = self._last_egress_frame
                    crashes_here = 1
                if crashes_here >= self.max_relaunches:
                    if self.breaker is not None:
                        self.breaker.record_crash_loop(self.key)
                    tm.counter("engine.crash_loops").inc()
                    raise CrashLoopError(
                        f"{self.key or 'run'} crashed {crashes_here} "
                        f"launches in a row without checkpoint progress "
                        f"(last: {crash})",
                        launches=launches,
                        last_exitcode=crash.exitcode,
                        last_signal=crash.signal_name,
                    ) from crash
                tm.counter("engine.child_relaunches").inc()
                tm.event(
                    "child_relaunched",
                    key=self.key,
                    launches=launches,
                    crashes_at_position=crashes_here,
                )
                continue
            if self.breaker is not None:
                self.breaker.record_success(self.key)
            if launches > 1:
                tm.counter("engine.crash_resumes").inc()
                tm.event(
                    "crash_resumed",
                    key=self.key,
                    launches=launches,
                    last_signal=(
                        last_crash.signal_name if last_crash else None
                    ),
                )
            return result


def _parent_platform() -> Optional[str]:
    """The platform string children must pin, resolved from the
    parent's live jax config (falls back to the env var)."""
    try:
        import jax

        value = getattr(jax.config, "jax_platforms", None)
        if value:
            return str(value)
    except Exception:  # noqa: BLE001
        pass
    return os.environ.get("JAX_PLATFORMS") or None


def _merge_child_telemetry(tm: Any, summary: Optional[Dict[str, Any]]) -> None:
    """Fold a child's run summary into the parent's telemetry: counter
    deltas add up, events replay (so obs reports see one stream)."""
    if not summary:
        return
    for name, delta in (summary.get("counters") or {}).items():
        try:
            tm.counter(name).inc(int(delta))
        except Exception:  # noqa: BLE001 — malformed child counter
            continue
    for record in summary.get("events") or []:
        if not isinstance(record, dict) or "event" not in record:
            continue
        fields = {k: v for k, v in record.items() if k != "event"}
        try:
            tm.event(record["event"], **fields)
        except TypeError:  # field name collides with the name parameter
            continue


def run_isolated(
    fn: Callable[[Any], Any],
    payload: Any = None,
    **kwargs: Any,
) -> Any:
    """One-shot convenience: ``IsolatedRunner(**kwargs).run(fn, payload)``."""
    return IsolatedRunner(**kwargs).run(fn, payload)
