"""Per-analyzer exact-value tests on fixture data (reference test shape:
``analyzers/AnalyzerTests.scala`` — SURVEY.md §4)."""

import math

import pytest

from deequ_tpu.analyzers import (
    Completeness,
    Compliance,
    Correlation,
    Maximum,
    MaxLength,
    Mean,
    Minimum,
    MinLength,
    PatternMatch,
    RatioOfSums,
    Size,
    StandardDeviation,
    Sum,
)
from deequ_tpu.analyzers.base import (
    EmptyStateException,
    NoSuchColumnException,
    WrongColumnTypeException,
)
from deequ_tpu.analyzers.datatype import DataType

from fixtures import (
    df_full,
    df_missing,
    df_numeric,
    df_numeric_with_nulls,
    df_strings,
)


def value(metric):
    assert metric.value.is_success, f"metric failed: {metric.value}"
    return metric.value.get()


class TestSize:
    def test_full(self):
        assert value(Size().calculate(df_full())) == 4.0

    def test_missing(self):
        assert value(Size().calculate(df_missing())) == 12.0

    def test_with_filter(self):
        metric = Size(where="att1 IS NOT NULL").calculate(df_missing())
        assert value(metric) == 10.0


class TestCompleteness:
    def test_complete_column(self):
        assert value(Completeness("item").calculate(df_missing())) == 1.0

    def test_att1(self):
        assert value(Completeness("att1").calculate(df_missing())) == 10 / 12

    def test_att2(self):
        assert value(Completeness("att2").calculate(df_missing())) == 6 / 12

    def test_missing_column_fails(self):
        metric = Completeness("nope").calculate(df_missing())
        assert metric.value.is_failure
        assert isinstance(metric.value.exception, NoSuchColumnException)

    def test_with_filter(self):
        # among rows where att1 = 'a' (7 rows), att2 is non-null in 6
        metric = Completeness("att2", where="att1 = 'a'").calculate(
            df_missing()
        )
        assert value(metric) == pytest.approx(6 / 7)


class TestNumeric:
    def test_mean(self):
        assert value(Mean("att1").calculate(df_numeric())) == 3.5

    def test_mean_with_filter(self):
        metric = Mean("att1", where="att2 = 0").calculate(df_numeric())
        assert value(metric) == 2.0

    def test_sum(self):
        assert value(Sum("att1").calculate(df_numeric())) == 21.0

    def test_min_max(self):
        assert value(Minimum("att1").calculate(df_numeric())) == 1.0
        assert value(Maximum("att1").calculate(df_numeric())) == 6.0

    def test_stddev(self):
        # population stddev of 1..6 = sqrt(17.5/6)
        metric = StandardDeviation("att1").calculate(df_numeric())
        assert value(metric) == pytest.approx(math.sqrt(17.5 / 6))

    def test_nulls_ignored(self):
        ds = df_numeric_with_nulls()
        assert value(Mean("att1").calculate(ds)) == 3.0  # (1+3+5)/3
        assert value(Sum("att2").calculate(ds)) == 16.0
        assert value(Minimum("att1").calculate(ds)) == 1.0

    def test_wrong_type_fails(self):
        metric = Mean("att1").calculate(df_full())  # string column
        assert metric.value.is_failure
        assert isinstance(metric.value.exception, WrongColumnTypeException)

    def test_empty_fails(self):
        from deequ_tpu.data import Dataset
        import pyarrow as pa

        empty = Dataset.from_arrow(
            pa.table({"att1": pa.array([], pa.float64())})
        )
        metric = Mean("att1").calculate(empty)
        assert metric.value.is_failure
        assert isinstance(metric.value.exception, EmptyStateException)

    def test_correlation(self):
        import numpy as np

        metric = Correlation("att1", "att2").calculate(df_numeric())
        expected = np.corrcoef([1, 2, 3, 4, 5, 6], [0, 0, 0, 5, 6, 7])[0, 1]
        assert value(metric) == pytest.approx(float(expected))

    def test_ratio_of_sums(self):
        metric = RatioOfSums("att1", "att2").calculate(df_numeric())
        assert value(metric) == pytest.approx(21.0 / 18.0)

    def test_correlation_extreme_magnitude_denominator(self):
        """Both second moments > ~1e154: the product form overflows to
        inf; the fallback sqrt(x)*sqrt(y) must recover the finite
        answer instead of silently returning 0.0 (r4 advisory)."""
        import numpy as np

        from deequ_tpu.analyzers.states import CorrelationState

        mk = np.float64(5e154)  # mk * mk -> inf in f64
        state = CorrelationState(
            np.float64(4.0),
            np.float64(2.5e77),
            np.float64(2.5e77),
            np.float64(-5e154),  # perfectly anticorrelated
            mk,
            mk,
        )
        metric = Correlation("a", "b").compute_metric_from_state(state)
        assert metric.value.is_success, metric.value
        assert metric.value.get() == pytest.approx(-1.0)
        # symmetric regime: both m_k nonzero but the product
        # UNDERFLOWS to 0 — same fallback must fire (review finding)
        tiny = np.float64(1e-200)
        state = CorrelationState(
            np.float64(4.0),
            np.float64(1e-100),
            np.float64(1e-100),
            tiny,  # perfectly correlated
            tiny,
            tiny,
        )
        metric = Correlation("a", "b").compute_metric_from_state(state)
        assert metric.value.is_success, metric.value
        assert metric.value.get() == pytest.approx(1.0)
        # SUBNORMAL product (nonzero but < tiny): the product form
        # carries too few bits and can report |r| > 1 — the fallback
        # must fire there too (review finding)
        sub = np.float64(1e-160)
        state = CorrelationState(
            np.float64(4.0),
            np.float64(1e-80),
            np.float64(1e-80),
            sub,
            sub,
            sub,
        )
        metric = Correlation("a", "b").compute_metric_from_state(state)
        assert metric.value.is_success, metric.value
        assert metric.value.get() == pytest.approx(1.0)
        assert metric.value.get() <= 1.0


class TestCompliance:
    def test_predicate(self):
        metric = Compliance("att1 big", "att1 >= 4").calculate(df_numeric())
        assert value(metric) == 0.5

    def test_string_equality(self):
        metric = Compliance("att1 is a", "att1 = 'a'").calculate(df_full())
        assert value(metric) == 0.5

    def test_in_list(self):
        metric = Compliance("vals", "att2 IN ('c', 'd')").calculate(df_full())
        assert value(metric) == 1.0

    def test_null_predicate_rows_not_compliant(self):
        metric = Compliance("att1 present", "att1 IS NOT NULL").calculate(
            df_missing()
        )
        assert value(metric) == 10 / 12


class TestStrings:
    def test_min_max_length(self):
        ds = df_strings()
        assert value(MinLength("name").calculate(ds)) == 3.0
        assert value(MaxLength("name").calculate(ds)) == 6.0

    def test_pattern_match(self):
        metric = PatternMatch(
            "email", r"^[^@]+@[^@]+\.[a-z]+$"
        ).calculate(df_strings())
        assert value(metric) == 0.75

    def test_datatype(self):
        metric = DataType("typed").calculate(df_strings())
        dist = value(metric)
        assert dist.values["Integral"].absolute == 1
        assert dist.values["Fractional"].absolute == 1
        assert dist.values["Boolean"].absolute == 1
        assert dist.values["String"].absolute == 1
