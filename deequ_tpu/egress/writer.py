"""Host-side quarantine/clean parquet writer for row-level egress.

This module is HOST-ONLY by design (enforced by the ``wire-discipline``
staticcheck rule): the device half of egress lives in
``deequ_tpu/egress/plan.py`` — per-row constraint masks evaluated
inside the fused scan and bit-packed per batch. What arrives here is
the already-fetched packed epilogue output (numpy uint8 bit planes +
a valid-row count), and everything this module does is Arrow/parquet
plumbing:

- a **sequential span reader** pulls row content for each consumed span
  from the dataset's ``record_batches`` iterator — zero-copy slices for
  in-memory tables, a host-side sequential re-read for parquet sources
  (the device wire is never touched, ``engine.data_passes`` counts only
  metric scans);
- per span (one fold of the scan — a batch or an OOM sub-slice), rows
  split into the **clean** and **quarantine** outputs, each written as
  its own parquet row group immediately — the writer's host footprint
  is bounded by one span, never the table (flush-per-batch, also a
  staticcheck rule);
- **quarantined-batch degradation** (engine/resilience.py) folds into
  the SAME artifact: a batch the scan skipped lands whole in the
  quarantine output with its ``BatchFailure`` provenance
  (``__error_class__``/``__error_message__``/``__retry_attempts__``)
  and NULL outcome columns (the scan never evaluated them);
- the **wire-codec discipline** applies symmetrically on the way out:
  provenance integers (``__row_index__``, ``__batch_seq__``) are
  narrowed via ``engine.wire.narrowest_int_dtype`` — decided ONCE at
  geometry-bind time from the row count, never per batch.

Filtered-row semantics mirror ``verification/rowlevel.py`` exactly
(the differential oracle): under ``"true"`` a where-excluded row
passes; under ``"null"`` its outcome column is SQL NULL and only
``~pass & ~excluded`` quarantines.
"""

from __future__ import annotations

import json
import os
import shutil
import struct
import zlib
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

from deequ_tpu.engine.wire import narrowest_int_dtype
from deequ_tpu.io.storage import durable_replace, storage_for
from deequ_tpu.telemetry import get_telemetry

#: ``__failed_constraints__`` marker for rows the scan never evaluated
#: because their whole batch was quarantined by the resilience layer
BATCH_QUARANTINED = "__batch_quarantined__"

_PROV_ROW = "__row_index__"
_PROV_SEQ = "__batch_seq__"
_PROV_FAILED = "__failed_constraints__"
_PROV_ERR_CLASS = "__error_class__"
_PROV_ERR_MSG = "__error_message__"
_PROV_ATTEMPTS = "__retry_attempts__"
_PROV_TENANT = "__tenant__"
_PROV_RUN = "__run_id__"

#: segment-internal routing column: 0 = clean, 1 = quarantine. Exists
#: only inside ``spans/seg-*.parquet`` — compaction strips it before a
#: row group reaches the public split.
_SPLIT_COL = "__egress_split__"

#: columns only the quarantine split carries (null on clean rows while
#: they ride a span segment; dropped again at compaction)
_Q_ONLY = (
    _PROV_FAILED,
    _PROV_ERR_CLASS,
    _PROV_ERR_MSG,
    _PROV_ATTEMPTS,
    _PROV_TENANT,
    _PROV_RUN,
)

_Q_ONLY_TYPES = {
    _PROV_FAILED: pa.string(),
    _PROV_ERR_CLASS: pa.string(),
    _PROV_ERR_MSG: pa.string(),
    _PROV_ATTEMPTS: pa.int32(),
    _PROV_TENANT: pa.string(),
    _PROV_RUN: pa.string(),
}

#: CRC-stamped segment footer: a span segment is an ordinary parquet
#: payload followed by ``<magic, payload_len, crc32>`` — readers strip
#: and verify the footer, so a torn tail (crash mid-publish) is
#: DETECTED, never half-read as data
_SEG_MAGIC = b"DQTPUSG1"
_SEG_FOOTER = struct.Struct("<8sqI")


@dataclass
class RowLevelSink:
    """User-facing egress request: stream row-level pass/fail outcomes
    to a partitioned clean/quarantine parquet split under ``out_dir``.

    Pass one to ``VerificationRunBuilder.with_row_level_sink`` (or
    ``row_level_sink=`` on ``do_verification_run`` /
    ``service.RunRequest``); after the run, ``sink.report`` (also
    ``result.row_level_egress``) describes what was written. See
    docs/EGRESS.md."""

    out_dir: str
    #: "true" (where-excluded rows pass, the reference default) or
    #: "null" (nullable outcome columns, only ~pass & ~excluded fails)
    filtered_row_outcome: str = "true"
    #: row columns to carry into the split (default: every column)
    columns: Optional[Sequence[str]] = None
    tenant: str = ""
    run_id: str = ""
    #: set by the run: the EgressReport for the last finalize
    report: Optional["EgressReport"] = None

    def __post_init__(self):
        if self.filtered_row_outcome not in ("true", "null"):
            raise ValueError(
                "filtered_row_outcome must be 'true' or 'null', got "
                f"{self.filtered_row_outcome!r}"
            )


@dataclass
class EgressReport:
    """What one run's egress produced (also serialized into the
    manifest)."""

    status: str  # complete | interrupted | aborted | no_row_level_constraints
    rows_total: int = 0
    rows_clean: int = 0
    rows_quarantined: int = 0
    bytes_raw: int = 0
    bytes_encoded: int = 0
    #: constraint name -> "scan" (rode the fused scan) or "deferred"
    #: (finalize phase: uniqueness / untraceable assertions)
    constraints: Dict[str, str] = field(default_factory=dict)
    #: constraint name -> reason it has no outcome column
    unsupported: Dict[str, str] = field(default_factory=dict)
    clean_dir: str = ""
    quarantine_dir: str = ""
    manifest_path: str = ""

    @property
    def rows_written(self) -> int:
        return self.rows_clean + self.rows_quarantined


class _SpanReader:
    """Sequential row-content reader: ``take(n)`` returns the next n
    source rows as an Arrow table. Backed by the dataset's
    ``record_batches`` iterator, so the buffered remainder is bounded
    by one read batch plus one span — never the table."""

    def __init__(self, data, columns: Sequence[str], batch_rows: int = 1 << 16):
        self._iter = iter(data.record_batches(list(columns), batch_rows))
        self._parts: List[pa.Table] = []
        self._buffered = 0
        self.schema: Optional[pa.Schema] = None

    def take(self, n: int) -> pa.Table:
        while self._buffered < n:
            nxt = next(self._iter, None)
            if nxt is None:
                break
            part = pa.Table.from_batches([nxt])
            if self.schema is None:
                self.schema = part.schema
            self._parts.append(part)
            self._buffered += part.num_rows
        if self._buffered < n:
            raise RuntimeError(
                f"egress span reader exhausted: need {n} more rows, "
                f"source has {self._buffered} — span accounting is "
                "misaligned with the scan"
            )
        tbl = (
            pa.concat_tables(self._parts)
            if len(self._parts) > 1
            else self._parts[0]
        )
        out = tbl.slice(0, n)
        rest = tbl.slice(n)
        self._parts = [rest] if rest.num_rows else []
        self._buffered = rest.num_rows
        return out.combine_chunks()


@dataclass
class _FailureSpan:
    """A quarantined-batch span in SOURCE row coordinates (failures
    always cover the TAIL of their scan unit — the rows the partial
    sub-dispatch never folded)."""

    start: int
    length: int
    error_class: str
    message: str
    attempts: int


def _combine(
    passes: np.ndarray, excl: Optional[np.ndarray], mode: str
) -> Tuple[np.ndarray, Optional[np.ndarray], np.ndarray]:
    """(outcome values, null mask or None, per-row fail) for one
    constraint — the exact ``rowlevel.row_level_results`` semantics."""
    if excl is None:
        return passes, None, ~passes
    if mode == "true":
        outcome = passes | excl
        return outcome, None, ~outcome
    # "null": excluded rows are SQL NULL and never quarantine
    return passes, excl, ~passes & ~excl


class QuarantineWriter:
    """Streams one run's row-level outcomes to a clean/quarantine
    parquet split. Two operating modes:

    - **direct** (every outcome column rides the scan): each fold's
      span is written as soon as it is consumed — rows fetched, split,
      and flushed per batch;
    - **spool** (deferred constraints present — uniqueness or an
      untraceable assertion): the scan phase spools only the packed
      bit planes to disk (flushed per batch, ~planes/8 bytes per row),
      and ``finalize`` replays them merged with the finalize-phase
      outcomes. The deferred families need a second look at the data
      by nature (uniqueness is global), so the run honestly reports
      ``engine.data_passes == 2``.
    """

    def __init__(
        self,
        sink: RowLevelSink,
        data,
        scan_names: Sequence[str],
        excl_of: Sequence[Optional[int]],
        deferred_names: Sequence[str],
        plane_shape: Tuple[int, int],
        row_columns: Sequence[str],
    ):
        self.sink = sink
        self._data = data
        self.scan_names = list(scan_names)
        self.excl_of = list(excl_of)
        self.deferred_names = list(deferred_names)
        self._plane_shape = tuple(plane_shape)
        self._row_columns = list(row_columns)
        self.num_rows = int(data.num_rows)
        self.cursor = 0
        self.rows_clean = 0
        self.rows_quarantined = 0
        self.bytes_raw = 0
        self.bytes_encoded = 0
        self._reader: Optional[_SpanReader] = None
        self._writers: Dict[str, pq.ParquetWriter] = {}
        self._paths: Dict[str, str] = {}
        self._schemas: Dict[str, pa.Schema] = {}
        self._row_schema: Optional[pa.Schema] = None
        # scan-unit geometry (set by bind_geometry once the engine has
        # planned the scan): unit_rows is the quarantine granularity —
        # a CHUNK on the resident path, a batch on the streaming path
        self._unit_rows: Optional[int] = None
        self._batch_size: Optional[int] = None
        self._idx_dtype = narrowest_int_dtype(0, max(self.num_rows - 1, 0))
        self._seq_dtype = np.dtype(np.int64)
        self._probe = None  # live ScanDegradation supplier (direct mode)
        self._pending: List[_FailureSpan] = []
        self._seen_failure_idx: set = set()
        self._last_record: Any = None
        self.spool_mode = bool(self.deferred_names)
        self._spool = None
        self._spool_path = os.path.join(sink.out_dir, "_scan_bits.spool")
        os.makedirs(sink.out_dir, exist_ok=True)
        # span-segment state (docs/EGRESS.md "Durable egress"): every
        # span lands in an OPEN streamed segment under spans/, rotated
        # to a CRC-stamped seg-{seq}.parquet at each durable flush and
        # compacted into the public split at finish. The spool is
        # opened LAZILY in append mode — eager "wb" here would truncate
        # the very bytes a resume is about to trust.
        self._seg_dir = os.path.join(sink.out_dir, "spans")
        self._seg_tmp = os.path.join(self._seg_dir, ".seg-open.tmp")
        self._seg_seq = 0
        self._seg_writer: Optional[pq.ParquetWriter] = None
        self._seg_schema: Optional[pa.Schema] = None
        self._open_span_rows = 0
        self._span_row_bound: Optional[int] = None
        self._rows_replayed = 0

    # -- wiring ---------------------------------------------------------

    def bind_geometry(self, unit_rows: int, batch_size: int) -> None:
        """Called once the engine has planned the scan: the unit the
        resilience layer quarantines at (chunk rows on the resident
        path, batch rows streaming) and the batch size for
        ``__batch_seq__``. Narrowing for ``__batch_seq__`` is decided
        HERE, once per run — never per batch (wire discipline)."""
        self._unit_rows = int(unit_rows)
        self._batch_size = int(batch_size)
        n_units = max(
            1, -(-max(self.num_rows, 1) // max(self._batch_size, 1))
        )
        self._seq_dtype = narrowest_int_dtype(0, n_units - 1)
        # bound the OPEN span segment to one checkpoint interval's
        # worth of rows — past that it rotates to disk (with an
        # egress_span_overflow event) instead of growing silently
        from deequ_tpu import config

        every = int(config.options().checkpoint_every_batches)
        self._span_row_bound = (
            every * self._batch_size if every > 0 else None
        )

    def set_degradation_probe(self, probe) -> None:
        """Direct mode: a callable returning the ACTIVE scan's live
        ``ScanDegradation`` record, consulted before each span so
        quarantined units interleave into the output in source order."""
        self._probe = probe

    # -- scan-phase consumption (called from the op's host_fold) --------

    def consume(self, bits: np.ndarray, valid: int) -> None:
        """One fold of the scan: ``bits`` is the packed (planes, B/8)
        uint8 output, ``valid`` the number of real rows it covers (the
        batch's True-prefix). Either written through immediately
        (direct) or spooled (deferred constraints present); both paths
        flush per call — the host never accumulates row content."""
        bits = np.ascontiguousarray(bits, dtype=np.uint8)
        valid = int(valid)
        if bits.shape != self._plane_shape:
            raise RuntimeError(
                f"egress fold shape {bits.shape} != planned "
                f"{self._plane_shape}"
            )
        if self.spool_mode:
            spool = self._ensure_spool()
            spool.write(struct.pack("<q", valid))
            spool.write(bits.tobytes())
            spool.flush()
            return
        if self._probe is not None:
            self._refresh_failures(self._probe())
        self._emit(bits, valid, deferred=None)

    # -- failure interleaving -------------------------------------------

    def _refresh_failures(self, record) -> None:
        if record is None:
            return
        self._last_record = record
        unit = self._unit_rows
        if unit is None:
            raise RuntimeError(
                "egress writer has no scan geometry — bind_geometry "
                "was never called"
            )
        for f in getattr(record, "failures", ()):
            idx = int(f.batch_index)
            if idx in self._seen_failure_idx:
                continue
            self._seen_failure_idx.add(idx)
            unit_rows = max(0, min(self.num_rows - idx * unit, unit))
            length = min(int(f.rows), unit_rows)
            # partial quarantines cover the TAIL of the unit — the
            # prefix was folded by the sub-dispatch before it gave up
            start = idx * unit + (unit_rows - length)
            self._pending.append(
                _FailureSpan(
                    start=start,
                    length=length,
                    error_class=str(f.error_class),
                    message=str(f.message),
                    attempts=int(f.attempts),
                )
            )
        self._pending.sort(key=lambda s: s.start)

    def _drain_failures(self) -> None:
        while self._pending and self._pending[0].start <= self.cursor:
            span = self._pending.pop(0)
            if span.start < self.cursor:
                raise RuntimeError(
                    f"egress alignment: quarantined span at row "
                    f"{span.start} overlaps rows already written "
                    f"(cursor {self.cursor})"
                )
            self._emit_failure(span)

    # -- span emission ---------------------------------------------------

    def _ensure_reader(self) -> _SpanReader:
        if self._reader is None:
            self._reader = _SpanReader(self._data, self._row_columns)
        return self._reader

    def _emit(
        self,
        bits: np.ndarray,
        valid: int,
        deferred: Optional[Dict[str, Tuple[np.ndarray, Optional[np.ndarray]]]],
    ) -> None:
        self._drain_failures()
        if valid <= 0:
            return
        start = self.cursor
        planes = np.unpackbits(bits, axis=1, bitorder="little")[
            :, :valid
        ].astype(bool)
        n_scan = len(self.scan_names)
        outcome_cols: List[Tuple[str, np.ndarray, Optional[np.ndarray]]] = []
        fails: List[np.ndarray] = []
        for i, name in enumerate(self.scan_names):
            e = self.excl_of[i]
            excl = planes[n_scan + e] if e is not None else None
            outcome, null_mask, fail = _combine(
                planes[i], excl, self.sink.filtered_row_outcome
            )
            outcome_cols.append((name, outcome, null_mask))
            fails.append(fail)
        for name in self.deferred_names:
            full = (deferred or {}).get(name)
            if full is None:
                continue  # oracle degraded this constraint at finalize
            full_out, full_excl = full
            p = np.asarray(full_out[start : start + valid], dtype=bool)
            excl = (
                np.asarray(full_excl[start : start + valid], dtype=bool)
                if full_excl is not None
                else None
            )
            outcome, null_mask, fail = _combine(
                p, excl, self.sink.filtered_row_outcome
            )
            outcome_cols.append((name, outcome, null_mask))
            fails.append(fail)
        fail_any = (
            np.logical_or.reduce(fails)
            if fails
            else np.zeros(valid, dtype=bool)
        )
        rows = self._ensure_reader().take(valid)
        row_idx = np.arange(start, start + valid)
        clean_sel = ~fail_any
        self._write_split("clean", rows, outcome_cols, clean_sel, row_idx)
        self._write_split(
            "quarantine",
            rows,
            outcome_cols,
            fail_any,
            row_idx,
            failed_labels=self._failed_labels(fails, fail_any, outcome_cols),
        )
        n_clean = int(clean_sel.sum())
        self.rows_clean += n_clean
        self.rows_quarantined += valid - n_clean
        self.cursor += valid
        self._note_open_span()

    def _failed_labels(
        self,
        fails: List[np.ndarray],
        fail_any: np.ndarray,
        outcome_cols: List[Tuple[str, np.ndarray, Optional[np.ndarray]]],
    ) -> List[str]:
        """One ';'-joined failing-constraint set per quarantined row,
        built per UNIQUE failure pattern (never per-row Python over
        every row)."""
        nq = int(fail_any.sum())
        if nq == 0 or not fails:
            return []
        names = [name for name, _o, _m in outcome_cols]
        sub = np.stack(fails, axis=0)[:, fail_any]
        uniq, inv = np.unique(sub, axis=1, return_inverse=True)
        labels = [
            ";".join(names[i] for i in np.nonzero(uniq[:, k])[0])
            for k in range(uniq.shape[1])
        ]
        return [labels[j] for j in np.asarray(inv).ravel()]

    def _emit_failure(self, span: _FailureSpan) -> None:
        """A quarantined scan unit lands WHOLE in the quarantine output
        with its BatchFailure provenance; outcome columns are NULL (the
        scan never evaluated them for these rows)."""
        rows = self._ensure_reader().take(span.length)
        row_idx = np.arange(span.start, span.start + span.length)
        null_outcomes = [
            (name, None, None)
            for name in self.scan_names + self.deferred_names
        ]
        self._write_split(
            "quarantine",
            rows,
            null_outcomes,
            np.ones(span.length, dtype=bool),
            row_idx,
            failed_labels=[BATCH_QUARANTINED] * span.length,
            error=(span.error_class, span.message, span.attempts),
        )
        self.rows_quarantined += span.length
        self.cursor += span.length
        self._note_open_span()

    def _write_split(
        self,
        which: str,
        rows: pa.Table,
        outcome_cols: Sequence[Tuple[str, Optional[np.ndarray], Optional[np.ndarray]]],
        sel: np.ndarray,
        row_idx: np.ndarray,
        failed_labels: Optional[List[str]] = None,
        error: Optional[Tuple[str, str, int]] = None,
    ) -> None:
        n = int(sel.sum())
        if n == 0:
            return
        if self._row_schema is None:
            self._row_schema = rows.schema
        sel_pa = pa.array(sel)
        arrays = list(rows.filter(sel_pa).columns)
        names = list(rows.schema.names)
        for name, outcome, null_mask in outcome_cols:
            if outcome is None:  # batch-quarantined: never evaluated
                arrays.append(pa.nulls(n, pa.bool_()))
            elif null_mask is None:
                arrays.append(pa.array(outcome[sel]))
            else:
                arrays.append(pa.array(outcome[sel], mask=null_mask[sel]))
            names.append(name)
        idx = row_idx[sel]
        arrays.append(pa.array(idx.astype(self._idx_dtype)))
        names.append(_PROV_ROW)
        seq = idx // max(int(self._batch_size or 1), 1)
        arrays.append(pa.array(seq.astype(self._seq_dtype)))
        names.append(_PROV_SEQ)
        # wire diet, egress direction: provenance ints narrowed once
        # per run; raw prices the same columns at canonical int64
        raw_extra = (16 - self._idx_dtype.itemsize - self._seq_dtype.itemsize) * n
        if which == "quarantine":
            arrays.append(
                pa.array(failed_labels or [""] * n, pa.string())
            )
            names.append(_PROV_FAILED)
            err_class, err_msg, attempts = error or (None, None, 0)
            arrays.append(pa.array([err_class] * n, pa.string()))
            names.append(_PROV_ERR_CLASS)
            arrays.append(pa.array([err_msg] * n, pa.string()))
            names.append(_PROV_ERR_MSG)
            arrays.append(
                pa.array(np.full(n, int(attempts), dtype=np.int32))
            )
            names.append(_PROV_ATTEMPTS)
            arrays.append(
                pa.array([self.sink.tenant] * n, pa.string())
            )
            names.append(_PROV_TENANT)
            arrays.append(pa.array([self.sink.run_id] * n, pa.string()))
            names.append(_PROV_RUN)
        table = pa.Table.from_arrays(arrays, names=names)
        # one row group per span-split, into the OPEN segment — the
        # per-batch flush the wire-discipline rule requires; the public
        # split files materialize at compaction (finish)
        self._segment_append(which, table)
        nbytes = table.nbytes
        self.bytes_encoded += nbytes
        self.bytes_raw += nbytes + raw_extra
        tm = get_telemetry()
        tm.counter("engine.egress_bytes_encoded").inc(nbytes)
        tm.counter("engine.egress_bytes_raw").inc(nbytes + raw_extra)

    def _ensure_writer(self, which: str, schema: pa.Schema) -> pq.ParquetWriter:
        writer = self._writers.get(which)
        if writer is None:
            split_dir = os.path.join(self.sink.out_dir, which)
            os.makedirs(split_dir, exist_ok=True)
            path = os.path.join(split_dir, "part-00000.parquet")
            writer = pq.ParquetWriter(path, schema)
            self._writers[which] = writer
            self._paths[which] = path
            self._schemas[which] = schema
        return writer

    # -- durable span segments (docs/EGRESS.md "Durable egress") --------

    def _ensure_spool(self):
        """Open the bit-plane spool lazily, in APPEND mode — after a
        resume the file already holds every fsynced record up to the
        cursor's ``plane_spool_offset`` and must not be truncated."""
        if self._spool is None:
            self._spool = open(self._spool_path, "ab")
        return self._spool

    def _segment_schema(self, first: pa.Schema) -> pa.Schema:
        """The segment superset schema: row columns + outcome columns +
        provenance, the quarantine-only columns (null on clean rows),
        and the routing tag. Identical whichever split seeds it, so
        every segment of a run — and of its resumed reincarnations —
        shares one schema."""
        fields = list(first)
        names = set(first.names)
        for name in _Q_ONLY:
            if name not in names:
                fields.append(pa.field(name, _Q_ONLY_TYPES[name]))
        fields.append(pa.field(_SPLIT_COL, pa.int8()))
        return pa.schema(fields)

    def _segment_append(self, which: str, table: pa.Table) -> None:
        if self._seg_writer is None:
            if self._seg_schema is None:
                self._seg_schema = self._segment_schema(table.schema)
            os.makedirs(self._seg_dir, exist_ok=True)
            self._seg_writer = pq.ParquetWriter(
                self._seg_tmp, self._seg_schema
            )
        n = table.num_rows
        split_val = 1 if which == "quarantine" else 0
        arrays = []
        for fld in self._seg_schema:
            if fld.name == _SPLIT_COL:
                arrays.append(
                    pa.array(np.full(n, split_val, dtype=np.int8))
                )
            elif fld.name in table.schema.names:
                arrays.append(table.column(fld.name))
            else:
                arrays.append(pa.nulls(n, fld.type))
        self._seg_writer.write_table(
            pa.Table.from_arrays(arrays, schema=self._seg_schema)
        )
        self._open_span_rows += n

    def _note_open_span(self) -> None:
        """Bound the open (not yet durably flushed) segment: past one
        checkpoint interval's worth of rows it is rotated to disk with
        an ``egress_span_overflow`` event instead of growing silently.
        A healthy checkpointed run never trips this — the checkpoint
        flush rotates first; rows in an overflow segment past the last
        cursor are simply truncated-and-rescanned on resume."""
        bound = self._span_row_bound
        if bound is None or self._open_span_rows <= bound:
            return
        get_telemetry().event(
            "egress_span_overflow",
            open_rows=self._open_span_rows,
            bound=bound,
            span_seq=self._seg_seq,
        )
        self._finalize_open_segment()
        # an overflow rotation is durable progress between checkpoints
        # — stream it to the isolation parent like a checkpoint flush
        from deequ_tpu.engine.subproc import notify_egress_progress

        notify_egress_progress(
            {
                "span_seq": self._seg_seq - 1,
                "rows_clean": self.rows_clean,
                "rows_quarantined": self.rows_quarantined,
                "spool_offset": 0,
            }
        )

    def _finalize_open_segment(self) -> bool:
        """Close the open segment, stamp its CRC footer, and DURABLY
        publish it as ``spans/seg-{seq:010d}.parquet`` (fsync + atomic
        rename + directory fsync). Returns False when nothing was
        written since the last rotation. This is the durable-flush
        evidence that must lexically precede every
        :class:`EgressCursor` construction (the ``egress-durability``
        staticcheck rule)."""
        if self._seg_writer is None:
            return False
        self._seg_writer.close()
        self._seg_writer = None
        with open(self._seg_tmp, "rb") as fh:
            payload = fh.read()
        footer = _SEG_FOOTER.pack(
            _SEG_MAGIC, len(payload), zlib.crc32(payload) & 0xFFFFFFFF
        )
        with open(self._seg_tmp, "ab") as fh:
            fh.write(footer)
        final = os.path.join(
            self._seg_dir, f"seg-{self._seg_seq:010d}.parquet"
        )
        durable_replace(self._seg_tmp, final)
        self._seg_seq += 1
        self._open_span_rows = 0
        get_telemetry().counter("engine.egress_spans_flushed").inc()
        return True

    def flush_durable(self):
        """Make every row consumed so far durable and return the
        :class:`~deequ_tpu.io.state_provider.EgressCursor` naming the
        durable state. Called by the engine's checkpoint writer AFTER
        the pending host folds are drained and BEFORE the ScanCursor is
        saved — the write-ahead ordering (flush THEN cursor) that makes
        resume replay-nothing/drop-nothing."""
        from deequ_tpu.io.state_provider import EgressCursor

        spool_offset = 0
        if self.spool_mode and self._spool is not None:
            self._spool.flush()
            os.fsync(self._spool.fileno())
            spool_offset = self._spool.tell()
        self._finalize_open_segment()
        cursor = EgressCursor(
            last_durably_flushed_span_seq=self._seg_seq - 1,
            rows_emitted_clean=self.rows_clean,
            rows_emitted_quarantined=self.rows_quarantined,
            plane_spool_offset=spool_offset,
            bytes_raw=self.bytes_raw,
            bytes_encoded=self.bytes_encoded,
        )
        # a spawned child streams the durable cursor to its parent so
        # egress advancement between checkpoints resets the crash-loop
        # budget (engine/subproc.py progress frames)
        from deequ_tpu.engine.subproc import notify_egress_progress

        notify_egress_progress(
            {
                "span_seq": cursor.last_durably_flushed_span_seq,
                "rows_clean": self.rows_clean,
                "rows_quarantined": self.rows_quarantined,
                "spool_offset": spool_offset,
            }
        )
        return cursor

    def align_resume(self, payload):
        """Reconcile the writer with a (possibly absent) scan
        checkpoint BEFORE the scan restarts. With a trustworthy egress
        cursor in the checkpoint the durable state is restored — torn
        tail truncated past the cursor's span seq, span reader
        fast-forwarded, zero rows replayed — and the payload is
        returned for the scan to resume from. Anything else (no
        checkpoint, a cursor-less checkpoint, missing or corrupt
        segments) degrades to a FRESH artifact: stale outputs are
        wiped and None is returned so the scan restarts at row zero."""
        cursor = payload["cursor"] if payload is not None else None
        eg = getattr(cursor, "egress", None)
        if eg is None or not self._resume_from(
            eg, payload.get("degradation")
        ):
            self.start_fresh()
            return None
        tm = get_telemetry()
        # pinned 0 by construction: the cursor was written only after
        # its span segment fsynced, so nothing needs re-emission
        tm.counter("engine.egress_rows_replayed").inc(
            self._rows_replayed
        )
        tm.event(
            "egress_resumed",
            span_seq=int(eg.last_durably_flushed_span_seq),
            rows_clean=self.rows_clean,
            rows_quarantined=self.rows_quarantined,
            rows_replayed=self._rows_replayed,
        )
        return payload

    def _resume_from(self, eg, record) -> bool:
        seq = int(eg.last_durably_flushed_span_seq)
        # drop the torn open segment and any segments PAST the cursor
        # (overflow rotations after the last checkpoint): their rows
        # were never cursored, so the rescan re-emits them exactly once
        if os.path.exists(self._seg_tmp):
            os.remove(self._seg_tmp)
        have = self._list_segments()
        if any(s not in have for s in range(seq + 1)):
            return False
        if seq >= 0 and not self._segment_intact(have[seq]):
            return False
        for s, path in have.items():
            if s > seq:
                os.remove(path)
        offset = int(eg.plane_spool_offset)
        if self.spool_mode:
            if not os.path.exists(self._spool_path):
                if offset:
                    return False
            elif os.path.getsize(self._spool_path) < offset:
                return False
            else:
                with open(self._spool_path, "rb+") as fh:
                    fh.truncate(offset)
        self.rows_clean = int(eg.rows_emitted_clean)
        self.rows_quarantined = int(eg.rows_emitted_quarantined)
        self.cursor = self.rows_clean + self.rows_quarantined
        self.bytes_raw = int(eg.bytes_raw)
        self.bytes_encoded = int(eg.bytes_encoded)
        self._seg_seq = seq + 1
        self._rows_replayed = 0
        # fast-forward the sequential span reader past the rows already
        # durably written — taken and discarded, never re-emitted
        skip = self.cursor
        reader = self._ensure_reader()
        while skip > 0:
            step = min(skip, 1 << 16)
            reader.take(step)
            skip -= step
        # failure spans already emitted (whole, before the cursor) must
        # not re-enter the pending queue from the restored record
        self._refresh_failures(record)
        self._pending = [
            s for s in self._pending if s.start >= self.cursor
        ]
        return True

    def start_fresh(self) -> None:
        """Wipe every artifact a previous attempt may have left under
        ``out_dir`` — segments, split outputs, spool, manifest — so a
        non-resumable attempt rebuilds from row zero, never on top of
        stale spans."""
        if self._seg_writer is not None:
            try:
                self._seg_writer.close()
            except Exception:  # noqa: BLE001 — best-effort teardown
                pass
            self._seg_writer = None
        for path in (
            self._seg_tmp,
            self._spool_path,
            os.path.join(self.sink.out_dir, "manifest.json"),
        ):
            if os.path.exists(path):
                os.remove(path)
        for sub in ("spans", "clean", "quarantine"):
            shutil.rmtree(
                os.path.join(self.sink.out_dir, sub), ignore_errors=True
            )
        self._seg_seq = 0
        self._open_span_rows = 0
        self._rows_replayed = 0

    def _list_segments(self) -> Dict[int, str]:
        out: Dict[int, str] = {}
        if not os.path.isdir(self._seg_dir):
            return out
        for name in os.listdir(self._seg_dir):
            if name.startswith("seg-") and name.endswith(".parquet"):
                try:
                    out[int(name[4:-8])] = os.path.join(
                        self._seg_dir, name
                    )
                except ValueError:
                    continue
        return out

    def _read_segment_payload(self, path: str) -> Optional[bytes]:
        """The parquet payload of a CRC-stamped segment, or None when
        the footer is missing, torn, or fails its checksum."""
        with open(path, "rb") as fh:
            blob = fh.read()
        if len(blob) < _SEG_FOOTER.size:
            return None
        magic, length, crc = _SEG_FOOTER.unpack(
            blob[-_SEG_FOOTER.size :]
        )
        if magic != _SEG_MAGIC or length != len(blob) - _SEG_FOOTER.size:
            return None
        payload = blob[:length]
        if (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
            return None
        return payload

    def _segment_intact(self, path: str) -> bool:
        try:
            return self._read_segment_payload(path) is not None
        except OSError:
            return False

    def _compact_segments(self) -> None:
        """Collapse the span segments into the public ``clean/`` +
        ``quarantine/`` split — row group by row group, in span order,
        each group routed whole by its ``__egress_split__`` tag — so
        the compacted layout (one row group per span-split) is
        byte-identical whether or not the run was ever interrupted."""
        tm = get_telemetry()
        have = self._list_segments()
        for s in range(len(have)):
            path = have.get(s)
            if path is None:
                raise RuntimeError(
                    f"egress segment seq {s} missing at compaction — "
                    "the span sequence must be gapless"
                )
            payload = self._read_segment_payload(path)
            if payload is None:
                raise RuntimeError(
                    f"egress segment {path} failed its CRC check"
                )
            pf = pq.ParquetFile(pa.BufferReader(payload))
            for g in range(pf.num_row_groups):
                group = pf.read_row_group(g)
                if group.num_rows == 0:
                    continue
                is_q = bool(group.column(_SPLIT_COL)[0].as_py())
                which = "quarantine" if is_q else "clean"
                drop = {_SPLIT_COL}
                if which == "clean":
                    drop.update(_Q_ONLY)
                routed = group.select(
                    [
                        nm
                        for nm in group.schema.names
                        if nm not in drop
                    ]
                )
                self._ensure_writer(which, routed.schema).write_table(
                    routed
                )
            tm.counter("engine.egress_segments_compacted").inc()

    # -- finalize --------------------------------------------------------

    def replay_spool(
        self,
        deferred: Dict[str, Tuple[np.ndarray, Optional[np.ndarray]]],
        record,
    ) -> None:
        """Spool mode phase 2: merge the scanned bit planes with the
        finalize-phase (deferred) outcomes and write the split, span by
        span — bounded by one span, exactly like the direct path."""
        if self._spool is not None:
            self._spool.close()
            self._spool = None
        self._refresh_failures(record)
        n_planes, b8 = self._plane_shape
        rec_bytes = n_planes * b8
        with open(self._spool_path, "rb") as fh:
            while True:
                head = fh.read(8)
                if len(head) < 8:
                    break
                (valid,) = struct.unpack("<q", head)
                payload = fh.read(rec_bytes)
                bits = np.frombuffer(payload, dtype=np.uint8).reshape(
                    n_planes, b8
                )
                self._emit(bits, int(valid), deferred=deferred)

    def finish(self, record, interrupted: bool) -> Tuple[int, int]:
        """Drain trailing quarantined units (a failure after the last
        fold), close the parquet writers (writing empty files for a
        split that never materialized, so consumers can always read
        both), and return (rows_clean, rows_quarantined). Traced runs
        record the flush as one ``egress`` child span."""
        from deequ_tpu.telemetry import clock as _wall_clock

        _t0 = _wall_clock()
        self._refresh_failures(record)
        self._drain_failures()
        if not interrupted and self.cursor != self.num_rows:
            # not an exception: an interrupt mid-scan legitimately
            # leaves a tail unwritten, and we only know "interrupted"
            # when the engine says so — anything else is a real
            # misalignment worth surfacing loudly
            raise RuntimeError(
                f"egress wrote {self.cursor} of {self.num_rows} source "
                "rows without an interruption — span accounting bug"
            )
        if interrupted:
            # leave the artifact in resumable span form: publish the
            # open segment (rows past the last durable cursor are
            # truncated-and-rescanned on resume), keep the spool and
            # segments, write NO split files — only a completing
            # attempt compacts, so row counters are accounted exactly
            # once across however many attempts the run took
            self._finalize_open_segment()
            if self._spool is not None:
                self._spool.flush()
                self._spool.close()
                self._spool = None
            return self.rows_clean, self.rows_quarantined
        self._finalize_open_segment()
        self._compact_segments()
        for which in ("clean", "quarantine"):
            if which not in self._writers:
                schema = self._split_schema_for(which)
                if schema is not None:
                    self._ensure_writer(which, schema)
        for writer in self._writers.values():
            writer.close()
        self._writers.clear()
        if self._spool is not None:
            self._spool.close()
            self._spool = None
        if os.path.exists(self._spool_path):
            os.remove(self._spool_path)
        shutil.rmtree(self._seg_dir, ignore_errors=True)
        tm = get_telemetry()
        tm.counter("engine.rows_clean").inc(self.rows_clean)
        tm.counter("engine.rows_quarantined").inc(self.rows_quarantined)
        if tm.current_trace() is not None:
            tm.emit_span(
                "egress",
                _wall_clock() - _t0,
                rows_clean=self.rows_clean,
                rows_quarantined=self.rows_quarantined,
            )
        return self.rows_clean, self.rows_quarantined

    def abort(self) -> None:
        """Scan failed outright: close everything without the
        alignment check; whatever was written stays on disk for
        inspection, the report says 'aborted'."""
        if self._seg_writer is not None:
            try:
                self._seg_writer.close()
            except Exception:  # noqa: BLE001 — best-effort teardown
                pass
            self._seg_writer = None
        for writer in self._writers.values():
            try:
                writer.close()
            except Exception:  # noqa: BLE001 — best-effort teardown
                pass
        self._writers.clear()
        if self._spool is not None:
            try:
                self._spool.close()
            except Exception:  # noqa: BLE001
                pass
            self._spool = None
        if os.path.exists(self._spool_path):
            os.remove(self._spool_path)

    def _split_schema_for(self, which: str) -> Optional[pa.Schema]:
        """Schema for an empty split file. Normally derived from the
        run's row schema; a resumed run that emitted nothing after its
        resume never learned one, so the schema is derived from the
        OTHER split's compacted schema instead (None when neither
        source exists — no rows at all, no files)."""
        if self._row_schema is not None:
            return self._empty_schema_for(which)
        other = self._schemas.get(
            "quarantine" if which == "clean" else "clean"
        )
        if other is None:
            return None
        if which == "clean":
            return pa.schema(
                [f for f in other if f.name not in _Q_ONLY]
            )
        return pa.schema(
            list(other)
            + [
                pa.field(nm, _Q_ONLY_TYPES[nm])
                for nm in _Q_ONLY
                if nm not in other.names
            ]
        )

    def _empty_schema_for(self, which: str) -> pa.Schema:
        fields = list(self._row_schema)
        for name in self.scan_names + self.deferred_names:
            fields.append(pa.field(name, pa.bool_()))
        fields.append(pa.field(_PROV_ROW, pa.from_numpy_dtype(self._idx_dtype)))
        fields.append(pa.field(_PROV_SEQ, pa.from_numpy_dtype(self._seq_dtype)))
        if which == "quarantine":
            fields.extend(
                [
                    pa.field(_PROV_FAILED, pa.string()),
                    pa.field(_PROV_ERR_CLASS, pa.string()),
                    pa.field(_PROV_ERR_MSG, pa.string()),
                    pa.field(_PROV_ATTEMPTS, pa.int32()),
                    pa.field(_PROV_TENANT, pa.string()),
                    pa.field(_PROV_RUN, pa.string()),
                ]
            )
        return pa.schema(fields)

    def write_manifest(self, report: EgressReport, extra: Dict[str, Any]) -> str:
        path = os.path.join(self.sink.out_dir, "manifest.json")
        payload = {
            "status": report.status,
            "tenant": self.sink.tenant,
            "run_id": self.sink.run_id,
            "filtered_row_outcome": self.sink.filtered_row_outcome,
            "rows_total": report.rows_total,
            "rows_clean": report.rows_clean,
            "rows_quarantined": report.rows_quarantined,
            "bytes_raw": report.bytes_raw,
            "bytes_encoded": report.bytes_encoded,
            "constraints": report.constraints,
            "unsupported": report.unsupported,
            "clean": self._paths.get("clean", ""),
            "quarantine": self._paths.get("quarantine", ""),
            **extra,
        }
        blob = json.dumps(payload, indent=2, default=str).encode()
        # durable + atomic (temp + fsync + rename): a crash during
        # finalize must never leave a torn manifest for a
        # status="interrupted" reader to misparse
        storage_for(self.sink.out_dir).write_bytes(
            "manifest.json", blob, durable=True
        )
        return path
