"""Token-level discipline rules migrated from ``tools.telemetry_lint``.

Five rule families, unchanged in WHAT they flag (the token strings and
scopes are the originals, so ``tools.telemetry_lint``'s tuple API can
be rebuilt from these findings verbatim), changed in HOW: they run on
the shared :class:`~tools.staticcheck.core.SourceFile` token streams,
report through the framework's :class:`Finding`/waiver machinery, and
a malformed file surfaces as a ``tokenize-error`` finding instead of
crashing (the old scanner caught the nonexistent
``tokenize.TokenizeError`` — an AttributeError on first contact).

- ``telemetry-timing``: own-clock/own-trace NAME tokens outside
  ``deequ_tpu/telemetry/`` (docs/OBSERVABILITY.md).
- ``oom-taxonomy``: ad-hoc OOM classification (``MemoryError`` NAMEs,
  allocator marker strings) outside ``engine/memory.py``.
- ``sync-discipline``: ``device_get``/``asarray`` in ``engine/``
  outside pack.py without a waiver (``# sync-ok:`` still honored).
- ``service-time`` / ``service-admission``: the PR 7 service rules —
  injected clocks only, engine entry only via the runner's admission
  layer (docs/SERVICE.md).
"""

from __future__ import annotations

import tokenize
from typing import Iterable, List, Sequence, Tuple

from tools.staticcheck.core import Analyzer, Finding, SourceFile, register

HOT_PATH_DIRS = (
    "deequ_tpu/engine",
    "deequ_tpu/data",
    "deequ_tpu/analyzers",
    "deequ_tpu/profiles",
    "deequ_tpu/verification",
    "deequ_tpu/sketches",
    "deequ_tpu/checks",
    "deequ_tpu/io",
    "deequ_tpu/utils",
    "deequ_tpu/service",
)

FORBIDDEN_NAMES = frozenset(
    {"perf_counter", "start_trace", "stop_trace", "TraceAnnotation"}
)
EXEMPT_PREFIX = "deequ_tpu/telemetry/"

FORBIDDEN_OOM_NAMES = frozenset({"MemoryError"})
FORBIDDEN_OOM_MARKERS = ("resource_exhausted", "out of memory")
OOM_EXEMPT_FILES = frozenset({"deequ_tpu/engine/memory.py"})

FORBIDDEN_SYNC_NAMES = frozenset({"device_get", "asarray"})
SYNC_HOT_PREFIX = "deequ_tpu/engine/"
SYNC_EXEMPT_FILES = frozenset({"deequ_tpu/engine/pack.py"})

SERVICE_PREFIX = "deequ_tpu/service/"
SERVICE_TIME_NAMES = frozenset({"sleep", "monotonic"})
SERVICE_ADMISSION_NAMES = frozenset(
    {
        "run_scan",
        "prepare_scan",
        "execute_plan",
        "_run_scan_resident",
        "_run_scan_streaming",
    }
)
SERVICE_TIME_ATTRS = frozenset(
    {"time", "sleep", "monotonic", "perf_counter"}
)


def _in_hot_path(rel: str) -> bool:
    return any(rel.startswith(d + "/") for d in HOT_PATH_DIRS)


def _service_hits(tokens: Sequence[tokenize.TokenInfo]) -> List[
    Tuple[int, str, str]
]:
    """(line, symbol, rule) hits for one service module: banned NAMEs
    plus the ``time.<attr>`` chain check, run over significant tokens
    only so comments/docstrings never flag."""
    out: List[Tuple[int, str, str]] = []
    significant = [
        tok
        for tok in tokens
        if tok.type
        in (tokenize.NAME, tokenize.OP, tokenize.NUMBER, tokenize.STRING)
    ]
    for i, tok in enumerate(significant):
        if tok.type != tokenize.NAME:
            continue
        if tok.string in SERVICE_TIME_NAMES:
            out.append((tok.start[0], tok.string, "service-time"))
        elif tok.string in SERVICE_ADMISSION_NAMES:
            out.append((tok.start[0], tok.string, "service-admission"))
        elif (
            tok.string == "time"
            and i + 2 < len(significant)
            and significant[i + 1].string == "."
            and significant[i + 2].type == tokenize.NAME
            and significant[i + 2].string in SERVICE_TIME_ATTRS
        ):
            out.append(
                (
                    tok.start[0],
                    f"time.{significant[i + 2].string}",
                    "service-time",
                )
            )
    return out


class TokenDisciplineAnalyzer(Analyzer):
    name = "tokens"
    rules = (
        "telemetry-timing",
        "oom-taxonomy",
        "sync-discipline",
        "service-time",
        "service-admission",
        "tokenize-error",
    )
    description = (
        "token-level hot-path discipline (timing/OOM/sync/service), "
        "migrated from tools.telemetry_lint"
    )

    def analyze(
        self, files: Sequence[SourceFile], root: str
    ) -> Iterable[Finding]:
        for sf in files:
            if not _in_hot_path(sf.rel):
                continue
            if sf.rel.startswith(EXEMPT_PREFIX):
                continue
            if sf.token_error is not None:
                yield Finding(
                    rule="tokenize-error",
                    path=sf.rel,
                    line=0,
                    message=f"cannot tokenize module: {sf.token_error}",
                    symbol="<tokenize error>",
                )
                continue
            oom_exempt = sf.rel in OOM_EXEMPT_FILES
            sync_checked = sf.rel.startswith(
                SYNC_HOT_PREFIX
            ) and sf.rel not in SYNC_EXEMPT_FILES
            for tok in sf.tokens:
                if tok.type == tokenize.NAME and tok.string in FORBIDDEN_NAMES:
                    yield Finding(
                        rule="telemetry-timing",
                        path=sf.rel,
                        line=tok.start[0],
                        message=(
                            f"ad-hoc timing/tracing token '{tok.string}' — "
                            "wall-clock attribution lives in "
                            "deequ_tpu/telemetry/"
                        ),
                        symbol=tok.string,
                    )
                elif (
                    tok.type == tokenize.NAME
                    and not oom_exempt
                    and tok.string in FORBIDDEN_OOM_NAMES
                ):
                    yield Finding(
                        rule="oom-taxonomy",
                        path=sf.rel,
                        line=tok.start[0],
                        message=(
                            f"ad-hoc OOM classification '{tok.string}' — "
                            "memory-pressure taxonomy lives in "
                            "engine/memory.py"
                        ),
                        symbol=tok.string,
                    )
                elif (
                    tok.type == tokenize.NAME
                    and sync_checked
                    and tok.string in FORBIDDEN_SYNC_NAMES
                ):
                    yield Finding(
                        rule="sync-discipline",
                        path=sf.rel,
                        line=tok.start[0],
                        message=(
                            f"engine-layer device sync '{tok.string}' "
                            "outside the packed epilogue (engine/pack.py)"
                        ),
                        symbol=tok.string,
                    )
                elif (
                    tok.type == tokenize.STRING
                    and not oom_exempt
                    and any(
                        marker in tok.string.lower()
                        for marker in FORBIDDEN_OOM_MARKERS
                    )
                ):
                    yield Finding(
                        rule="oom-taxonomy",
                        path=sf.rel,
                        line=tok.start[0],
                        message=(
                            "allocator-failure marker string — OOM "
                            "string-matching lives in engine/memory.py"
                        ),
                        symbol="<oom marker string>",
                    )
            if sf.rel.startswith(SERVICE_PREFIX):
                for line, symbol, rule in _service_hits(sf.tokens):
                    reason = (
                        "service modules run on injected clocks "
                        "(engine/deadline.py)"
                        if rule == "service-time"
                        else "service modules enter the engine via the "
                        "runner's admission layer only"
                    )
                    yield Finding(
                        rule=rule,
                        path=sf.rel,
                        line=line,
                        message=f"'{symbol}' in service layer — {reason}",
                        symbol=symbol,
                    )


register(TokenDisciplineAnalyzer())
