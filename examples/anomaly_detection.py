"""Anomaly detection on a metric time series via the repository.

Reference example: anomaly-detection example (SURVEY.md §2.5, §3.5):
append daily Size metrics to a repository, then let an anomaly check
compare today's value against the history.
"""

import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)  # allow running from a source checkout without installing

import numpy as np

from deequ_tpu import (
    Dataset,
    InMemoryMetricsRepository,
    RelativeRateOfChangeStrategy,
    ResultKey,
    Size,
    VerificationSuite,
)


def main():
    repository = InMemoryMetricsRepository()
    rng = np.random.default_rng(2)

    def dataset_of(n):
        return Dataset.from_pydict({"x": rng.normal(0, 1, n)})

    # seed a week of history with ~stable sizes
    for d, n in enumerate([10_000, 10_200, 9_900, 10_100, 10_050]):
        (
            VerificationSuite()
            .on_data(dataset_of(n))
            .use_repository(repository)
            .save_or_append_result(ResultKey.of(d))
            .add_anomaly_check(
                RelativeRateOfChangeStrategy(
                    max_rate_decrease=0.8, max_rate_increase=1.2
                ),
                Size(),
            )
            .run()
        )

    # today the pipeline truncated: only 3k rows arrive
    result = (
        VerificationSuite()
        .on_data(dataset_of(3_000))
        .use_repository(repository)
        .save_or_append_result(ResultKey.of(5))
        .add_anomaly_check(
            RelativeRateOfChangeStrategy(
                max_rate_decrease=0.8, max_rate_increase=1.2
            ),
            Size(),
        )
        .run()
    )
    print(f"today's run status: {result.status}")
    for record in result.check_results_as_records():
        print(f"  {record['constraint']}: {record['constraint_status']} "
              f"{record['constraint_message']}")
    assert result.status.value != "Success", "the 70% drop must be flagged"


if __name__ == "__main__":
    main()
