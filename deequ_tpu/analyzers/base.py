"""Analyzer framework: states are commutative monoids, metrics are values.

Reference contract (``src/main/scala/com/amazon/deequ/analyzers/Analyzer.scala``,
SURVEY.md §2.2): an analyzer is (compute state from data, compute metric
from state, preconditions); states implement ``sum`` (a commutative monoid
merge) — the whole incremental/distributed story hangs on that.

deequ_tpu expresses each scan-shareable analyzer as a :class:`ScanOps`
triple over fixed-shape pytrees:

- ``init()``                  — monoid identity (host numpy pytree)
- ``update(state, batch)``    — traced, vectorized masked reduction over a
                                device batch; XLA fuses all analyzers'
                                updates into a single pass (the TPU
                                equivalent of the reference's one
                                ``df.agg(...)`` scan, SURVEY.md §3.1 ★#1)
- ``merge(a, b)``             — traced monoid merge; also the collective
                                used across the device mesh and across
                                persisted incremental states

Finalization (state → metric) is a tiny host-side epilogue, and failures
(missing column, empty state) become failure *metrics*, never user-facing
exceptions (SURVEY.md §5.3).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from deequ_tpu.data.table import ColumnRequest, Dataset, Kind, Schema
from deequ_tpu.metrics.metric import DoubleMetric, Entity, Metric
from deequ_tpu.utils.trylike import Failure


# --------------------------------------------------------------------------
# Failure model (reference: analyzers/runners/MetricCalculationException.scala)
# --------------------------------------------------------------------------


class MetricCalculationException(Exception):
    """Base for per-analyzer failures embedded into failure metrics."""


class NoSuchColumnException(MetricCalculationException):
    pass


class WrongColumnTypeException(MetricCalculationException):
    pass


class NoColumnsSpecifiedException(MetricCalculationException):
    pass


class NumberOfSpecifiedColumnsException(MetricCalculationException):
    pass


class IllegalAnalyzerParameterException(MetricCalculationException):
    pass


class EmptyStateException(MetricCalculationException):
    pass


class MetricCalculationRuntimeException(MetricCalculationException):
    pass


def wrap_if_necessary(exc: BaseException) -> MetricCalculationException:
    if isinstance(exc, MetricCalculationException):
        return exc
    return MetricCalculationRuntimeException(repr(exc))


# --------------------------------------------------------------------------
# Preconditions (reference: analyzers/Preconditions object)
# --------------------------------------------------------------------------

Precondition = Callable[[Schema], None]


def has_column(column: str) -> Precondition:
    def check(schema: Schema) -> None:
        if not schema.has_column(column):
            raise NoSuchColumnException(
                f"Input data does not include column {column}!"
            )

    return check


def is_numeric(column: str) -> Precondition:
    def check(schema: Schema) -> None:
        if not schema.kind_of(column).is_numeric:
            raise WrongColumnTypeException(
                f"Expected type of column {column} to be numeric, but found "
                f"{schema.kind_of(column).value} instead!"
            )

    return check


def is_string(column: str) -> Precondition:
    def check(schema: Schema) -> None:
        if schema.kind_of(column) != Kind.STRING:
            raise WrongColumnTypeException(
                f"Expected type of column {column} to be String, but found "
                f"{schema.kind_of(column).value} instead!"
            )

    return check


def is_not_nested(column: str) -> Precondition:
    def check(schema: Schema) -> None:
        if schema.kind_of(column) == Kind.UNKNOWN:
            raise WrongColumnTypeException(
                f"Unsupported nested/unknown type in column {column}!"
            )

    return check


def at_least_one(columns: Sequence[str]) -> Precondition:
    def check(schema: Schema) -> None:
        if len(columns) == 0:
            raise NoColumnsSpecifiedException(
                "At least one column needs to be specified!"
            )

    return check


def exactly_n_columns(columns: Sequence[str], n: int) -> Precondition:
    def check(schema: Schema) -> None:
        if len(columns) != n:
            raise NumberOfSpecifiedColumnsException(
                f"Exactly {n} columns needed, got {len(columns)}"
            )

    return check


# --------------------------------------------------------------------------
# Scan ops
# --------------------------------------------------------------------------

StateTree = Any  # pytree of arrays (numpy host-side, jax inside jit)
Batch = Dict[str, Any]


class _CacheTokenAuto:
    def __repr__(self) -> str:  # pragma: no cover — debugging aid
        return "<CACHE_TOKEN_AUTO>"


CACHE_TOKEN_AUTO = _CacheTokenAuto()


class _DeltaPrime:
    def __repr__(self) -> str:  # pragma: no cover — debugging aid
        return "<DELTA_PRIME>"


# Sentinel passed to ScanOps.host_delta on NON-streaming paths (resident
# scan, sharded step) where no per-batch dictionary deltas flow: the op
# must load the FULL dictionaries from the dataset (a pre-pass is fine
# there — the data is already resident). Without priming, a delta-aware
# op's LUT state would silently stay empty on those paths.
DELTA_PRIME = _DeltaPrime()


@dataclass
class ScanOps:
    """The (identity, update, merge) triple for one analyzer, compiled
    against a concrete dataset (closures hold compiled predicates).

    ``consts`` — per-dataset lookup tables (dictionary LUTs for
    PatternMatch/DataType/HLL-on-strings). They enter the jitted scan as
    RUNTIME INPUTS, not closure constants: embedded constants would bake
    each dataset's dictionary into the HLO and force a full XLA
    recompile per dataset, defeating the persistent compilation cache.
    When ``consts`` is set, ``update`` takes ``(state, batch, consts)``.
    LUT shapes should be padded to powers of two (``pad_pow2``) so
    different dictionaries of similar size share one compiled program.

    Host-folded analyzers (KLL): ``update`` emits a small fixed-shape
    per-batch device output instead of a running carry, and the engine
    folds it into a host accumulator via ``host_fold`` after each batch
    — only k floats cross the boundary, the data pass stays fused."""

    init: Callable[[], StateTree]
    update: Callable[..., StateTree]
    merge: Callable[[StateTree, StateTree], StateTree]
    host_init: Optional[Callable[[], Any]] = None
    host_fold: Optional[Callable[[Any, Any], Any]] = None
    consts: Optional[Dict[str, np.ndarray]] = None
    # behavior fingerprint for the engine's cross-run plan cache: two
    # ops with EQUAL tokens must trace to identical computations (all
    # dataset-specific content rides `consts`).
    # - CACHE_TOKEN_AUTO (default): the runner derives a standard token
    #   for the built-in analyzers; the engine treats a still-AUTO op
    #   as uncacheable.
    # - None: EXPLICIT opt-out — never reuse a compiled plan containing
    #   this op (dataset-derived constants baked into the closure).
    cache_token: Optional[object] = CACHE_TOKEN_AUTO
    # collector ops (one-pass spill): the final state is a device-
    # resident key buffer consumed by a post-scan sort finalize — the
    # engine excludes it from the epilogue's packed fetch instead of
    # round-tripping megabytes of keys through the host.
    device_result: bool = False
    # one-pass dictionary deltas (docs/PERF.md "Wire diet"): ops whose
    # LUTs live in STATE instead of consts receive incremental
    # dictionary updates here. Called on the host as
    # ``host_delta(state, deltas)`` where ``deltas`` maps column ->
    # {"start": int, "values": ndarray} (new uniques appended at
    # ``start``), or with the DELTA_PRIME sentinel on non-streaming
    # paths (load full dictionaries from the dataset). Returns the
    # updated state tree; applied in batch order BEFORE that batch's
    # fused update so codes never index past the shipped LUT rows.
    host_delta: Optional[Callable[[StateTree, Any], StateTree]] = None

    def apply_update(self, state, batch, consts):
        if self.consts is None:
            return self.update(state, batch)
        return self.update(state, batch, consts)


def make_cache_token(
    analyzer: "ScanShareableAnalyzer",
    dataset: Dataset,
    predicates: Sequence[Optional[str]] = (),
) -> Optional[tuple]:
    """Standard ScanOps.cache_token: the analyzer's repr (frozen
    dataclass => deterministic, includes every parameter) plus the KINDS
    of the involved columns (update closures branch on kind at build
    time). None when any predicate bakes dictionary-derived constants
    into its closure."""
    from deequ_tpu.sql.predicate import compile_predicate

    for expression in predicates:
        if expression is None:
            continue
        if not compile_predicate(expression, dataset).dataset_independent:
            return None
    kinds = tuple(
        sorted(
            {
                (r.column, dataset.schema.kind_of(r.column).value)
                for r in analyzer.device_requests(dataset)
            }
        )
    )
    return (repr(analyzer), kinds)


def pad_pow2(arr: np.ndarray, fill=0) -> np.ndarray:
    """Pad a 1-D LUT to the next power-of-two length so compiled scans
    are shared across datasets whose dictionaries have similar sizes."""
    n = len(arr)
    m = 1 << max(0, (n - 1).bit_length())
    if m <= n:
        return arr
    return np.concatenate([arr, np.full(m - n, fill, dtype=arr.dtype)])


# --------------------------------------------------------------------------
# Analyzer base classes
# --------------------------------------------------------------------------


@dataclass(frozen=True)
class Analyzer:
    """Base analyzer. Frozen dataclass => hashable, dedupable (the runner
    dedups analyzers and uses them as context-map keys, SURVEY.md §2.4)."""

    # -- identity -------------------------------------------------------

    @property
    def name(self) -> str:
        return type(self).__name__

    @property
    def entity(self) -> Entity:
        return Entity.COLUMN

    @property
    def instance(self) -> str:
        raise NotImplementedError

    @property
    def identity_key(self) -> str:
        """Stable identity for context slicing (``AnalyzerContext.subset``
        and the service-side scan coalescer). Frozen dataclass ``repr`` is
        deterministic and parameter-complete — two analyzers with equal
        keys compute the same metric on the same data, the same contract
        ``make_cache_token`` already leans on."""
        return repr(self)

    # -- contract -------------------------------------------------------

    def preconditions(self) -> List[Precondition]:
        return []

    def compute_metric_from_state(self, state: Optional[StateTree]) -> Metric:
        """Host-side finalize; ``state=None`` means no rows contributed."""
        raise NotImplementedError

    def to_failure_metric(self, exc: BaseException) -> Metric:
        return DoubleMetric(
            self.entity, self.name, self.instance, Failure(wrap_if_necessary(exc))
        )

    # -- convenience ----------------------------------------------------

    def calculate(
        self,
        data: Dataset,
        aggregate_with=None,
        save_states_with=None,
        engine=None,
    ) -> Metric:
        """Compute just this analyzer (delegates to the runner so scan
        sharing / precondition semantics are identical)."""
        from deequ_tpu.analyzers.runner import AnalysisRunner

        context = AnalysisRunner.do_analysis_run(
            data,
            [self],
            aggregate_with=aggregate_with,
            save_states_with=save_states_with,
            engine=engine,
        )
        return context.metric(self)  # type: ignore[return-value]


@dataclass(frozen=True)
class ScanShareableAnalyzer(Analyzer):
    """An analyzer whose state updates fuse into the shared single pass."""

    def device_requests(self, dataset: Dataset) -> List[ColumnRequest]:
        raise NotImplementedError

    def make_ops(self, dataset: Dataset) -> ScanOps:
        raise NotImplementedError


@dataclass(frozen=True)
class GroupingAnalyzer(Analyzer):
    """An analyzer over value frequencies; the runner computes one
    frequency table per distinct (grouping columns, filter) and shares it
    (reference: GroupingAnalyzers.scala / FrequencyBasedAnalyzer)."""

    def grouping_columns(self) -> List[str]:
        raise NotImplementedError

    @property
    def filter_condition(self) -> Optional[str]:
        return None

    def compute_metric_from_frequencies(self, frequencies) -> Metric:
        raise NotImplementedError

    def preconditions(self) -> List[Precondition]:
        cols = self.grouping_columns()
        checks: List[Precondition] = [at_least_one(cols)]
        checks.extend(has_column(c) for c in cols)
        checks.extend(is_not_nested(c) for c in cols)
        return checks


def merged_where_clause(where: Optional[str]) -> str:
    return where if where else "(no filter)"


def filter_suffix(where: Optional[str]) -> Tuple:
    """Include the filter in analyzer identity so differently-filtered
    analyzers don't collide in the context map."""
    return (where,) if where else ()
