"""ColumnProfilerRunner: fluent builder for profiling runs.

Reference: ``profiles/ColumnProfilerRunner.scala`` +
``ColumnProfilerRunBuilder.scala`` (SURVEY.md §2.5).
"""

from __future__ import annotations

from typing import Optional, Sequence

from deequ_tpu.data.table import Dataset
from deequ_tpu.engine.scan import AnalysisEngine
from deequ_tpu.profiles.profiler import (
    ColumnProfiler,
    ColumnProfiles,
    DEFAULT_LOW_CARDINALITY_THRESHOLD,
)
from deequ_tpu.sketches.kll import KLLParameters


class ColumnProfilerRunner:
    def on_data(self, data: Dataset) -> "ColumnProfilerRunBuilder":
        return ColumnProfilerRunBuilder(data)


class ColumnProfilerRunBuilder:
    def __init__(self, data: Dataset):
        self._data = data
        self._restrict_to_columns: Optional[Sequence[str]] = None
        self._low_cardinality_threshold = DEFAULT_LOW_CARDINALITY_THRESHOLD
        self._kll_profiling = False
        self._kll_parameters: Optional[KLLParameters] = None
        self._engine: Optional[AnalysisEngine] = None

    def restrict_to_columns(
        self, columns: Sequence[str]
    ) -> "ColumnProfilerRunBuilder":
        self._restrict_to_columns = list(columns)
        return self

    def with_low_cardinality_histogram_threshold(
        self, threshold: int
    ) -> "ColumnProfilerRunBuilder":
        self._low_cardinality_threshold = threshold
        return self

    def with_kll_profiling(
        self, kll_parameters: Optional[KLLParameters] = None
    ) -> "ColumnProfilerRunBuilder":
        self._kll_profiling = True
        self._kll_parameters = kll_parameters
        return self

    def with_engine(self, engine: AnalysisEngine) -> "ColumnProfilerRunBuilder":
        self._engine = engine
        return self

    def run(self) -> ColumnProfiles:
        return ColumnProfiler.profile(
            self._data,
            restrict_to_columns=self._restrict_to_columns,
            low_cardinality_histogram_threshold=self._low_cardinality_threshold,
            kll_profiling=self._kll_profiling,
            kll_parameters=self._kll_parameters,
            engine=self._engine,
        )
