"""The staticcheck framework: file model, waivers, registry, runner.

Everything an analyzer needs arrives as a :class:`SourceFile` — the
raw source, the ``ast`` tree (or the parse error), the token stream
(or the tokenize error), and the per-line waiver map — so individual
analyzers never re-read or re-parse, and a malformed file degrades to
ONE ``parse-error`` finding instead of crashing the run (the exact
failure mode the old ``tokenize.TokenizeError`` AttributeError hid;
the real name is ``tokenize.TokenError``).

Waiver syntax (docs/STATIC_ANALYSIS.md):

    x = thing()  # lint-ok: <rule>[,<rule2>]: <reason>

A trailing waiver covers its own line; a waiver comment ALONE on a
line covers the next code line (for statements too long to share a
line with a reason). The rule list must name the rule being waived
(``*`` waives any rule — discouraged) and the reason is mandatory:
an unreasoned waiver is itself a finding. The legacy ``# sync-ok:
<reason>`` marker (PR 6) is accepted as a same-line waiver for the
``sync-discipline`` rule so the six existing engine sites keep
working unchanged.
"""

from __future__ import annotations

import ast
import io
import json
import os
import re
import tokenize
from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

#: directories scanned by default, relative to the repo root. Analyzers
#: narrow further by path prefix; the framework only decides what gets
#: parsed at all (product code — tools/tests lint themselves via pytest).
DEFAULT_SCAN_DIRS = ("deequ_tpu",)

WAIVER_RE = re.compile(
    r"#\s*lint-ok:\s*(?P<rules>[\w*][\w*,\s-]*?):\s*(?P<reason>.+)"
)
LEGACY_SYNC_RE = re.compile(r"#\s*sync-ok:\s*(?P<reason>.+)")


@dataclass
class Finding:
    """One analyzer finding, anchored to a repo-relative line."""

    rule: str
    path: str  # repo-relative, forward slashes
    line: int
    message: str
    symbol: str = ""  # the offending token/attribute, when one exists
    waived: bool = False
    waive_reason: str = ""

    def to_dict(self) -> Dict[str, Any]:
        out = {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "message": self.message,
        }
        if self.symbol:
            out["symbol"] = self.symbol
        if self.waived:
            out["waived"] = True
            out["waive_reason"] = self.waive_reason
        return out

    def render(self) -> str:
        tag = " (waived)" if self.waived else ""
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}{tag}"


@dataclass
class Waiver:
    rules: Tuple[str, ...]
    reason: str
    line: int  # the line the waiver COVERS

    def covers(self, rule: str) -> bool:
        return "*" in self.rules or rule in self.rules


@dataclass
class SourceFile:
    """One parsed module, shared by every analyzer."""

    rel: str  # repo-relative path, forward slashes
    path: str  # absolute path
    source: str
    tree: Optional[ast.AST] = None
    parse_error: Optional[str] = None
    tokens: List[Any] = field(default_factory=list)
    token_error: Optional[str] = None
    waivers: Dict[int, List[Waiver]] = field(default_factory=dict)

    def waiver_for(self, rule: str, line: int) -> Optional[Waiver]:
        for waiver in self.waivers.get(line, ()):
            if waiver.covers(rule):
                return waiver
        return None


def _extract_waivers(
    source: str, tokens: Sequence[Any]
) -> Dict[int, List[Waiver]]:
    lines = source.splitlines()
    waivers: Dict[int, List[Waiver]] = {}

    def add(line: int, waiver: Waiver) -> None:
        waivers.setdefault(line, []).append(waiver)

    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        comment_line = tok.start[0]
        match = WAIVER_RE.search(tok.string)
        legacy = LEGACY_SYNC_RE.search(tok.string)
        if match is None and legacy is None:
            continue
        if match is not None:
            rules = tuple(
                r.strip() for r in match.group("rules").split(",") if r.strip()
            )
            reason = match.group("reason").strip()
        else:
            rules = ("sync-discipline",)
            reason = legacy.group("reason").strip()
        before = (
            lines[comment_line - 1][: tok.start[1]]
            if comment_line - 1 < len(lines)
            else ""
        )
        if before.strip():
            covered = comment_line  # trailing: waives its own line
        else:
            # standalone: waives the next non-blank, non-comment line
            covered = comment_line + 1
            while covered - 1 < len(lines):
                text = lines[covered - 1].strip()
                if text and not text.startswith("#"):
                    break
                covered += 1
        target = Waiver(rules=rules, reason=reason, line=covered)
        add(covered, target)
        # a legacy sync-ok trailing a continuation also covers the line
        # the comment sits on (the historical behavior)
        if legacy is not None and covered != comment_line:
            add(comment_line, Waiver(rules=rules, reason=reason,
                                     line=comment_line))
    return waivers


def load_source_file(root: str, rel: str) -> SourceFile:
    path = os.path.join(root, rel.replace("/", os.sep))
    with open(path, "rb") as fh:
        raw = fh.read()
    source = raw.decode("utf-8", errors="replace")
    sf = SourceFile(rel=rel, path=path, source=source)
    try:
        sf.tokens = list(tokenize.tokenize(io.BytesIO(raw).readline))
    except (tokenize.TokenError, IndentationError, SyntaxError) as exc:
        sf.token_error = f"{type(exc).__name__}: {exc}"
    try:
        sf.tree = ast.parse(source, filename=rel)
    except SyntaxError as exc:
        sf.parse_error = f"{type(exc).__name__}: {exc.msg} (line {exc.lineno})"
    sf.waivers = _extract_waivers(source, sf.tokens)
    return sf


def collect_files(
    root: str, scan_dirs: Sequence[str] = DEFAULT_SCAN_DIRS
) -> List[SourceFile]:
    files: List[SourceFile] = []
    for rel_dir in scan_dirs:
        top = os.path.join(root, rel_dir)
        if not os.path.isdir(top):
            continue
        for dirpath, _dirnames, filenames in os.walk(top):
            for filename in sorted(filenames):
                if not filename.endswith(".py"):
                    continue
                rel = os.path.relpath(
                    os.path.join(dirpath, filename), root
                ).replace(os.sep, "/")
                files.append(load_source_file(root, rel))
    files.sort(key=lambda f: f.rel)
    return files


# --------------------------------------------------------------------------
# Registry
# --------------------------------------------------------------------------


class Analyzer:
    """Base class: subclass, set ``name``/``rules``/``description``,
    implement ``analyze``. Registration is explicit (``register``), so
    importing the package wires the default suite exactly once."""

    name: str = ""
    rules: Tuple[str, ...] = ()
    description: str = ""

    def analyze(
        self, files: Sequence[SourceFile], root: str
    ) -> Iterable[Finding]:
        raise NotImplementedError


_REGISTRY: "Dict[str, Analyzer]" = {}


def register(analyzer: Analyzer) -> Analyzer:
    if not analyzer.name or not analyzer.rules:
        raise ValueError("analyzer needs a name and at least one rule")
    _REGISTRY[analyzer.name] = analyzer
    return analyzer


def all_analyzers() -> List[Analyzer]:
    return list(_REGISTRY.values())


def all_rules() -> List[Tuple[str, str]]:
    """(rule, owning-analyzer description) pairs, for ``--list-rules``."""
    out = []
    for analyzer in _REGISTRY.values():
        for rule in analyzer.rules:
            out.append((rule, analyzer.description))
    return sorted(out)


# --------------------------------------------------------------------------
# Runner
# --------------------------------------------------------------------------


def run_analyzers(
    root: str,
    rules: Optional[Sequence[str]] = None,
    scan_dirs: Sequence[str] = DEFAULT_SCAN_DIRS,
) -> List[Finding]:
    """Run every registered analyzer over ``root`` and apply waivers.
    Returns ALL findings (waived ones carry ``waived=True``); callers
    gate on the unwaived subset. A file that fails to parse yields one
    ``parse-error`` finding and is skipped by the AST analyzers."""
    files = collect_files(root, scan_dirs)
    findings: List[Finding] = []
    wanted = set(rules) if rules else None
    if wanted is None or "parse-error" in wanted:
        for sf in files:
            if sf.parse_error is not None:
                findings.append(
                    Finding(
                        rule="parse-error",
                        path=sf.rel,
                        line=0,
                        message=f"cannot parse module: {sf.parse_error}",
                    )
                )
    for analyzer in all_analyzers():
        if wanted is not None and not wanted.intersection(analyzer.rules):
            continue
        for finding in analyzer.analyze(files, root):
            if wanted is not None and finding.rule not in wanted:
                continue
            findings.append(finding)
    by_rel = {sf.rel: sf for sf in files}
    for finding in findings:
        sf = by_rel.get(finding.path)
        if sf is None:
            continue
        waiver = sf.waiver_for(finding.rule, finding.line)
        if waiver is not None:
            finding.waived = True
            finding.waive_reason = waiver.reason
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings


def unwaived(findings: Sequence[Finding]) -> List[Finding]:
    return [f for f in findings if not f.waived]


def summarize(findings: Sequence[Finding]) -> Dict[str, Any]:
    by_rule: Dict[str, int] = {}
    for f in findings:
        if not f.waived:
            by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
    return {
        "total": len(findings),
        "unwaived": sum(1 for f in findings if not f.waived),
        "waived": sum(1 for f in findings if f.waived),
        "by_rule": by_rule,
    }


def to_json(findings: Sequence[Finding], root: str) -> str:
    return json.dumps(
        {
            "root": root,
            "summary": summarize(findings),
            "findings": [f.to_dict() for f in findings],
        },
        indent=2,
        sort_keys=True,
    )


def default_root() -> str:
    return os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )


# -- small shared AST helpers ----------------------------------------------


def dotted_name(node: ast.AST) -> Optional[str]:
    """'a.b.c' for a Name/Attribute chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def annotation_class(node: Optional[ast.AST]) -> Optional[str]:
    """The class name an annotation resolves to, unwrapping
    Optional[X] / List[X] / "X" string forms; None when it isn't a
    simple class reference."""
    if node is None:
        return None
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        try:
            node = ast.parse(node.value, mode="eval").body
        except SyntaxError:
            return None
    if isinstance(node, ast.Subscript):
        outer = dotted_name(node.value) or ""
        if outer.split(".")[-1] in (
            "Optional", "List", "Sequence", "Iterable", "Tuple", "Set",
            "FrozenSet", "Deque",
        ):
            inner = node.slice
            if isinstance(inner, ast.Tuple) and inner.elts:
                inner = inner.elts[0]
            return annotation_class(inner)
        return None
    name = dotted_name(node)
    if name is None:
        return None
    return name.split(".")[-1]
