"""A production-shaped end-to-end pipeline (round-4 surface):

1. precompile the schema's plans before any data exists
   (tools/warmup.py machinery — the first real run then deserializes
   instead of paying the cold XLA compile);
2. STREAM a multi-file parquet table through the one-pass profiler
   (device cache off: host memory stays O(batch), the source is read
   once);
3. verify checks that exercise the r4 predicate grammar (string
   functions, CASE, CAST, date arithmetic) plus row-level outcomes;
4. persist metrics to a repository addressed by a storage URI
   (mem:// here; register_storage_scheme for S3/GCS in a deployment);
5. run an anomaly check of today's Size against the stored history.

Run: python examples/production_pipeline.py
"""

import datetime
import os
import shutil
import sys
import tempfile

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from deequ_tpu import (  # noqa: E402
    Check,
    CheckLevel,
    CheckStatus,
    Dataset,
    VerificationSuite,
    config,
)
from deequ_tpu.analyzers import Size  # noqa: E402
from deequ_tpu.anomalydetection import (  # noqa: E402
    AnomalyDetector,
    DataPoint,
    RelativeRateOfChangeStrategy,
)
from deequ_tpu.profiles.profiler import ColumnProfiler  # noqa: E402
from deequ_tpu.repository.base import ResultKey  # noqa: E402
from deequ_tpu.repository.fs import FileSystemMetricsRepository  # noqa: E402
from tools.warmup import synthetic_dataset  # noqa: E402


def make_day_shards(directory: str, day: int, rows: int) -> None:
    rng = np.random.default_rng(100 + day)
    base = datetime.datetime(2026, 7, 1) + datetime.timedelta(days=day)
    for shard in range(3):
        n = rows // 3
        amount = rng.gamma(2.0, 40.0, n)
        amount[rng.random(n) < 0.02] = np.nan
        table = pa.table(
            {
                "order_id": pa.array(
                    rng.integers(0, 1 << 40, n, dtype=np.int64)
                ),
                "amount": pa.array(
                    amount, pa.float64(), mask=np.isnan(amount)
                ),
                "status": pa.array(
                    np.array(["open", "shipped", "done", " DONE "])[
                        rng.integers(0, 4, n)
                    ]
                ).dictionary_encode(),
                "created": pa.array(
                    [
                        base + datetime.timedelta(seconds=int(s))
                        for s in rng.integers(0, 86_400, n)
                    ],
                    pa.timestamp("us"),
                ),
            }
        )
        pq.write_table(table, os.path.join(directory, f"d{day}-s{shard}.parquet"))


def main() -> None:
    schema = {
        "order_id": "int64",
        "amount": "float64",
        "status": "string",
        "created": "timestamp",
    }
    batch = 1 << 18

    with config.configure(batch_size=batch, device_cache_bytes=0):
        repo = FileSystemMetricsRepository("mem://warehouse/metrics.json")
        check = (
            Check(CheckLevel.ERROR, "orders")
            .has_size(lambda s: s > 0)
            .is_complete("order_id")
            .is_unique("order_id")
            .has_completeness("amount", lambda c: c > 0.9)
            .satisfies(
                "CASE WHEN amount IS NULL THEN 1 "
                "WHEN CAST(amount AS INT) >= 0 THEN 1 ELSE 0 END = 1",
                "non-negative-or-null",
                lambda f: f == 1.0,
            )
            .satisfies(
                "LOWER(TRIM(status)) IN ('open', 'shipped', 'done')",
                "status-domain",
                lambda f: f == 1.0,
            )
            .satisfies(
                "DATEDIFF('2026-08-01', created) BETWEEN 0 AND 62",
                "recent",
                lambda f: f == 1.0,
            )
        )

        # 1) warm the compiles from the SCHEMA, before any data: the
        # PROFILER plans and THIS CHECK's plans (uniqueness +
        # compliance predicates) both precompile, so day 0 below
        # deserializes instead of paying the cold XLA compile
        warm = synthetic_dataset(schema, batch, nullable=True, wide_ints=True)
        ColumnProfiler.profile(warm)
        VerificationSuite().on_data(warm).add_check(check).run()
        print("warmup: plans compiled for", list(schema))

        workdir = tempfile.mkdtemp(prefix="deequ_tpu_example_prod_")
        try:
            for day in range(4):
                shard_dir = os.path.join(workdir, f"day{day}")
                os.makedirs(shard_dir)
                # day 3 is an incident: volume collapses
                rows = 120_000 if day < 3 else 30_000
                make_day_shards(shard_dir, day, rows)
                data = Dataset.from_parquet(shard_dir)

                result = (
                    VerificationSuite()
                    .on_data(data)
                    .add_check(check)
                    .use_repository(repo)
                    .save_or_append_result(
                        ResultKey.of(day, {"dataset": "orders"})
                    )
                    .run()
                )
                print(
                    f"day {day}: rows={data.num_rows} "
                    f"checks={result.status.name} "
                    f"(scan passes: "
                    f"{len(result.run_metadata.passes)})"
                )
                assert result.status == CheckStatus.SUCCESS

            # 5) anomaly check: is today's Size anomalous vs history?
            history = sorted(
                (
                    DataPoint(
                        r.result_key.dataset_date,
                        r.analyzer_context.metric(Size()).value.get(),
                    )
                    for r in repo.load().get()
                ),
                key=lambda p: p.time,
            )
            detector = AnomalyDetector(
                RelativeRateOfChangeStrategy(
                    max_rate_decrease=0.5, max_rate_increase=2.0
                )
            )
            verdict = detector.is_new_point_anomalous(
                history[:-1], history[-1]
            )
            print(
                f"size history "
                f"{[int(p.metric_value) for p in history]}; day "
                f"{history[-1].time} anomalous: {verdict.is_anomalous}"
            )
            assert verdict.is_anomalous  # the day-3 collapse is caught
        finally:
            shutil.rmtree(workdir, ignore_errors=True)

    print("production pipeline example: OK")


if __name__ == "__main__":
    main()
