"""Canned data fixtures with known exact metric values (reference:
``src/test/scala/com/amazon/deequ/utils/FixtureSupport.scala``,
SURVEY.md §4)."""

import numpy as np
import pyarrow as pa

from deequ_tpu.data import Dataset


def df_full() -> Dataset:
    """4 complete rows."""
    return Dataset.from_pydict(
        {
            "item": ["1", "2", "3", "4"],
            "att1": ["a", "b", "a", "b"],
            "att2": ["c", "d", "d", "d"],
        }
    )


def df_missing() -> Dataset:
    """12 rows; att1 has 2 nulls (10/12 complete), att2 has 6 nulls
    (6/12 complete)."""
    att1 = ["a", "a", "b", "a", None, "a", "b", "a", "a", None, "b", "a"]
    att2 = ["f", "d", None, "f", None, "f", None, "d", None, None, None, "f"]
    return Dataset.from_pydict(
        {
            "item": [str(i + 1) for i in range(12)],
            "att1": att1,
            "att2": att2,
        }
    )


def df_numeric() -> Dataset:
    """6 rows of known numeric values: att1 = 1..6, att2 = 0,0,0,5,6,7."""
    return Dataset.from_pydict(
        {
            "item": ["1", "2", "3", "4", "5", "6"],
            "att1": [1, 2, 3, 4, 5, 6],
            "att2": [0, 0, 0, 5, 6, 7],
        }
    )


def df_numeric_with_nulls() -> Dataset:
    return Dataset.from_arrow(
        pa.table(
            {
                "att1": pa.array([1.0, None, 3.0, None, 5.0], pa.float64()),
                "att2": pa.array([2.0, 4.0, None, None, 10.0], pa.float64()),
            }
        )
    )


def df_unique() -> Dataset:
    """unique: all distinct; non_unique: a,a,b,b,c; half: a,a,b,c,d."""
    return Dataset.from_pydict(
        {
            "unique": ["1", "2", "3", "4", "5"],
            "non_unique": ["a", "a", "b", "b", "c"],
            "half": ["a", "a", "b", "c", "d"],
        }
    )


def df_strings() -> Dataset:
    return Dataset.from_pydict(
        {
            "email": [
                "someone@somewhere.org",
                "someone@else.com",
                "invalid-email",
                "other@domain.io",
            ],
            "name": ["foo", "bar", "foobar", None],
            "typed": ["1", "2.5", "true", "hello"],
        }
    )


def big_numeric(n: int = 100_000, seed: int = 7) -> Dataset:
    rng = np.random.default_rng(seed)
    return Dataset.from_pydict(
        {
            "x": rng.normal(10.0, 3.0, n),
            "y": rng.integers(0, 50, n),
        }
    )
