"""Exactly-once row-level egress under process death (docs/EGRESS.md
"Durable egress", docs/RESILIENCE.md): the chaos differentials.

A child process is hard-killed at each adversarial point the design
calls out — MID-SPAN (rows consumed past the last durable flush),
POST-FLUSH-PRE-CURSOR (the span segment is durable but the checkpoint
cursor naming it never landed), and MID-FINALIZE (compaction torn
half-way through writing the public split) — and the relaunched run
must publish a clean/quarantine split BYTE-identical to an
uninterrupted oracle, with zero duplicate ``__row_index__`` values,
conserved row counters, and ``engine.egress_rows_replayed`` pinned at
0 (the flush-THEN-cursor ordering means a resume never re-emits a row
the previous attempt already made durable).

The same contract is then driven through every composition surface the
sink now rides: service restart recovery (``VerificationService
.recover()`` over the journal after the whole daemon dies by SIGKILL),
checkpoint-conserving preemption (a solo BATCH egress run is a victim
only when the service has a durable checkpoint plane), and crash
isolation (the spawn child streams the artifact dir directly and a
relaunched child resumes it mid-write).

Every child entry point is module-level (spawn pickles by reference);
crash-once semantics cross the relaunch boundary via fsync'd token
files, never in-memory state — the same discipline as
tests/test_crash_recovery.py.
"""

import functools
import json
import multiprocessing
import os
import pickle
import signal
import types

import numpy as np
import pyarrow.parquet as pq
import pytest

from deequ_tpu import Check, CheckLevel, config
from deequ_tpu.data import Dataset
from deequ_tpu.egress import RowLevelSink
from deequ_tpu.engine.deadline import ManualClock
from deequ_tpu.engine.subproc import (
    CrashLoopError,
    IsolatedRunner,
    checkpoint_progress_probe,
    reset_breakers,
)
from deequ_tpu.service import (
    Priority,
    RunRequest,
    RunState,
    VerificationService,
)
from deequ_tpu.telemetry import get_telemetry


@pytest.fixture(autouse=True)
def _reaped_and_reset():
    reset_breakers()
    yield
    assert multiprocessing.active_children() == []
    reset_breakers()


def _egress_data(n=1000, seed=7):
    """Plain-dict twin of tests/test_egress.py's dataset: nulls in
    ``s`` and out-of-range ``v`` values guarantee BOTH splits are
    non-empty. Picklable, so it crosses the spawn boundary."""
    rng = np.random.default_rng(seed)
    v = rng.integers(0, 120, size=n)
    s = [
        None if rng.random() < 0.08 else f"u{int(x):03d}@ex.com"
        for x in rng.integers(0, 40, size=n)
    ]
    u = rng.integers(0, n // 2, size=n)
    return {
        "v": [int(x) for x in v],
        "s": s,
        "u": [int(x) for x in u],
    }


def _egress_checks(deferred=False, picklable=False):
    check = (
        Check(CheckLevel.ERROR, "durable egress")
        .is_complete("s")
        .satisfies("v < 90", "v_small")
        .where("v >= 10")
    )
    if not picklable:
        # has_pattern holds a closure: fine everywhere except the
        # isolated-service path, whose checks must cross spawn
        check = check.has_pattern("s", r"@ex\.com$")
    checks = [check]
    if deferred:
        checks.append(
            Check(CheckLevel.WARNING, "deferred").is_unique("u")
        )
    return checks


def _split_bytes(out_dir):
    out = {}
    for split in ("clean", "quarantine"):
        path = os.path.join(out_dir, split, "part-00000.parquet")
        with open(path, "rb") as fh:
            out[split] = fh.read()
    return out


def _manifest(out_dir):
    path = os.path.join(out_dir, "manifest.json")
    with open(path, encoding="utf-8") as fh:
        payload = json.load(fh)
    # the split paths embed the per-test tmp dir; everything else must
    # match the oracle exactly
    payload.pop("clean", None)
    payload.pop("quarantine", None)
    return payload


def _assert_exactly_once(out_dir, rows_total):
    """The artifact covers every input row exactly once: no duplicate
    ``__row_index__`` anywhere across the split, no gaps."""
    indexes = []
    for split in ("clean", "quarantine"):
        table = pq.read_table(
            os.path.join(out_dir, split, "part-00000.parquet"),
            columns=["__row_index__"],
        )
        indexes.extend(table.column("__row_index__").to_pylist())
    assert len(indexes) == len(set(indexes)), "duplicate rows emitted"
    assert sorted(indexes) == list(range(rows_total))


# --------------------------------------------------------------------------
# Spawn-child entry points (module level: pickled by reference)
# --------------------------------------------------------------------------


def _crash_once_token(token):
    """Pay ONE hard crash across the relaunch chain: fsync a marker
    before dying so the relaunched child sees the crash already
    happened. Returns only when the crash was already paid."""
    from deequ_tpu.testing.faults import hard_crash

    if token is None or os.path.exists(token):
        return
    with open(token, "x", encoding="utf-8") as fh:
        fh.write("crashed\n")
        fh.flush()
        os.fsync(fh.fileno())
    hard_crash()


def _sink_scan_child(payload):
    """Run one sink-carrying verification in this process, with the
    configured kill point armed (token-gated: the relaunch survives).
    Returns the egress report's counters plus this process's replay
    telemetry — the resumed launch must report zero replayed rows."""
    from deequ_tpu.io import state_provider
    from deequ_tpu.engine.scan import AnalysisEngine
    from deequ_tpu.verification.suite import VerificationSuite

    ds = Dataset.from_pydict(payload["data"])
    token = payload.get("crash_token_path")
    kill = payload.get("kill")
    if kill == "mid_span":
        # die producing batch 7: two batches of rows sit in the OPEN
        # span, past the cursor checkpointed after batch 5
        from deequ_tpu.testing.faults import FaultInjectingDataset

        ds = FaultInjectingDataset(
            ds, crash_at_batch=7, crash_token_path=token
        )
    if kill == "post_flush_pre_cursor":
        # _write_checkpoint flushes the span durably THEN saves the
        # cursor: dying at save entry is exactly the window where the
        # segment exists but no cursor names it — resume must discard
        # the orphaned segment and re-emit it, never double-publish
        real_save = state_provider.ScanCheckpointer.save
        calls = {"n": 0}

        def crashing_save(self, *args, **kwargs):
            calls["n"] += 1
            if calls["n"] == 2:
                _crash_once_token(token)
            return real_save(self, *args, **kwargs)

        state_provider.ScanCheckpointer.save = crashing_save
    if kill == "mid_finalize":
        # die during compaction, AFTER the first segment was already
        # routed into the public split writers: the relaunch finds a
        # torn clean/part file and must wipe it, not append to it
        from deequ_tpu.egress import writer as writer_mod

        real_read = writer_mod.QuarantineWriter._read_segment_payload
        calls = {"n": 0}

        def crashing_read(self, *args, **kwargs):
            calls["n"] += 1
            if calls["n"] == 2:
                _crash_once_token(token)
            return real_read(self, *args, **kwargs)

        writer_mod.QuarantineWriter._read_segment_payload = crashing_read

    sink = RowLevelSink(
        payload["out_dir"],
        tenant=payload.get("tenant", "acme"),
        run_id=payload.get("run_id", "r1"),
    )
    cfg = dict(
        batch_size=104,
        checkpoint_every_batches=3,
        device_cache_bytes=(
            (1 << 30) if payload["mode"] == "resident" else 0
        ),
    )
    with config.configure(**cfg):
        result = VerificationSuite.do_verification_run(
            ds,
            _egress_checks(
                deferred=payload.get("deferred", False),
                picklable=payload.get("picklable", False),
            ),
            engine=AnalysisEngine(
                checkpointer=state_provider.ScanCheckpointer(
                    payload["ckpt_path"]
                )
            ),
            row_level_sink=sink,
        )
    report = result.row_level_egress
    tm = get_telemetry()
    return {
        "status": report.status,
        "rows_total": report.rows_total,
        "rows_clean": report.rows_clean,
        "rows_quarantined": report.rows_quarantined,
        "rows_replayed": tm.counter("engine.egress_rows_replayed").value,
        "spans_flushed": tm.counter("engine.egress_spans_flushed").value,
        "segments_compacted": tm.counter(
            "engine.egress_segments_compacted"
        ).value,
    }


def _egress_service_victim(payload):
    """A whole service daemon that dies by SIGKILL mid-egress: the run
    has streamed two durable span segments (and their cursors) into
    the artifact dir when the kill lands at batch 7. Never returns."""
    from deequ_tpu.testing.faults import FaultInjectingDataset

    ds = FaultInjectingDataset(
        Dataset.from_pydict(payload["data"]),
        crash_at_batch=7,
        crash_signum=signal.SIGKILL,
    )
    svc = VerificationService(
        workers=1, isolated=False, journal_dir=payload["journal_dir"]
    ).start()
    with config.configure(
        checkpoint_every_batches=3, batch_size=104, device_cache_bytes=0
    ):
        handle = svc.submit(
            RunRequest(
                tenant="acme",
                checks=tuple(_egress_checks()),
                dataset=ds,
                row_level_sink=RowLevelSink(
                    payload["out_dir"], tenant="acme", run_id="r1"
                ),
                priority=Priority.STANDARD,
            )
        )
        handle.wait(timeout=120)  # the SIGKILL lands first
    return "unreachable"


def _crashy_dict_factory(data, token):
    """Dataset factory for the ISOLATED service path: runs in the
    spawn child, configures the child's scan geometry (config does not
    cross the spawn boundary), and arms a token-gated hard crash at
    batch 7 — the relaunched child resumes the artifact mid-write."""
    from deequ_tpu.testing.faults import FaultInjectingDataset

    config.set_option(
        batch_size=104, checkpoint_every_batches=3, device_cache_bytes=0
    )
    return FaultInjectingDataset(
        Dataset.from_pydict(data),
        crash_at_batch=7,
        crash_token_path=token,
    )


# --------------------------------------------------------------------------
# SIGKILL at each adversarial point → byte-identical split
# --------------------------------------------------------------------------


@pytest.mark.parametrize("mode", ["resident", "streaming"])
@pytest.mark.parametrize(
    "kill", ["mid_span", "post_flush_pre_cursor", "mid_finalize"]
)
class TestKillPointDifferential:
    def test_kill_then_resume_byte_identical(self, tmp_path, mode, kill):
        data = _egress_data()
        ref_out = str(tmp_path / "ref-out")
        ref = _sink_scan_child(
            {
                "mode": mode,
                "data": data,
                "ckpt_path": str(tmp_path / "ref-ckpt"),
                "out_dir": ref_out,
            }
        )
        assert ref["status"] == "complete"
        assert ref["rows_quarantined"] > 0  # both splits non-trivial

        ckpt_path = str(tmp_path / "ckpt")
        out_dir = str(tmp_path / "out")
        tm = get_telemetry()
        crashes_before = tm.counter("engine.child_crashes").value
        runner = IsolatedRunner(
            key=f"egress:{mode}:{kill}",
            max_relaunches=3,
            timeout_s=300.0,
            progress_probe=checkpoint_progress_probe(ckpt_path),
            use_breaker=False,
        )
        got = runner.run(
            _sink_scan_child,
            {
                "mode": mode,
                "data": data,
                "kill": kill,
                "ckpt_path": ckpt_path,
                "out_dir": out_dir,
                "crash_token_path": str(tmp_path / "crash-token"),
            },
        )
        # the kill actually happened, and one relaunch finished it
        assert (
            tm.counter("engine.child_crashes").value - crashes_before == 1
        )
        assert got["status"] == "complete"
        # the exactly-once pin: the resumed launch re-emitted nothing
        # that was already durable
        assert got["rows_replayed"] == 0
        assert got["rows_total"] == ref["rows_total"] == 1000
        assert got["rows_clean"] == ref["rows_clean"]
        assert got["rows_quarantined"] == ref["rows_quarantined"]
        # the published artifact is BYTE-identical to the oracle's
        assert _split_bytes(out_dir) == _split_bytes(ref_out)
        assert _manifest(out_dir) == _manifest(ref_out)
        _assert_exactly_once(out_dir, got["rows_total"])
        # finalize swept the private spool/segment plane
        assert not os.path.exists(os.path.join(out_dir, "spans"))
        assert not os.path.exists(
            os.path.join(out_dir, "_scan_bits.spool")
        )


class TestDeferredSpoolDurability:
    def test_streaming_deferred_kill_mid_span(self, tmp_path):
        """The deferred-family spool (streaming + is_unique: row bits
        spilled to ``_scan_bits.spool``) rides the same cursor: the
        killed run's spool is truncated to the durable offset on
        resume and the deferred outcomes still match the oracle."""
        data = _egress_data()
        ref_out = str(tmp_path / "ref-out")
        ref = _sink_scan_child(
            {
                "mode": "streaming",
                "data": data,
                "deferred": True,
                "ckpt_path": str(tmp_path / "ref-ckpt"),
                "out_dir": ref_out,
            }
        )
        assert ref["status"] == "complete"
        out_dir = str(tmp_path / "out")
        ckpt_path = str(tmp_path / "ckpt")
        runner = IsolatedRunner(
            key="egress:spool",
            max_relaunches=3,
            timeout_s=300.0,
            progress_probe=checkpoint_progress_probe(ckpt_path),
            use_breaker=False,
        )
        got = runner.run(
            _sink_scan_child,
            {
                "mode": "streaming",
                "data": data,
                "deferred": True,
                "kill": "mid_span",
                "ckpt_path": ckpt_path,
                "out_dir": out_dir,
                "crash_token_path": str(tmp_path / "crash-token"),
            },
        )
        assert got["status"] == "complete"
        assert got["rows_replayed"] == 0
        assert _split_bytes(out_dir) == _split_bytes(ref_out)
        _assert_exactly_once(out_dir, got["rows_total"])


# --------------------------------------------------------------------------
# Service restart recovery: SIGKILLed daemon → recover() → same bytes
# --------------------------------------------------------------------------


class TestServiceRestartRecovery:
    def test_sigkilled_egress_run_recovers_byte_identical(self, tmp_path):
        data = _egress_data()
        journal_dir = str(tmp_path / "journal")
        out_dir = str(tmp_path / "out")
        victim = IsolatedRunner(
            key="egress-victim",
            max_relaunches=1,
            timeout_s=300.0,
            use_breaker=False,
        )
        with pytest.raises(CrashLoopError) as excinfo:
            victim.run(
                _egress_service_victim,
                {
                    "data": data,
                    "journal_dir": journal_dir,
                    "out_dir": out_dir,
                },
            )
        assert excinfo.value.last_signal == "SIGKILL"
        # the durable span plane survived the kill alongside the
        # journal: segments are there for the recovered run to keep
        assert os.path.isdir(os.path.join(out_dir, "spans"))

        oracle_out = str(tmp_path / "oracle-out")
        oracle = _sink_scan_child(
            {
                "mode": "streaming",
                "data": data,
                "ckpt_path": str(tmp_path / "oracle-ckpt"),
                "out_dir": oracle_out,
            }
        )
        tm = get_telemetry()
        resumes_before = tm.counter("engine.resumes").value
        replayed_before = tm.counter("engine.egress_rows_replayed").value
        with config.configure(
            checkpoint_every_batches=3, batch_size=104, device_cache_bytes=0
        ):
            svc = VerificationService(
                workers=1, isolated=False, journal_dir=journal_dir
            )
            recovered = svc.recover(
                resolve=lambda rid, e: RunRequest(
                    tenant=e["tenant"],
                    checks=tuple(_egress_checks()),
                    dataset=Dataset.from_pydict(data),
                    row_level_sink=RowLevelSink(
                        out_dir, tenant="acme", run_id="r1"
                    ),
                )
            )
            assert len(recovered) == 1
            svc.start()
            try:
                handle = recovered[0]
                assert handle.wait(timeout=120)
                assert handle.status == RunState.DONE
                result = handle.result(timeout=0)
            finally:
                svc.stop(drain=False, timeout=10)
        # resumed from the dead daemon's cursor, re-emitting nothing
        assert tm.counter("engine.resumes").value - resumes_before == 1
        assert (
            tm.counter("engine.egress_rows_replayed").value
            == replayed_before
        )
        report = result.row_level_egress
        assert report is not None and report.status == "complete"
        assert report.rows_clean + report.rows_quarantined == 1000
        assert _split_bytes(out_dir) == _split_bytes(oracle_out)
        _assert_exactly_once(out_dir, report.rows_total)
        assert oracle["rows_clean"] == report.rows_clean


# --------------------------------------------------------------------------
# Preemption: sink victims require the durable egress plane
# --------------------------------------------------------------------------


def _sink_ticket():
    from deequ_tpu.service.queue import RunHandle, RunTicket

    handle = RunHandle("run-s", "acme", Priority.BATCH)
    return RunTicket(
        seq=0,
        handle=handle,
        payload=types.SimpleNamespace(row_level_sink=object()),
        budget=None,
    )


class TestPreemptEligibility:
    def test_sink_victim_requires_durable_egress(self):
        from deequ_tpu.service.preempt import PreemptionController

        blind = PreemptionController(clock=ManualClock())
        blind.register([_sink_ticket()])
        # without a durable checkpoint plane a mid-egress preempt
        # would tear the artifact: the sink run is not a victim
        assert blind.preempt_for("needy") is False

        durable = PreemptionController(
            clock=ManualClock(), durable_egress=True
        )
        ticket = _sink_ticket()
        durable.register([ticket])
        assert durable.preempt_for("needy") is True
        assert ticket.preempt_requested is True


class TestPreemptedEgressRun:
    ROWS = 200_000

    def _factory(self):
        rows = self.ROWS

        def factory():
            rng = np.random.default_rng(23)
            return Dataset.from_pydict(
                {
                    "k1": [
                        int(x)
                        for x in rng.integers(0, 1 << 40, rows)
                    ],
                    "v1": [
                        float(x) for x in rng.normal(0, 1, rows)
                    ],
                }
            )

        return factory

    def _batch_checks(self):
        return [
            Check(CheckLevel.ERROR, "preempt-egress")
            .is_complete("k1")
            .satisfies("v1 < 1.5", "v1_bounded")
        ]

    def test_preempted_solo_batch_egress_conserved(self, tmp_path):
        """The composition PR 18 refused: a solo BATCH run CARRYING A
        SINK is preempted by interactive demand, requeued, resumed —
        and the artifact is conserved (identical to an unpreempted
        run, every row exactly once, zero replays)."""
        factory = self._factory()
        tm = get_telemetry()

        def _submit(svc, sink, priority, key):
            return svc.submit(
                RunRequest(
                    tenant="acme",
                    checks=(
                        tuple(self._batch_checks())
                        if priority == Priority.BATCH
                        else (
                            Check(
                                CheckLevel.ERROR, "quick"
                            ).is_complete("k1"),
                        )
                    ),
                    dataset_key=key,
                    dataset_factory=factory,
                    priority=priority,
                    row_level_sink=sink,
                )
            )

        solo_out = str(tmp_path / "solo-out")
        out_dir = str(tmp_path / "out")
        with config.configure(
            batch_size=4096, checkpoint_every_batches=1,
            device_cache_bytes=0,
        ):
            solo_svc = VerificationService(
                workers=1, isolated=False, preemption=True,
                journal_dir=str(tmp_path / "solo-journal"),
            ).start()
            try:
                solo = _submit(
                    solo_svc, RowLevelSink(solo_out), Priority.BATCH,
                    "egress/solo",
                )
                assert solo.wait(timeout=120)
                assert solo.status == RunState.DONE
            finally:
                solo_svc.stop(drain=False, timeout=30)

            preempts_before = tm.counter("service.preemptions").value
            replayed_before = tm.counter(
                "engine.egress_rows_replayed"
            ).value
            svc = VerificationService(
                workers=1, isolated=False, preemption=True,
                journal_dir=str(tmp_path / "journal"),
            ).start()
            try:
                batch = _submit(
                    svc, RowLevelSink(out_dir), Priority.BATCH,
                    "egress/batch",
                )
                assert _spin_until(
                    lambda: batch.status == RunState.RUNNING
                )
                quick = _submit(
                    svc, None, Priority.INTERACTIVE, "egress/quick"
                )
                assert quick.wait(timeout=120)
                assert batch.wait(timeout=120)
                assert batch.status == RunState.DONE
                result = batch.result(timeout=0)
            finally:
                svc.stop(drain=False, timeout=30)

        assert (
            tm.counter("service.preemptions").value - preempts_before
            == 1
        )
        assert (
            tm.counter("engine.egress_rows_replayed").value
            == replayed_before
        )
        report = result.row_level_egress
        assert report is not None and report.status == "complete"
        assert (
            report.rows_clean + report.rows_quarantined == self.ROWS
        )
        solo_report = solo.result(timeout=0).row_level_egress
        assert report.rows_clean == solo_report.rows_clean
        assert report.rows_quarantined == solo_report.rows_quarantined
        assert _split_bytes(out_dir) == _split_bytes(solo_out)
        _assert_exactly_once(out_dir, self.ROWS)


def _spin_until(predicate, timeout_s=60.0):
    import time

    deadline = time.monotonic() + timeout_s
    while not predicate():
        if time.monotonic() > deadline:
            return False
        time.sleep(0.005)
    return True


# --------------------------------------------------------------------------
# Crash isolation: the spawn child streams the artifact directly
# --------------------------------------------------------------------------


class TestIsolatedSinkExecution:
    def test_spawn_child_crash_resumes_the_artifact(self, tmp_path):
        """The composition PR 17 refused: a sink-carrying service run
        executes in the SPAWN CHILD (no inline fallback), the child
        hard-crashes mid-egress, and the relaunched child resumes the
        artifact from the durable cursor — same bytes as an
        uninterrupted run, report re-stamped onto the submitting
        process's sink."""
        data = _egress_data()
        oracle_out = str(tmp_path / "oracle-out")
        oracle = _sink_scan_child(
            {
                "mode": "streaming",
                "data": data,
                "picklable": True,
                "ckpt_path": str(tmp_path / "oracle-ckpt"),
                "out_dir": oracle_out,
            }
        )
        assert oracle["status"] == "complete"

        out_dir = str(tmp_path / "out")
        sink = RowLevelSink(out_dir, tenant="acme", run_id="r1")
        factory = functools.partial(
            _crashy_dict_factory, data, str(tmp_path / "iso-token")
        )
        checks = tuple(_egress_checks(picklable=True))
        # the whole point is the CHILD path: if any of this stopped
        # pickling, the service would fall back inline and the armed
        # crash would kill the test process itself
        pickle.dumps((checks, factory, sink))

        tm = get_telemetry()
        crashes_before = tm.counter("engine.child_crashes").value
        fallbacks_before = tm.counter(
            "service.isolation_inline_fallbacks"
        ).value
        svc = VerificationService(
            workers=1, isolated=True,
            journal_dir=str(tmp_path / "journal"),
        ).start()
        try:
            handle = svc.submit(
                RunRequest(
                    tenant="acme",
                    checks=checks,
                    dataset_key="iso-egress",
                    dataset_factory=factory,
                    row_level_sink=sink,
                )
            )
            assert handle.wait(timeout=300)
            assert handle.status == RunState.DONE
            result = handle.result(timeout=0)
        finally:
            svc.stop(drain=False, timeout=10)
        assert (
            tm.counter("service.isolation_inline_fallbacks").value
            == fallbacks_before
        )
        assert (
            tm.counter("engine.child_crashes").value - crashes_before
            == 1
        )
        report = result.row_level_egress
        assert report is not None and report.status == "complete"
        # the child's report landed on the submitting process's sink
        assert sink.report is report
        assert report.rows_clean == oracle["rows_clean"]
        assert report.rows_quarantined == oracle["rows_quarantined"]
        assert _split_bytes(out_dir) == _split_bytes(oracle_out)
        _assert_exactly_once(out_dir, report.rows_total)
