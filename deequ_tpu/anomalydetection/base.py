"""Anomaly detection over metric time series.

Reference: ``src/main/scala/com/amazon/deequ/anomalydetection/``
(SURVEY.md §2.5): ``AnomalyDetectionStrategy.detect(Vector[DataPoint])``
+ ``AnomalyDetector.isNewPointAnomalous(history, newPoint)``. Pure
host-side numerics over small series — engine-free by design, exactly as
in the reference (L10 sits on the repository, never on data).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Tuple

import numpy as np


@dataclass(frozen=True)
class DataPoint:
    time: int  # epoch millis (ResultKey.dataset_date)
    metric_value: Optional[float]


@dataclass(frozen=True)
class Anomaly:
    value: Optional[float]
    confidence: float
    detail: Optional[str] = None


@dataclass
class DetectionResult:
    anomalies: List[Tuple[int, Anomaly]] = field(default_factory=list)

    @property
    def is_anomalous(self) -> bool:
        return len(self.anomalies) > 0


class AnomalyDetectionStrategy:
    """detect(values, search_interval) -> [(index, Anomaly), ...]"""

    def detect(
        self,
        values: Sequence[float],
        search_interval: Optional[Tuple[int, int]] = None,
    ) -> List[Tuple[int, Anomaly]]:
        raise NotImplementedError


@dataclass
class AnomalyDetector:
    """Orders history by time and asks the strategy about the new point
    (reference: AnomalyDetector.scala)."""

    strategy: AnomalyDetectionStrategy

    def detect_anomalies_in_history(
        self,
        data_points: Sequence[DataPoint],
        search_interval: Optional[Tuple[int, int]] = None,
    ) -> DetectionResult:
        ordered = sorted(
            (p for p in data_points if p.metric_value is not None),
            key=lambda p: p.time,
        )
        values = np.asarray([p.metric_value for p in ordered], dtype=float)
        if search_interval is None:
            search = None
        else:
            lo, hi = search_interval
            search = (
                sum(1 for p in ordered if p.time < lo),
                sum(1 for p in ordered if p.time < hi),
            )
        found = self.strategy.detect(values, search)
        return DetectionResult(
            [(ordered[i].time, a) for i, a in found]
        )

    def is_new_point_anomalous(
        self,
        history: Sequence[DataPoint],
        new_point: DataPoint,
    ) -> DetectionResult:
        if new_point.metric_value is None:
            raise ValueError("new point must carry a metric value")
        history = [p for p in history if p.time < new_point.time]
        all_points = list(history) + [new_point]
        return self.detect_anomalies_in_history(
            all_points, (new_point.time, new_point.time + 1)
        )
