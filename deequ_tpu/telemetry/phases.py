"""PhaseClock: wall-time decomposition buckets for the scan paths.

Lives in the telemetry layer so the engine's hot loop contains no clock
calls of its own (tools/telemetry_lint.py enforces that split — all
attribution comes from ONE place and stays comparable across PRs).

Buckets: ``host_wait_s`` — blocked pulling the staging generator
(source read/convert; on the resident path this also covers the
device_put DISPATCH of chunk staging); ``put_s`` — transfer dispatch
incl. link backpressure; ``dispatch_s`` — jitted step dispatch (the
FIRST step's trace+compile is split out as ``first_step_s`` so a cold
run doesn't read as dispatch overhead); ``sync_s`` — blocked on the
device queue draining.

Attribution caveat (measured, docs/PERF.md): when the host->device link
saturates, backpressure and GIL contention smear waiting across
buckets — the SUM (~= wall) and bytes_shipped/wall are the robust
signals; individual buckets are indicative.
"""

from __future__ import annotations

import contextlib
import time
from typing import Dict, Iterator


class PhaseClock:
    def __init__(self, mode: str):
        self.times: Dict[str, object] = {
            "host_wait_s": 0.0, "put_s": 0.0, "dispatch_s": 0.0,
            "first_step_s": 0.0, "sync_s": 0.0, "mode": mode,
        }
        self._steps = 0

    def timed_iter(self, iterator) -> Iterator:
        """Yield from ``iterator``, accumulating time blocked in its
        ``__next__`` into host_wait_s (keeps the caller a for-loop)."""
        it = iter(iterator)
        while True:
            t0 = time.perf_counter()
            try:
                item = next(it)
            except StopIteration:
                return
            self.times["host_wait_s"] += time.perf_counter() - t0
            yield item

    @contextlib.contextmanager
    def phase(self, key: str) -> Iterator[None]:
        if key == "dispatch_s":
            self._steps += 1
            if self._steps == 1:
                key = "first_step_s"
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.times[key] += time.perf_counter() - t0
