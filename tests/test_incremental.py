"""Incremental/merge correctness: split a fixture across partitions,
compute per-partition states, merge via run_on_aggregated_states, and
assert equality with metrics on the union — the multi-node simulation
(reference: StateAggregationTests / IncrementalAnalysisTest, SURVEY.md §4).
Plus state-provider round-trips (StateProviderTest shape)."""

import numpy as np
import pytest

from deequ_tpu.analyzers import (
    AnalysisRunner,
    Completeness,
    CountDistinct,
    Distinctness,
    Entropy,
    Histogram,
    Maximum,
    Mean,
    Minimum,
    Size,
    StandardDeviation,
    Sum,
    Uniqueness,
)
from deequ_tpu.data import Dataset
from deequ_tpu.io import FileSystemStateProvider, InMemoryStateProvider
from fixtures import big_numeric, df_missing


ANALYZERS = [
    Size(),
    Completeness("att1"),
    Completeness("att2"),
    Distinctness("att1"),
    Uniqueness("att1"),
    CountDistinct("att2"),
    Entropy("att1"),
    Histogram("att2"),
]


def _split(dataset: Dataset, parts: int):
    n = dataset.num_rows
    bounds = np.linspace(0, n, parts + 1).astype(int)
    out = []
    for lo, hi in zip(bounds[:-1], bounds[1:]):
        mask = np.zeros(n, dtype=bool)
        mask[lo:hi] = True
        out.append(dataset.filter_rows(mask))
    return out


def _assert_metric_equal(ma, mb, analyzer):
    assert ma.value.is_success == mb.value.is_success, analyzer
    if not ma.value.is_success:
        return
    a, b = ma.value.get(), mb.value.get()
    if isinstance(a, float):
        assert a == pytest.approx(b), analyzer
    else:  # distributions
        assert a == b, analyzer


def test_partitioned_states_merge_to_global():
    data = df_missing()
    providers = []
    for part in _split(data, 3):
        provider = InMemoryStateProvider()
        AnalysisRunner.do_analysis_run(
            part, ANALYZERS, save_states_with=provider
        )
        providers.append(provider)

    merged = AnalysisRunner.run_on_aggregated_states(
        data.schema, ANALYZERS, providers
    )
    full = AnalysisRunner.do_analysis_run(data, ANALYZERS)
    for analyzer in ANALYZERS:
        _assert_metric_equal(
            merged.metric(analyzer), full.metric(analyzer), analyzer
        )


def test_numeric_states_merge_to_global():
    data = big_numeric(20_000)
    analyzers = [
        Mean("x"),
        Sum("x"),
        Minimum("x"),
        Maximum("x"),
        StandardDeviation("x"),
    ]
    providers = []
    for part in _split(data, 4):
        provider = InMemoryStateProvider()
        AnalysisRunner.do_analysis_run(
            part, analyzers, save_states_with=provider
        )
        providers.append(provider)
    merged = AnalysisRunner.run_on_aggregated_states(
        data.schema, analyzers, providers
    )
    full = AnalysisRunner.do_analysis_run(data, analyzers)
    for analyzer in analyzers:
        a = merged.metric(analyzer).value.get()
        b = full.metric(analyzer).value.get()
        assert a == pytest.approx(b, rel=1e-9), analyzer


def test_aggregate_with_prior_states():
    """aggregate_with: new data merged with persisted prior state."""
    data = df_missing()
    part_a, part_b = _split(data, 2)
    provider = InMemoryStateProvider()
    analyzers = [Size(), Completeness("att1")]
    AnalysisRunner.do_analysis_run(
        part_a, analyzers, save_states_with=provider
    )
    ctx = AnalysisRunner.do_analysis_run(
        part_b, analyzers, aggregate_with=provider
    )
    assert ctx.metric(Size()).value.get() == 12.0
    assert ctx.metric(Completeness("att1")).value.get() == 10 / 12


def test_filesystem_state_roundtrip(tmp_path):
    data = df_missing()
    provider = FileSystemStateProvider(str(tmp_path))
    AnalysisRunner.do_analysis_run(
        data, ANALYZERS, save_states_with=provider
    )
    reloaded = FileSystemStateProvider(str(tmp_path))
    merged = AnalysisRunner.run_on_aggregated_states(
        data.schema, ANALYZERS, [reloaded]
    )
    full = AnalysisRunner.do_analysis_run(data, ANALYZERS)
    for analyzer in ANALYZERS:
        _assert_metric_equal(
            merged.metric(analyzer), full.metric(analyzer), analyzer
        )
