"""Exact metrics on a high-cardinality id column + a warehouse-style
metrics table.

Two round-3 capabilities in one runnable example:

1. CountDistinct / Uniqueness on a multi-million-cardinality int64
   column run ENTIRELY on device (sort + segment count — no host-side
   dictionary is ever built; see analyzers/spill.py). The run metadata
   records which execution path each grouping plan took.
2. Results land in a TableMetricsRepository — an append-only parquet
   dataset directory (the SparkTableMetricsRepository analog): several
   writers can append concurrently, and time-travel/tag queries read it
   back like any warehouse table.
"""

import os
import sys
import tempfile

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

import numpy as np
import pyarrow as pa

from deequ_tpu import Dataset, TableMetricsRepository
from deequ_tpu.analyzers import (
    AnalysisRunner,
    CountDistinct,
    Distinctness,
    Uniqueness,
)
from deequ_tpu.repository.base import ResultKey


def main() -> None:
    rng = np.random.default_rng(1)
    n = 2_000_000
    ds = Dataset.from_arrow(
        pa.table({"order_id": rng.integers(0, 1 << 40, n, dtype=np.int64)})
    )
    analyzers = [
        CountDistinct("order_id"),
        Uniqueness("order_id"),
        Distinctness("order_id"),
    ]

    repo_dir = tempfile.mkdtemp(prefix="deequ_tpu_metrics_tbl_")
    repo = TableMetricsRepository(repo_dir)

    for day, tag in ((20260729, "ingest-a"), (20260730, "ingest-b")):
        ctx = AnalysisRunner.on_data(ds).add_analyzers(analyzers).\
            use_repository(repo).\
            save_or_append_result(ResultKey.of(day, {"source": tag})).run()
        spills = [
            e
            for e in ctx.run_metadata.events
            if e["event"] == "grouping_spill"
        ]
        print(f"day {day}: paths {[e['path'] for e in spills]}")
        for a in analyzers:
            print(f"  {a.name:>14}: {ctx.metric(a).value.get():,.4f}")

    # warehouse-style readback: time travel + tag filter
    loaded = (
        repo.load()
        .after(20260729)
        .with_tag_values({"source": "ingest-b"})
        .get_success_metrics_as_records()
    )
    print(f"repository query returned {len(loaded)} metric records")
    assert any(r["name"] == "CountDistinct" for r in loaded)


if __name__ == "__main__":
    main()
