"""ConstraintSuggestionRunner: profile -> rules -> suggestions, with
optional train/test evaluation.

Reference: ``suggestions/ConstraintSuggestionRunner.scala`` (SURVEY.md
§2.5, §3.4): profile the (train split of the) data, apply every rule to
every column profile, and optionally verify the suggested constraints on
a holdout split (``useTrainTestSplitWithTestsetRatio``).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import numpy as np

from deequ_tpu.checks.check import Check, CheckLevel
from deequ_tpu.data.table import Dataset
from deequ_tpu.engine.scan import AnalysisEngine
from deequ_tpu.profiles.profiler import ColumnProfiler, ColumnProfiles
from deequ_tpu.sketches.kll import KLLParameters
from deequ_tpu.suggestions.rules import ConstraintRule, ConstraintSuggestion
from deequ_tpu.verification.suite import VerificationResult, VerificationSuite


@dataclass
class ConstraintSuggestionResult:
    column_profiles: ColumnProfiles
    constraint_suggestions: Dict[str, List[ConstraintSuggestion]] = field(
        default_factory=dict
    )
    verification_result: Optional[VerificationResult] = None

    def all_suggestions(self) -> List[ConstraintSuggestion]:
        return [
            s for group in self.constraint_suggestions.values() for s in group
        ]


class ConstraintSuggestionRunner:
    def on_data(self, data: Dataset) -> "ConstraintSuggestionRunBuilder":
        return ConstraintSuggestionRunBuilder(data)


class ConstraintSuggestionRunBuilder:
    def __init__(self, data: Dataset):
        self._data = data
        self._rules: List[ConstraintRule] = []
        self._restrict_to_columns: Optional[Sequence[str]] = None
        self._low_cardinality_threshold: Optional[int] = None
        self._kll_profiling = False
        self._kll_parameters: Optional[KLLParameters] = None
        self._testset_ratio: Optional[float] = None
        self._testset_seed: int = 42
        self._engine: Optional[AnalysisEngine] = None

    def add_constraint_rule(
        self, rule: ConstraintRule
    ) -> "ConstraintSuggestionRunBuilder":
        self._rules.append(rule)
        return self

    def add_constraint_rules(
        self, rules: Sequence[ConstraintRule]
    ) -> "ConstraintSuggestionRunBuilder":
        self._rules.extend(rules)
        return self

    def restrict_to_columns(
        self, columns: Sequence[str]
    ) -> "ConstraintSuggestionRunBuilder":
        self._restrict_to_columns = list(columns)
        return self

    def with_low_cardinality_histogram_threshold(
        self, threshold: int
    ) -> "ConstraintSuggestionRunBuilder":
        self._low_cardinality_threshold = threshold
        return self

    def with_kll_profiling(
        self, kll_parameters: Optional[KLLParameters] = None
    ) -> "ConstraintSuggestionRunBuilder":
        self._kll_profiling = True
        self._kll_parameters = kll_parameters
        return self

    def use_train_test_split_with_testset_ratio(
        self, testset_ratio: float, seed: int = 42
    ) -> "ConstraintSuggestionRunBuilder":
        if not 0.0 < testset_ratio < 1.0:
            raise ValueError("testset_ratio must be in (0, 1)")
        self._testset_ratio = testset_ratio
        self._testset_seed = seed
        return self

    def with_engine(
        self, engine: AnalysisEngine
    ) -> "ConstraintSuggestionRunBuilder":
        self._engine = engine
        return self

    def run(self) -> ConstraintSuggestionResult:
        train, test = self._split()
        from deequ_tpu.profiles.profiler import (
            DEFAULT_LOW_CARDINALITY_THRESHOLD,
        )

        profiles = ColumnProfiler.profile(
            train,
            restrict_to_columns=self._restrict_to_columns,
            low_cardinality_histogram_threshold=(
                self._low_cardinality_threshold
                or DEFAULT_LOW_CARDINALITY_THRESHOLD
            ),
            kll_profiling=self._kll_profiling,
            kll_parameters=self._kll_parameters,
            engine=self._engine,
        )
        suggestions: Dict[str, List[ConstraintSuggestion]] = {}
        for column, profile in profiles.profiles.items():
            for rule in self._rules:
                try:
                    if rule.should_be_applied(profile, profiles.num_records):
                        suggestions.setdefault(column, []).append(
                            rule.candidate(profile, profiles.num_records)
                        )
                except Exception:  # noqa: BLE001 — a rule must not kill the run
                    continue

        verification_result = None
        if test is not None and any(suggestions.values()):
            check = Check(
                CheckLevel.WARNING, "Suggested constraints (holdout eval)"
            )
            for group in suggestions.values():
                for suggestion in group:
                    check = suggestion.apply_to_check(check)
            verification_result = (
                VerificationSuite()
                .on_data(test)
                .add_check(check)
                .run()
            )
        return ConstraintSuggestionResult(
            profiles, suggestions, verification_result
        )

    def _split(self):
        if self._testset_ratio is None:
            return self._data, None
        rng = np.random.default_rng(self._testset_seed)
        n = self._data.num_rows
        test_mask = rng.random(n) < self._testset_ratio
        return (
            self._data.filter_rows(~test_mask),
            self._data.filter_rows(test_mask),
        )
