"""Pluggable byte storage for state providers and metric repositories.

Reference: deequ's ``HdfsStateProvider`` / repository paths accept any
Hadoop filesystem URI — S3, HDFS, local — resolved by the FileSystem
registry (SURVEY.md §2.2 StateProvider row; VERDICT r3 missing #5).
This module is the TPU-stack analog: a minimal :class:`Storage`
protocol (atomic-visibility writes, reads, listing) plus a URI-scheme
registry, so ``FileSystemStateProvider("s3://bucket/states")`` routes
through whatever backend the deployment registers, while plain local
paths keep the direct, zero-overhead os-path implementation.

Backends in-tree:

- ``LocalStorage`` — the default for plain paths and ``file://``;
  writes are temp-file + ``os.replace`` (atomic visibility, matching
  the repository/table.py discipline);
- ``MemoryStorage`` (``mem://``) — an in-process dict, used by tests
  to exercise every remote-path branch without a cloud SDK, and handy
  as a scratch repository.

Cloud SDKs are not baked into this image, so S3/GCS/HDFS classes are
NOT shipped; deployments register one in a few lines:

    from deequ_tpu.io.storage import Storage, register_storage_scheme

    class S3Storage(Storage):
        def __init__(self, uri): ...  # boto3 client
        ...

    register_storage_scheme("s3", S3Storage)

after which every state provider / repository accepts ``s3://`` URIs.
"""

from __future__ import annotations

import contextlib
import os
import threading
from typing import Callable, Dict, Iterator, List, Optional


class Storage:
    """Byte-blob storage under a base location. Keys are '/'-relative
    names (no scheme); implementations must give ``write_bytes``
    atomic VISIBILITY (a concurrent ``read_bytes``/``list_keys`` sees
    either the whole blob or nothing). ``durable=True`` additionally
    asks for crash DURABILITY: the blob must survive power loss once
    the call returns (fsync on local disks); backends without a
    stronger guarantee may ignore it."""

    def read_bytes(self, key: str) -> Optional[bytes]:
        raise NotImplementedError

    def write_bytes(
        self, key: str, data: bytes, durable: bool = False
    ) -> None:
        raise NotImplementedError

    def list_keys(self, prefix: str = "") -> List[str]:
        raise NotImplementedError

    def exists(self, key: str) -> bool:
        return self.read_bytes(key) is not None

    def delete(self, key: str) -> None:
        """Remove a blob; deleting a missing key is a no-op (checkpoint
        cleanup must be idempotent)."""
        raise NotImplementedError


class LocalStorage(Storage):
    """Plain directory storage (the default)."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _full(self, key: str) -> str:
        return os.path.join(self.root, *key.split("/"))

    def read_bytes(self, key: str) -> Optional[bytes]:
        try:
            with open(self._full(key), "rb") as fh:
                return fh.read()
        except FileNotFoundError:
            return None

    def write_bytes(
        self, key: str, data: bytes, durable: bool = False
    ) -> None:
        full = self._full(key)
        os.makedirs(os.path.dirname(full), exist_ok=True)
        tmp = f"{full}.tmp.{os.getpid()}.{threading.get_ident()}"
        try:
            with open(tmp, "wb") as fh:
                fh.write(data)
                if durable:
                    # survive power loss, not just process death: the
                    # rename below orders only METADATA — without an
                    # fsync of the data first, a crash can leave the
                    # new name pointing at zero-length garbage
                    fh.flush()
                    os.fsync(fh.fileno())
            os.replace(tmp, full)  # atomic visibility
            if durable:
                # the rename itself lives in the directory: fsync it
                # too, or the replace may not survive the crash. Some
                # filesystems refuse O_RDONLY directory fsync — treat
                # that as "as durable as this FS gets", not an error.
                try:
                    dir_fd = os.open(
                        os.path.dirname(full) or ".", os.O_RDONLY
                    )
                    try:
                        os.fsync(dir_fd)
                    finally:
                        os.close(dir_fd)
                except OSError:
                    pass
        finally:
            if os.path.exists(tmp):  # failed write: no orphan
                os.unlink(tmp)

    def list_keys(self, prefix: str = "") -> List[str]:
        # walk only the subtree the prefix's directory part names: a
        # fleet lease listing ("leases/lease-...") must not pay for
        # sibling trees like <fleet_dir>/checkpoints, whose file count
        # grows with every run — fence checks sit on persist paths
        base = self.root
        if "/" in prefix:
            sub = prefix.rsplit("/", 1)[0]
            base = os.path.join(self.root, *sub.split("/"))
            if not os.path.isdir(base):
                return []
        out = []
        for dirpath, _dirs, files in os.walk(base):
            for name in files:
                rel = os.path.relpath(
                    os.path.join(dirpath, name), self.root
                ).replace(os.sep, "/")
                # skip this class's own in-flight temps
                # (<key>.tmp.<pid>.<tid>) and bare .tmp files
                if ".tmp." in rel or rel.endswith(".tmp"):
                    continue
                if rel.startswith(prefix):
                    out.append(rel)
        return sorted(out)

    def exists(self, key: str) -> bool:
        return os.path.exists(self._full(key))

    def delete(self, key: str) -> None:
        try:
            os.unlink(self._full(key))
        except FileNotFoundError:
            pass


def durable_replace(tmp_path: str, final_path: str) -> None:
    """Atomically and DURABLY publish ``tmp_path`` (a fully written
    file) as ``final_path``: fsync the data, rename, fsync the parent
    directory — the same discipline as ``LocalStorage.write_bytes(
    durable=True)``, for callers that stream a file to disk (the
    egress span segments) instead of holding bytes in memory. After
    return the file survives power loss under its final name; some
    filesystems refuse directory fsync, which is treated as "as
    durable as this FS gets", not an error."""
    with open(tmp_path, "rb+") as fh:
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp_path, final_path)
    try:
        dir_fd = os.open(os.path.dirname(final_path) or ".", os.O_RDONLY)
        try:
            os.fsync(dir_fd)
        finally:
            os.close(dir_fd)
    except OSError:
        pass


class MemoryStorage(Storage):
    """In-process storage (``mem://name``): one shared namespace per
    URI, thread-safe — the remote-backend stand-in for tests."""

    _spaces: Dict[str, Dict[str, bytes]] = {}
    _lock = threading.Lock()

    def __init__(self, uri: str):
        name = uri.split("://", 1)[1]
        with MemoryStorage._lock:
            self._blobs = MemoryStorage._spaces.setdefault(name, {})

    def read_bytes(self, key: str) -> Optional[bytes]:
        with MemoryStorage._lock:
            return self._blobs.get(key)

    def write_bytes(
        self, key: str, data: bytes, durable: bool = False
    ) -> None:
        del durable  # process memory: no stronger guarantee exists
        with MemoryStorage._lock:
            self._blobs[key] = bytes(data)

    def list_keys(self, prefix: str = "") -> List[str]:
        with MemoryStorage._lock:
            return sorted(
                k for k in self._blobs if k.startswith(prefix)
            )

    def exists(self, key: str) -> bool:
        with MemoryStorage._lock:
            return key in self._blobs

    def delete(self, key: str) -> None:
        with MemoryStorage._lock:
            self._blobs.pop(key, None)


_SCHEMES: Dict[str, Callable[[str], Storage]] = {}


def register_storage_scheme(
    scheme: str, factory: Callable[[str], Storage]
) -> None:
    """Register ``factory(uri) -> Storage`` for ``scheme://`` URIs."""
    _SCHEMES[scheme.lower()] = factory


register_storage_scheme("mem", MemoryStorage)
register_storage_scheme(
    "file", lambda uri: LocalStorage(uri.split("://", 1)[1])
)


@contextlib.contextmanager
def interprocess_lock(path: str) -> Iterator[None]:
    """Cross-process advisory lock via ``fcntl.flock`` on a sidecar
    lock file (blocks until acquired; released on exit or process
    death — the kernel drops flocks with the fd). Two PROCESSES doing
    read-modify-write on a shared repository file serialize through
    this; a ``threading.Lock`` alone cannot see across fork/exec.
    No-ops on platforms without ``fcntl`` (Windows), where the
    in-process lock remains the only guarantee."""
    try:
        import fcntl
    except ImportError:  # pragma: no cover — non-POSIX
        yield
        return
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    fd = os.open(path, os.O_RDWR | os.O_CREAT, 0o644)
    try:
        fcntl.flock(fd, fcntl.LOCK_EX)
        try:
            yield
        finally:
            fcntl.flock(fd, fcntl.LOCK_UN)
    finally:
        os.close(fd)


#: serializes compare_and_swap for backends that have no filesystem to
#: flock (MemoryStorage): one process-wide lock is exactly the scope a
#: mem:// namespace has
_cas_memory_lock = threading.Lock()


def compare_and_swap(
    path_or_uri: str,
    key: str,
    expected: Optional[bytes],
    new: bytes,
) -> bool:
    """Atomic read-compare-write of one blob: publish ``new`` under
    ``key`` only if the blob currently holds exactly ``expected``
    (``None`` = the key must not exist), returning whether the swap
    won. This is the fleet lease primitive (service/fleet.py): two
    survivors racing to adopt a dead replica's epoch both CAS the same
    lease key and exactly one returns True.

    Linearization: LocalStorage serializes through an
    ``interprocess_lock`` sidecar next to the root (flock — kernel
    drops it on process death, so a crashed CAS holder never wedges
    the fleet); MemoryStorage serializes on a process-wide lock (its
    namespace cannot outlive the process anyway). The winning write is
    durable (fsync + dir fsync on local disks) — a lease that a peer
    acted on must survive power loss."""
    storage = storage_for(path_or_uri)
    if isinstance(storage, LocalStorage):
        lock_ctx = interprocess_lock(
            os.path.join(storage.root, ".cas.lock")
        )
    else:
        lock_ctx = _cas_memory_lock
    with lock_ctx:
        current = storage.read_bytes(key)
        if current != expected:
            return False
        storage.write_bytes(key, new, durable=True)
        return True


def storage_for(path_or_uri: str) -> Storage:
    """Resolve a path/URI to a Storage backend: plain paths use
    LocalStorage; ``scheme://`` URIs dispatch through the registry,
    with a deployment-pointing error for unregistered schemes."""
    if "://" in path_or_uri:
        scheme = path_or_uri.split("://", 1)[0].lower()
        factory = _SCHEMES.get(scheme)
        if factory is None:
            raise ValueError(
                f"no storage backend registered for {scheme}://; "
                "register one via deequ_tpu.io.storage."
                "register_storage_scheme (see the module docstring "
                "for the S3 sketch)"
            )
        return factory(path_or_uri)
    return LocalStorage(path_or_uri)
