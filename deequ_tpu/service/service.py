"""VerificationService: the always-on, multi-tenant facade.

Composition (docs/SERVICE.md has the architecture picture):

- ``submit()`` validates quotas, wraps the suite in a ``RunTicket``
  (deadline budget pinned at submit — queue wait burns it, matching
  the admission controller), and returns a ``RunHandle``;
- the ``Scheduler``'s workers pop by priority and drive the run
  through ``VerificationSuite.do_verification_run`` — i.e. through
  the runner's admission layer (``max_concurrent_runs`` +
  ``memory_watermark_bytes`` still gate device admission underneath;
  the service NEVER calls ``engine.run_scan`` directly, enforced by
  tools/telemetry_lint.py);
- the shared ``DatasetCache`` hands every run of the same table the
  same resident handle (one ``device_put`` for N tenants), pinned for
  the run's duration;
- ``warmup()`` precompiles the submitted suites' fused plans at
  startup via the ``tools/warmup.py`` machinery and records the warmed
  plan tokens in the ``PlanCache`` ledger, so steady state shows zero
  recompiles.

Shutdown: ``stop(drain=True)`` finishes queued work; ``drain(reason)``
(also wired to SIGTERM when ``start(install_sigterm=True)``) cancels
QUEUED runs cleanly while RUNNING runs finish under the engine's
graceful-shutdown supervision — checkpointed, partial metrics, the
same contract as a direct bounded run.
"""

from __future__ import annotations

import collections
import pickle
import threading
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Sequence

from deequ_tpu.engine.deadline import (
    MonotonicClock,
    RunBudget,
    shutdown_token,
)
from deequ_tpu.engine.subproc import CrashLoopError, IsolatedRunner
from deequ_tpu.io.state_provider import ScanCheckpointer
from deequ_tpu.service.caches import DatasetCache, PlanCache
from deequ_tpu.service.journal import RunJournal
from deequ_tpu.service.queue import (
    Priority,
    QuotaExceeded,
    RunHandle,
    RunQueue,
    RunState,
    RunTicket,
)
from deequ_tpu.service.fleet import epoch_fence_check
from deequ_tpu.service.preempt import run_cancel_token
from deequ_tpu.service.scheduler import Scheduler
from deequ_tpu.telemetry import get_telemetry


class ServiceOverloaded(RuntimeError):
    """A BATCH submission was shed at the edge (queue depth or crash
    rate over the ``service_shed_*`` thresholds). ``retry_after_s`` is
    the caller's resubmission hint — failing FAST with a hint beats
    accepting work that will deadline-expire silently in the queue."""

    def __init__(self, message: str, *, retry_after_s: float):
        super().__init__(message)
        self.retry_after_s = max(0.0, float(retry_after_s))


@dataclass
class RunRequest:
    """One suite submission. ``dataset_key`` + ``dataset_factory``
    address the shared dataset cache (same key -> same resident
    handle); pass a ``dataset`` directly to bypass sharing (it becomes
    a single-use factory keyed by object id)."""

    tenant: str
    checks: Sequence[Any]
    dataset_key: Optional[str] = None
    dataset_factory: Optional[Callable[[], Any]] = None
    dataset: Optional[Any] = None
    required_analyzers: Sequence[Any] = ()
    priority: int = Priority.STANDARD
    deadline_s: Optional[float] = None
    metrics_repository: Any = None
    result_key: Any = None
    #: egress.RowLevelSink — stream this run's row-level outcomes to a
    #: clean/quarantine parquet split (docs/EGRESS.md). Sink runs never
    #: coalesce (the artifact is per-run) but otherwise ride the full
    #: resilience stack: they checkpoint/resume through the durable
    #: span segments, execute in the spawn child under crash isolation
    #: (the child writes the artifact dir directly and streams egress
    #: progress frames back), and are preemptible when the service has
    #: a checkpoint path (docs/EGRESS.md "Durable egress").
    row_level_sink: Any = None
    #: explicit device-footprint estimate (bytes) for the elastic
    #: placement policy; None = derive from ``dataset`` at admit when
    #: one was passed (factory-only submissions place at the policy's
    #: default slice unless this is set)
    estimated_bytes: Optional[int] = None

    def __post_init__(self):
        if self.dataset is not None and self.dataset_factory is None:
            ds = self.dataset
            self.dataset_factory = lambda: ds
            if self.dataset_key is None:
                # content-derived default: two submissions of the SAME
                # in-memory table share the cache entry and may
                # coalesce — an id()-based key defeated both (every
                # rebuilt Dataset object was its own cache universe)
                try:
                    self.dataset_key = f"dataset-{ds.fingerprint()}"
                except Exception:  # noqa: BLE001 — unfingerprintable
                    self.dataset_key = f"dataset-{id(ds):x}"
        if self.dataset_key is None or self.dataset_factory is None:
            raise ValueError(
                "RunRequest needs dataset_key + dataset_factory "
                "(or a dataset)"
            )


class VerificationService:
    """Long-lived multi-tenant verification daemon. All knobs default
    from ``config.options()`` (service_* options); ``clock`` is
    injectable for fake-time tests and drives every scheduling
    decision."""

    def __init__(
        self,
        workers: Optional[int] = None,
        interactive_reserve: Optional[int] = None,
        clock: Any = None,
        dataset_watermark_bytes: Optional[int] = None,
        tenant_max_pending: Optional[int] = None,
        tenant_max_active: Optional[int] = None,
        execute: Optional[Callable[[RunTicket], Any]] = None,
        journal_dir: Optional[str] = None,
        isolated: Optional[bool] = None,
        shed_queue_depth: Optional[int] = None,
        shed_crash_rate: Optional[int] = None,
        shed_crash_window_s: Optional[float] = None,
        coalesce: Optional[bool] = None,
        coalesce_window_s: Optional[float] = None,
        coalesce_max_members: Optional[int] = None,
        execute_group: Optional[
            Callable[[List[RunTicket]], List[Any]]
        ] = None,
        elastic_placement: Optional[bool] = None,
        placer: Optional[Any] = None,
        trace: Optional[bool] = None,
        metrics_port: Optional[int] = None,
        slo_objectives: Optional[str] = None,
        preemption: Optional[bool] = None,
        autoscale: Optional[bool] = None,
        process_label: str = "",
        fleet_dir: Optional[str] = None,
        replica_id: Optional[str] = None,
        adopt_resolve: Optional[Callable[[Dict[str, Any]], Any]] = None,
    ):
        import os

        from deequ_tpu import config

        opts = config.options()
        self.clock = clock or MonotonicClock()
        # end-to-end tracing (docs/OBSERVABILITY.md "Tracing"): when on,
        # the queue mints a TraceContext per submission and the
        # scheduler/engine/spawn layers hang the run's span tree off it
        self.trace_enabled = bool(
            opts.service_trace if trace is None else trace
        )
        self.process_label = process_label
        # live plane: explicit metrics_port serves (0 = ephemeral bind);
        # None defers to config, where 0 means NO endpoint thread
        self._metrics_port: Optional[int] = (
            int(metrics_port)
            if metrics_port is not None
            else (
                int(opts.service_metrics_port)
                if opts.service_metrics_port > 0
                else None
            )
        )
        self.metrics_server: Optional[Any] = None
        # per-class/per-tenant latency SLOs over the queue-wait
        # histograms; "" = no tracker, no snapshot persistence
        slo_spec = (
            opts.service_slo_objectives
            if slo_objectives is None
            else slo_objectives
        )
        self.slo: Optional[Any] = None
        if slo_spec:
            from deequ_tpu.telemetry import SloTracker, parse_slo_objectives

            objectives = parse_slo_objectives(slo_spec)
            if objectives:
                self.slo = SloTracker(objectives)
        journal_dir = (
            journal_dir
            if journal_dir is not None
            else opts.service_journal_dir
        )
        self.journal: Optional[RunJournal] = (
            RunJournal(journal_dir) if journal_dir else None
        )
        self._checkpoint_path: Optional[str] = (
            journal_dir.rstrip("/") + "/checkpoints" if journal_dir else None
        )
        # fleet failover (docs/SERVICE.md "Fleet failover"): a shared
        # fleet dir turns this replica into a fleet member — heartbeat
        # lease, peer watch, orphan adoption, epoch fencing. Requires a
        # journal (the journal IS what a peer adopts); checkpoints move
        # to the SHARED fleet dir so an adopted run's durable cursors
        # are readable by whichever replica resumes it.
        fleet_dir = (
            fleet_dir if fleet_dir is not None else opts.service_fleet_dir
        )
        self.fleet: Optional[Any] = None
        self._adopt_resolve = adopt_resolve
        self._adopted_handles: List[RunHandle] = []
        #: journal dirs whose adoption replay is on the current call
        #: stack — finishing a dead adopter's intents re-enters
        #: ``_adopt_replica``, and a cyclic intent graph (two dead
        #: adopters pointing at each other) must not recurse forever
        self._adopting: set = set()
        if fleet_dir and self.journal is not None:
            from deequ_tpu.service.fleet import FleetSupervisor

            self._checkpoint_path = (
                fleet_dir.rstrip("/") + "/checkpoints"
            )
            replica = (
                replica_id
                or opts.service_fleet_replica
                or f"replica-{os.getpid()}"
            )
            self.fleet = FleetSupervisor(
                fleet_dir,
                replica,
                journal_dir=journal_dir,
                clock=self.clock,
                heartbeat_s=opts.service_fleet_heartbeat_s,
                lease_timeout_s=opts.service_fleet_lease_timeout_s,
                poison_replicas=opts.service_fleet_poison_replicas,
                on_adopt=self._adopt_replica,
                on_adopt_intent=self._journal_adopt_intent,
                on_adopt_lost=self._journal_adopt_lost,
            )
            self.journal.record_epoch(
                replica, self.fleet.epoch, reason="register"
            )
        self.isolated = (
            bool(opts.isolated_execution) if isolated is None else bool(isolated)
        )
        self.shed_queue_depth = int(
            opts.service_shed_queue_depth
            if shed_queue_depth is None
            else shed_queue_depth
        )
        self.shed_crash_rate = int(
            opts.service_shed_crash_rate
            if shed_crash_rate is None
            else shed_crash_rate
        )
        self.shed_crash_window_s = float(
            opts.service_shed_crash_window_s
            if shed_crash_window_s is None
            else shed_crash_window_s
        )
        self._crash_times: collections.deque = collections.deque()
        self._crash_lock = threading.Lock()
        watermark = (
            dataset_watermark_bytes
            if dataset_watermark_bytes is not None
            else (
                opts.service_dataset_watermark_bytes
                or opts.device_cache_bytes
            )
        )
        self.datasets = DatasetCache(watermark_bytes=watermark)
        self.plans = PlanCache()
        self.queue = RunQueue(
            clock=self.clock,
            tenant_max_pending=(
                tenant_max_pending
                if tenant_max_pending is not None
                else opts.service_tenant_max_pending
            ),
            tenant_max_active=(
                tenant_max_active
                if tenant_max_active is not None
                else opts.service_tenant_max_active
            ),
            trace_enabled=self.trace_enabled,
            process_label=self.process_label,
        )
        # scan coalescing (docs/SERVICE.md "Scan coalescing"): opt-in;
        # the group executor defaults to the service's own ONLY when
        # the solo executor is also the service's own — an injected
        # `execute=` stub (fake-clock tests) keeps strict solo
        # semantics unless it injects `execute_group=` too
        coalesce_on = bool(
            opts.service_coalesce if coalesce is None else coalesce
        )
        if execute_group is None and execute is None:
            execute_group = self._execute_group
        self.coalesce_policy = None
        if coalesce_on and execute_group is not None:
            from deequ_tpu.service.coalesce import CoalescePolicy

            self.coalesce_policy = CoalescePolicy(
                enabled=True,
                window_s=float(
                    opts.service_coalesce_window_s
                    if coalesce_window_s is None
                    else coalesce_window_s
                ),
                max_members=int(
                    opts.service_coalesce_max_members
                    if coalesce_max_members is None
                    else coalesce_max_members
                ),
            )
        # elastic device placement (docs/SERVICE.md "Elastic
        # placement"): opt-in like coalescing; an injected placer wins
        # over the flag (fake-pool tests)
        elastic_on = bool(
            opts.service_elastic_placement
            if elastic_placement is None
            else elastic_placement
        )
        self.placer = placer
        if self.placer is None and elastic_on:
            from deequ_tpu.service.placement import ElasticPlacer

            self.placer = ElasticPlacer(clock=self.clock)
        # checkpoint-conserving preemption (docs/SERVICE.md "Preemption
        # and autoscaling"): opt-in; OFF (the default) keeps the
        # scheduler/queue paths bit-identical to the pre-preemption
        # service — no controller, no per-attempt tokens, no skips
        preempt_on = bool(
            opts.service_preemption if preemption is None else preemption
        )
        self.preemption = None
        if preempt_on:
            from deequ_tpu.service.preempt import PreemptionController

            self.preemption = PreemptionController(
                clock=self.clock,
                max_preemptions_per_run=(
                    opts.service_preempt_max_per_run
                ),
                # sink runs are admissible victims only when their
                # egress cursor is durable (checkpointing service)
                durable_egress=self._checkpoint_path is not None,
            )
        self.scheduler = Scheduler(
            self.queue,
            execute if execute is not None else self._execute,
            workers=(
                workers if workers is not None else opts.service_workers
            ),
            interactive_reserve=(
                interactive_reserve
                if interactive_reserve is not None
                else opts.service_interactive_reserve
            ),
            clock=self.clock,
            execute_group=execute_group,
            coalesce=self.coalesce_policy,
            placer=self.placer,
            slo_tenants=(
                self.slo.tenant_objectives().keys()
                if self.slo is not None
                else None
            ),
            preemption=self.preemption,
            on_preempted=self._journal_preempted,
            on_resumed=self._journal_resumed,
            fence=(
                self._scheduler_fence if self.fleet is not None else None
            ),
        )
        # queue-driven autoscaling: the control loop over the per-class
        # queue-wait histograms and SLO burn (service/autoscale.py)
        autoscale_on = bool(
            opts.service_autoscale if autoscale is None else autoscale
        )
        self.autoscaler: Optional[Any] = None
        if autoscale_on:
            from deequ_tpu.service.autoscale import AutoscaleController

            self.autoscaler = AutoscaleController(
                self.scheduler,
                clock=self.clock,
                interval_s=opts.service_autoscale_interval_s,
                min_workers=opts.service_autoscale_min_workers,
                max_workers=opts.service_autoscale_max_workers,
                target_interactive_p99_s=(
                    opts.service_autoscale_target_interactive_p99_s
                ),
                slo=self.slo,
            )
        self._run_seq = 0
        self._handles: Dict[str, RunHandle] = {}
        self._handles_lock = threading.Lock()
        self._uninstall_sigterm: Optional[Callable[[], None]] = None
        self._sigterm_watcher: Optional[threading.Thread] = None
        self._watcher_stop = threading.Event()

    # -- lifecycle ------------------------------------------------------

    def start(self, install_sigterm: bool = False) -> "VerificationService":
        if install_sigterm:
            from deequ_tpu.engine.deadline import install_graceful_shutdown

            self._uninstall_sigterm = install_graceful_shutdown()
            self._watcher_stop.clear()
            # lint-ok: thread-discipline: service-scoped watcher joined
            # in stop(); not part of a scan, so the ingest probe (which
            # tier-1 asserts empty between scans) must not see it
            self._sigterm_watcher = threading.Thread(
                target=self._watch_shutdown,
                daemon=True,
                name="deequ-tpu-service-shutdown-watch",
            )
            self._sigterm_watcher.start()
        self.scheduler.start()
        if self.autoscaler is not None:
            self.autoscaler.start()
        if self.fleet is not None:
            self.fleet.start()
        if self._metrics_port is not None and self.metrics_server is None:
            from deequ_tpu.telemetry import serve_metrics

            self.metrics_server = serve_metrics(
                self._metrics_port, health=self.health
            )
        get_telemetry().event(
            "service_started",
            workers=self.scheduler.workers,
            interactive_reserve=self.scheduler.interactive_reserve,
        )
        return self

    def _watch_shutdown(self) -> None:
        token = shutdown_token()
        while not self._watcher_stop.is_set():
            # Event.wait on the token — event-driven, not a time poll;
            # the short timeout only lets a stopped service reclaim the
            # watcher thread
            if token.wait(timeout=0.1):
                self.drain(token.reason or "shutdown requested")
                return

    def stop(
        self, drain: bool = True, timeout: Optional[float] = 30.0
    ) -> None:
        """Shut the service down. ``drain=True`` finishes everything
        already queued first; ``drain=False`` cancels queued runs
        (running ones still finish — workers are cooperative, not
        preemptive)."""
        if drain:
            self.wait_idle(timeout=timeout)
        self.queue.close()
        if not drain:
            self.queue.drain_queued("service stopping")
        self._watcher_stop.set()
        if self.autoscaler is not None:
            self.autoscaler.stop()
        self.scheduler.stop(timeout=timeout)
        if self.fleet is not None:
            # retire the lease only AFTER the scheduler drain: peers
            # skip a retired chain, so retiring while runs are still
            # in flight would forfeit failover coverage for exactly
            # the crash-during-shutdown the journal otherwise survives
            self.fleet.stop(retire=True)
        if self.metrics_server is not None:
            self.metrics_server.close()
            self.metrics_server = None
        if self._uninstall_sigterm is not None:
            self._uninstall_sigterm()
            self._uninstall_sigterm = None
        get_telemetry().event("service_stopped", drained=drain)

    def drain(self, reason: str = "shutdown requested") -> int:
        """SIGTERM semantics: refuse new work, cancel QUEUED runs with
        ``reason``, let RUNNING runs finish under the engine's
        supervision (checkpoint + partial metrics). Returns the number
        of queued runs drained."""
        self.queue.close()
        drained = self.queue.drain_queued(reason)
        get_telemetry().event(
            "service_drained", reason=reason, drained=drained
        )
        return drained

    def wait_idle(self, timeout: Optional[float] = None) -> bool:
        """Block until nothing is queued or running (best-effort;
        returns False on timeout). Poll cadence comes from the clock so
        fake-time tests spin fast."""
        deadline = (
            None if timeout is None else self.clock.now() + timeout
        )
        while True:
            snap = self.queue.snapshot()
            active = sum(snap["active_by_tenant"].values())
            if snap["depth"] == 0 and active == 0:
                return True
            if deadline is not None and self.clock.now() > deadline:
                return False
            self.queue.wait_event(self.clock.queue_poll_s())

    # -- submission -----------------------------------------------------

    def submit(self, request: RunRequest) -> RunHandle:
        """Queue one suite run; returns immediately with the handle.
        Raises ``QuotaExceeded`` when the tenant is over its pending
        quota and ``ServiceOverloaded`` when a BATCH submission hits a
        shed threshold. The deadline budget starts NOW — time spent
        queued counts against it."""
        self._maybe_shed(request)
        with self._handles_lock:
            self._run_seq += 1
            run_id = f"run-{self._run_seq}"
        return self._admit(request, run_id)

    def _admit(
        self, request: RunRequest, run_id: str, journal: bool = True
    ) -> RunHandle:
        """Build the handle/ticket for ``run_id`` and push it. Journal
        ordering is write-ahead: the submitted record lands durably
        BEFORE the ticket can be scheduled, so a crash between the two
        loses an unacknowledged submission, never an acknowledged one."""
        if not epoch_fence_check(self.fleet):
            # a fenced zombie must not ACCEPT work either: its journal
            # now belongs to the adopter, so an admission here would be
            # an unadoptable run
            from deequ_tpu.service.fleet import FencedReplica

            raise FencedReplica(
                "this replica's lease epoch was superseded by an "
                "adopter; restart the process to rejoin the fleet"
            )
        handle = RunHandle(run_id, request.tenant, request.priority)
        budget = None
        if request.deadline_s is not None:
            budget = RunBudget(
                deadline_s=float(request.deadline_s), clock=self.clock
            )
        surface = None
        if self.coalesce_policy is not None:
            # submit-time capture: the coalescer only groups tickets
            # whose config-derived plan-key surfaces are EQUAL, so a
            # config.configure(...) change between two submissions
            # can't smuggle differently-planned runs into one scan
            from deequ_tpu.engine.scan import coalesce_key_surface

            surface = coalesce_key_surface()
        estimated = 0
        if request.estimated_bytes is not None:
            estimated = max(0, int(request.estimated_bytes))
        elif self.placer is not None and request.dataset is not None:
            # submit-time footprint for the placement policy — the SAME
            # coarse estimate the admission watermark gates on (module
            # function: the service never builds an engine here)
            from deequ_tpu.engine.scan import estimated_run_bytes

            try:
                estimated = int(estimated_run_bytes(request.dataset))
            except Exception:  # noqa: BLE001 — estimate is advisory
                estimated = 0
        ticket = RunTicket(
            seq=0,  # assigned by the queue
            handle=handle,
            payload=request,
            budget=budget,
            estimated_bytes=estimated,
            dataset_key=request.dataset_key,
            coalesce_surface=surface,
        )
        tm = get_telemetry()
        if self.journal is not None:
            if journal:
                self.journal.record_submitted(
                    run_id,
                    tenant=request.tenant,
                    priority=int(request.priority),
                    deadline_s=request.deadline_s,
                    dataset_key=request.dataset_key,
                )
            handle.on_terminal = self._journal_terminal
        try:
            self.queue.push(ticket)  # raises QuotaExceeded pre-registration
        except QuotaExceeded:
            if self.journal is not None:
                self.journal.record_terminal(
                    run_id, RunState.REJECTED, reason="tenant quota"
                )
            raise
        with self._handles_lock:
            self._handles[run_id] = handle
        tm.counter("service.submitted").inc()
        tm.counter(f"service.tenant.{request.tenant}.submitted").inc()
        tm.event(
            "service_run_submitted",
            run_id=run_id,
            tenant=request.tenant,
            priority=Priority.name(request.priority),
            dataset_key=request.dataset_key,
            deadline_s=request.deadline_s,
        )
        if (
            self.preemption is not None
            and request.priority == Priority.INTERACTIVE
        ):
            # the admission IS the demand signal: if no worker (or no
            # device slice) can serve this run, the youngest solo
            # BATCH run yields at its next batch boundary
            self.scheduler.note_interactive_demand(run_id)
        return handle

    # -- load shedding ---------------------------------------------------

    def _maybe_shed(self, request: RunRequest) -> None:
        """Reject a BATCH submission fast when the service is drowning
        (deep queue or crashing children) — INTERACTIVE/STANDARD work is
        never shed, matching the scheduler's reserve semantics."""
        if request.priority < Priority.BATCH:
            return
        reason = None
        retry_after = 0.0
        if self.shed_queue_depth > 0:
            depth = self.queue.depth()
            if depth >= self.shed_queue_depth:
                reason = (
                    f"queue depth {depth} >= shed threshold "
                    f"{self.shed_queue_depth}"
                )
                # rough drain estimate: today's depth at one run per
                # worker-second — a HINT, not a promise
                retry_after = depth / max(1, self.scheduler.workers)
        if reason is None and self.shed_crash_rate > 0:
            now = self.clock.now()
            with self._crash_lock:
                while self._crash_times and (
                    now - self._crash_times[0] > self.shed_crash_window_s
                ):
                    self._crash_times.popleft()
                crashes = len(self._crash_times)
                oldest = self._crash_times[0] if self._crash_times else now
            if crashes >= self.shed_crash_rate:
                reason = (
                    f"{crashes} child crashes in the last "
                    f"{self.shed_crash_window_s:.0f}s"
                )
                retry_after = max(
                    0.0, self.shed_crash_window_s - (now - oldest)
                )
        if reason is None:
            return
        tm = get_telemetry()
        tm.counter("service.submissions_shed").inc()
        tm.event(
            "service_submission_shed",
            tenant=request.tenant,
            priority=Priority.name(request.priority),
            reason=reason,
            retry_after_s=retry_after,
        )
        raise ServiceOverloaded(
            f"service overloaded ({reason}); retry in {retry_after:.1f}s",
            retry_after_s=retry_after,
        )

    def _note_crash(self) -> None:
        with self._crash_lock:
            self._crash_times.append(self.clock.now())

    # -- journal hooks ---------------------------------------------------

    def _journal_terminal(self, handle: RunHandle) -> None:
        if self.journal is None:
            return
        if not epoch_fence_check(self.fleet):
            return  # the adopter owns this run's journal now
        state, error = handle.terminal_info()
        if state is None:
            return
        self.journal.record_terminal(
            handle.run_id,
            state,
            error=(
                f"{type(error).__name__}: {error}"[:500]
                if error is not None
                else None
            ),
        )

    def _journal_preempted(self, ticket: RunTicket, evidence: Any) -> None:
        """Write-ahead preemption record: lands BEFORE the ticket
        re-enters the queue, so a process death in between still sees
        the run as pending (and preempted) at recovery."""
        if self.journal is None:
            return
        if not epoch_fence_check(self.fleet):
            return
        self.journal.record_preempted(
            ticket.handle.run_id,
            reason=getattr(evidence, "reason", None),
            batch_index=int(getattr(evidence, "batch_index", 0) or 0),
            row_offset=int(getattr(evidence, "row_offset", 0) or 0),
            checkpointed=bool(getattr(evidence, "checkpointed", False)),
        )

    def _journal_resumed(self, ticket: RunTicket) -> None:
        if self.journal is None:
            return
        if not epoch_fence_check(self.fleet):
            return
        self.journal.record_resumed(
            ticket.handle.run_id, preemptions=int(ticket.preemptions)
        )

    # -- restart recovery ------------------------------------------------

    def recover(
        self,
        resolve: Optional[
            Callable[[str, Dict[str, Any]], Optional[RunRequest]]
        ] = None,
    ) -> List[RunHandle]:
        """Re-admit every journaled run that never reached a terminal
        state — call ONCE on a fresh service over the journal dir of a
        dead one, before accepting new traffic.

        Journal records are JSON (checks/datasets hold closures that do
        not serialize), so ``resolve(run_id, entry)`` rebuilds each
        ``RunRequest`` from the journaled fields (tenant, priority,
        deadline_s, dataset_key, started, last_checkpoint). Returning
        None declares the run unresolvable: it is journaled FAILED
        instead of silently dropped. Priority and deadline come from the
        JOURNAL (the submit-pinned envelope), not the resolver. Runs
        that already started resume mid-scan from their durable
        checkpoint cursors the moment they re-execute."""
        if self.journal is None:
            return []
        if not epoch_fence_check(self.fleet):
            return []
        tm = get_telemetry()
        pending = self.journal.pending_runs()
        # continue run numbering past every journaled id — a recovered
        # service must never mint a colliding run_id
        top = 0
        for run_id in pending:
            tail = run_id.rsplit("-", 1)[-1]
            if tail.isdigit():
                top = max(top, int(tail))
        with self._handles_lock:
            self._run_seq = max(self._run_seq, top)
        recovered: List[RunHandle] = []
        for run_id, entry in pending.items():
            request = resolve(run_id, entry) if resolve is not None else None
            if request is None:
                self.journal.record_terminal(
                    run_id,
                    RunState.FAILED,
                    error="unresolvable at recovery (no RunRequest)",
                )
                tm.event(
                    "service_run_unrecoverable",
                    run_id=run_id,
                    tenant=entry.get("tenant"),
                )
                continue
            if entry.get("priority") is not None:
                request.priority = int(entry["priority"])
            if entry.get("deadline_s") is not None:
                request.deadline_s = float(entry["deadline_s"])
            handle = self._admit(request, run_id, journal=False)
            recovered.append(handle)
            tm.event(
                "service_run_recovered",
                run_id=run_id,
                tenant=entry.get("tenant"),
                started=bool(entry.get("started")),
                last_checkpoint=entry.get("last_checkpoint"),
                preempted=bool(entry.get("preempted")),
                preempt_count=int(entry.get("preempt_count") or 0),
                # a re-admitted sink run resumes MID-ARTIFACT: its
                # durable span segments + egress cursor survive the
                # restart alongside the scan checkpoint
                egress=bool(request.row_level_sink is not None),
            )
        if recovered:
            tm.counter("service.runs_recovered").inc(len(recovered))
        if self.fleet is not None:
            # a restarted replica also finishes its own half-done
            # adoptions: an intent with no done record means a claim
            # CAS may have won without its replay completing — the
            # claimed chain is terminal and never re-polled, so this
            # is those runs' only road back
            for intent in self.journal.pending_adoptions():
                self._finish_adoption(self.journal, intent)
        self.journal.compact()
        return recovered

    # -- fleet adoption --------------------------------------------------

    def _scheduler_fence(self) -> bool:
        """Scheduler hook: True while this replica may finish runs."""
        return epoch_fence_check(self.fleet)

    def _journal_adopt_intent(self, adoption: Any) -> None:
        """FleetSupervisor ``on_adopt_intent`` hook, fired BEFORE the
        claim CAS: durably record in OUR journal which chain we are
        about to claim and where its journal lives. A claimed chain is
        terminal — nothing re-polls it — so without this write-ahead
        an adopter dying between the CAS win and the replay would
        strand the orphan's runs forever; with it, whoever adopts (or
        recovers) THIS journal finds the intent and finishes the
        adoption. Raising aborts the claim."""
        if not epoch_fence_check(self.fleet):
            from deequ_tpu.service.fleet import FencedReplica

            raise FencedReplica(
                "fenced: this replica must not claim peer chains"
            )
        self.journal.record_adoption_intent(
            adoption.replica, adoption.journal_dir, adoption.epoch
        )

    def _journal_adopt_lost(self, adoption: Any) -> None:
        """FleetSupervisor ``on_adopt_lost`` hook: another survivor
        won the claim CAS — close our intent so nobody replays a race
        we lost."""
        if not epoch_fence_check(self.fleet):
            return
        self.journal.record_adoption_done(
            adoption.replica, adoption.epoch, status="race_lost"
        )

    def _adopt_replica(self, adoption: Any) -> List[RunHandle]:
        """FleetSupervisor callback after WINNING the lease CAS on a
        dead peer's chain: replay the orphan journal's pending runs
        into OUR queue through the recover() resolve contract
        (``adopt_resolve(entry) -> RunRequest | None``).

        Ordering per run: (1) write-ahead ``submitted`` record in OUR
        journal under a fresh run id carrying ``adopted_from``, (2)
        admit, (3) mark the run ``adopted`` (terminal) in the ORPHAN
        journal. The whole replay runs under the adoption intent this
        replica journaled before the CAS (``_journal_adopt_intent``)
        and is closed by an ``adoption_done`` record at the end — an
        adopter dying ANYWHERE in between leaves a pending intent that
        its own adopter (or its restarted self, via ``recover()``)
        finishes: at-least-once across a double failure, exactly-once
        otherwise (the fence keeps the zombie original from ever
        double-persisting). A replica that finds itself fenced after
        the CAS win hands the claim back (``release_claim``) so the
        chain stays adoptable by a live survivor.

        Started runs resume from their durable cursors automatically:
        checkpoints live under the SHARED fleet dir keyed by plan
        token, not by replica or run id."""
        if not epoch_fence_check(self.fleet):
            self.fleet.release_claim(adoption.replica, adoption.epoch)
            return []
        if (
            adoption.journal_dir in self._adopting
            or adoption.journal_dir == self.journal.path
        ):
            # cyclic intent graph (dead adopters pointing at each
            # other) or a self-claim: nothing to replay that is not
            # already being replayed higher up this call stack
            self.fleet.release_claim(adoption.replica, adoption.epoch)
            return []
        self._adopting.add(adoption.journal_dir)
        try:
            return self._replay_orphan(adoption)
        finally:
            self._adopting.discard(adoption.journal_dir)

    def _replay_orphan(self, adoption: Any) -> List[RunHandle]:
        """The adoption replay body (see ``_adopt_replica`` for the
        ordering contract; the caller holds the re-entrancy guard and
        has already passed the epoch fence)."""
        if not epoch_fence_check(self.fleet):
            self.fleet.release_claim(adoption.replica, adoption.epoch)
            return []
        tm = get_telemetry()
        from deequ_tpu.service.journal import RunJournal as _Journal

        orphan = _Journal(adoption.journal_dir)
        orphan.record_epoch(
            self.fleet.replica_id,
            adoption.epoch,
            reason="adopted",
            stale_for_s=round(adoption.stale_for_s, 3),
        )
        adopted: List[RunHandle] = []
        for run_id, entry in orphan.pending_runs().items():
            # same key shape the isolated runner's breaker (and the
            # crash-loop ledger writes above) use
            plan_key = (
                f"dataset:{entry['dataset_key']}"
                if entry.get("dataset_key")
                else run_id
            )
            if self.fleet.quarantined(plan_key):
                # poison: this run already crashed enough DISTINCT
                # replicas — quarantine instead of walking the fleet
                tm.counter("service.fleet.poisoned_runs").inc()
                tm.event(
                    "fleet_run_poisoned",
                    run_id=run_id,
                    plan_key=plan_key,
                    replicas=self.fleet.crashed_replicas(plan_key),
                )
                orphan.record_terminal(
                    run_id,
                    RunState.FAILED,
                    error=(
                        "fleet poison quarantine: crashed "
                        f"{len(self.fleet.crashed_replicas(plan_key))} "
                        "distinct replicas"
                    ),
                )
                continue
            request = (
                self._adopt_resolve(entry)
                if self._adopt_resolve is not None
                else None
            )
            if request is None:
                orphan.record_terminal(
                    run_id,
                    RunState.FAILED,
                    error="unresolvable at adoption (no RunRequest)",
                )
                tm.event(
                    "service_run_unrecoverable",
                    run_id=run_id,
                    tenant=entry.get("tenant"),
                )
                continue
            if entry.get("priority") is not None:
                request.priority = int(entry["priority"])
            if entry.get("deadline_s") is not None:
                request.deadline_s = float(entry["deadline_s"])
            with self._handles_lock:
                self._run_seq += 1
                new_id = f"run-{self._run_seq}"
            self.journal.record_submitted(
                new_id,
                tenant=request.tenant,
                priority=int(request.priority),
                deadline_s=request.deadline_s,
                dataset_key=request.dataset_key,
                adopted_from=run_id,
                adopted_replica=adoption.replica,
            )
            handle = self._admit(request, new_id, journal=False)
            adopted.append(handle)
            orphan.record_terminal(
                run_id,
                "adopted",
                adopted_as=new_id,
                adopter=self.fleet.replica_id,
            )
            tm.counter("service.fleet.runs_adopted").inc()
            tm.event(
                "service_run_adopted",
                run_id=new_id,
                adopted_from=run_id,
                replica=adoption.replica,
                tenant=entry.get("tenant"),
                started=bool(entry.get("started")),
                last_checkpoint=entry.get("last_checkpoint"),
            )
        # finish the DEAD replica's own half-done adoptions: its
        # journal may hold intents with no done record — chains it
        # claimed whose replay never completed. Those chains are
        # terminally "adopted" and never re-polled, so this replay is
        # their runs' only road back.
        for intent in orphan.pending_adoptions():
            self._finish_adoption(orphan, intent)
        # close OUR intent for this chain: the replay is complete, a
        # later adopter of this journal has nothing left to finish
        self.journal.record_adoption_done(
            adoption.replica,
            adoption.epoch,
            status="adopted",
            runs=len(adopted),
        )
        # journal hygiene: the orphan log is now all-terminal — shrink
        # it (and our own) so the next scan is O(live runs)
        orphan.compact()
        self.journal.compact()
        with self._handles_lock:
            self._adopted_handles.extend(adopted)
        return adopted

    def _finish_adoption(
        self, journal: Any, intent: Dict[str, Any]
    ) -> None:
        """Complete a half-done adoption found in ``journal`` (ours at
        ``recover()``, a dead adopter's during replay): re-claim the
        nested orphan chain at ITS next epoch — the claim CAS keeps
        finishers unique however many replicas walk the same intent
        chain — and replay whatever runs are still pending in that
        journal (runs the dead adopter already re-admitted are
        terminal there and stay put). The intent is then closed in the
        journal that held it, which this replica now owns."""
        if not epoch_fence_check(self.fleet):
            return
        replica = str(intent.get("replica") or "")
        journal_dir = str(intent.get("journal_dir") or "")
        if not replica or not journal_dir:
            return
        if (
            replica != self.fleet.replica_id
            and journal_dir != self.journal.path
            and journal_dir not in self._adopting
        ):
            # re-claiming fires the full adoption cycle: our own
            # intent lands first, then the CAS, then _adopt_replica
            if self.fleet.adopt_chain(replica, journal_dir) is not None:
                get_telemetry().counter(
                    "service.fleet.adoptions_finished"
                ).inc()
        journal.record_adoption_done(
            replica,
            int(intent.get("epoch") or 0),
            status="finished",
            finisher=self.fleet.replica_id,
        )

    def adopted_runs(self) -> List[RunHandle]:
        """Handles of every run this replica adopted from dead peers."""
        with self._handles_lock:
            return list(self._adopted_handles)

    def handle(self, run_id: str) -> Optional[RunHandle]:
        with self._handles_lock:
            return self._handles.get(run_id)

    # -- warmup ---------------------------------------------------------

    def warmup(
        self,
        schema: Dict[str, str],
        suite: bool = True,
        nullable=(False, True),
        **kwargs,
    ) -> List[str]:
        """Precompile the fused plans production suites will need
        (tools/warmup.py machinery) and record the warmed plan tokens.
        Returns the tokens; after this, matching submissions execute
        with zero recompiles (the acceptance telemetry in
        examples/verification_service.py)."""
        warm_plans = _load_warm_plans()
        if self.placer is not None and "mesh_shapes" not in kwargs:
            # elastic placement: warm EVERY slice shape the policy can
            # choose, so a pool-pressure-driven resize never compiles
            shapes: List[int] = []
            ndev = 1
            while ndev <= self.placer.pool.max_slice:
                shapes.append(ndev)
                ndev *= 2
            kwargs["mesh_shapes"] = shapes
        report = warm_plans(
            schema, suite=suite, nullable=nullable, **kwargs
        )
        self.plans.note_warmed(report.get("tokens", []))
        return list(report.get("tokens", []))

    # -- the real executor ----------------------------------------------

    def _build_engine(self, lease: Any, run_id: str):
        """The per-run ``AnalysisEngine``, or None when neither a
        durable checkpoint path nor a placement lease calls for one.
        A leased run executes on its slice's mesh — the engine's
        placement feeds the shape-keyed plan cache, so every slice of
        the same size replays the warmed plan."""
        mesh = getattr(lease, "mesh", None) if lease is not None else None
        if self._checkpoint_path is None and mesh is None:
            return None
        from deequ_tpu.engine.scan import AnalysisEngine

        kwargs: Dict[str, Any] = {}
        if self._checkpoint_path is not None:
            kwargs["checkpointer"] = _JournalingCheckpointer(
                self._checkpoint_path, self.journal, run_id,
                fleet=self.fleet,
            )
        if mesh is not None:
            kwargs["mesh"] = mesh
        return AnalysisEngine(**kwargs)

    def _execute(self, ticket: RunTicket):
        request: RunRequest = ticket.payload
        if self.journal is not None and epoch_fence_check(self.fleet):
            self.journal.record_started(
                ticket.handle.run_id, tenant=request.tenant
            )
        return self._execute_solo(ticket)

    def _execute_solo(self, ticket: RunTicket):
        """Drive one already-journaled ticket (the solo path, and the
        per-member fallback of a failed superset scan)."""
        if self.isolated:
            payload = self._isolation_payload(ticket)
            if payload is not None:
                return self._execute_isolated(ticket, payload)
            get_telemetry().counter(
                "service.isolation_inline_fallbacks"
            ).inc()
            get_telemetry().event(
                "service_isolation_fallback",
                run_id=ticket.handle.run_id,
                reason="request does not pickle (closures in "
                "checks/dataset_factory); executing in-process",
            )
        return self._execute_inline(ticket)

    def _execute_inline(self, ticket: RunTicket):
        from deequ_tpu.verification.suite import VerificationSuite

        request: RunRequest = ticket.payload
        dataset, hit = self.datasets.lease(
            request.dataset_key, request.dataset_factory
        )
        get_telemetry().event(
            "service_dataset_leased",
            run_id=ticket.handle.run_id,
            dataset_key=request.dataset_key,
            cache_hit=hit,
        )
        engine = self._build_engine(
            ticket.lease, run_id=ticket.handle.run_id
        )
        try:
            result = VerificationSuite.do_verification_run(
                dataset,
                request.checks,
                required_analyzers=request.required_analyzers,
                engine=engine,
                metrics_repository=request.metrics_repository,
                save_or_append_results_with_key=request.result_key,
                deadline=ticket.budget,
                cancel=run_cancel_token(ticket),
                row_level_sink=request.row_level_sink,
            )
        finally:
            self.datasets.release(request.dataset_key)
        # per-run plan-cache accounting from the run's own telemetry
        # summary (counter deltas) — recompiles-after-warmup is THE
        # steady-state health signal
        self.plans.record_run(getattr(result, "telemetry", None))
        if (
            self.slo is not None
            and request.metrics_repository is not None
            and request.result_key is not None
        ):
            _persist_slo_records(
                request.metrics_repository,
                request.result_key,
                self.slo,
                fleet=self.fleet,
            )
        return result

    # -- isolated (child-process) execution ------------------------------

    def _isolation_payload(
        self, ticket: RunTicket
    ) -> Optional[Dict[str, Any]]:
        """The spawn-safe payload for this run, or None when the request
        holds closures that cannot cross a process boundary (the caller
        then falls back to in-process execution, loudly)."""
        request: RunRequest = ticket.payload
        payload = {
            "run_id": ticket.handle.run_id,
            "dataset_key": request.dataset_key,
            "dataset_factory": request.dataset_factory,
            "checks": list(request.checks),
            "required_analyzers": list(request.required_analyzers),
            "checkpoint_path": self._checkpoint_path,
            # the sink dataclass is spawn-safe (the child builds its
            # own QuarantineWriter over the artifact dir); the child's
            # EgressReport rides back on result.row_level_egress and is
            # re-stamped onto the SUBMITTING process's sink object by
            # _execute_isolated
            "row_level_sink": request.row_level_sink,
            "deadline_s": (
                ticket.budget.remaining()
                if ticket.budget is not None
                else None
            ),
            # slice SIZE crosses the boundary, not the lease: the child
            # owns its own jax runtime, so it rebuilds an ndev mesh
            # over its own first local devices (the lease still bounds
            # parent-side concurrency for the run's duration)
            "placement_ndev": (
                ticket.lease.ndev if ticket.lease is not None else None
            ),
        }
        try:
            pickle.dumps(payload)
        except Exception:  # noqa: BLE001 — any closure anywhere inside
            return None
        return payload

    def _execute_isolated(self, ticket: RunTicket, payload: Dict[str, Any]):
        from deequ_tpu.engine.subproc import checkpoint_progress_probe

        request: RunRequest = ticket.payload
        probe = (
            checkpoint_progress_probe(self._checkpoint_path)
            if self._checkpoint_path is not None
            else None
        )
        runner = IsolatedRunner(
            key=f"dataset:{request.dataset_key}",
            progress_probe=probe,
            timeout_s=(
                ticket.budget.remaining()
                if ticket.budget is not None
                else None
            ),
            clock=self.clock,
            # preemption (and client cancel) crosses the spawn boundary
            # as ONE control message; the child exits cleanly through
            # its checkpoint path — never terminated mid-batch
            cancel_token=run_cancel_token(ticket),
            epoch_guard=(
                self.fleet.child_guard() if self.fleet is not None else None
            ),
        )
        try:
            result = runner.run(_isolated_execute, payload)
        except CrashLoopError as exc:
            self._note_crash()
            if self.fleet is not None:
                # shared breaker ledger: a crash loop HERE becomes
                # fleet-visible, so the run cannot walk the fleet via
                # adoption once poison_replicas distinct hosts crashed
                self.fleet.note_crash_loop(
                    f"dataset:{request.dataset_key}"
                )
            from deequ_tpu import config

            policy = config.options().degradation_policy
            if policy == "fail":
                raise
            # warn/tolerate flooring: a crash loop yields NO partial
            # data, so the floored result is an empty one that carries
            # the crash provenance instead of failing the handle
            return _crash_loop_result(exc, policy)
        if request.row_level_sink is not None:
            # the child ran with a pickled COPY of the sink — land the
            # report on the submitting process's object, where callers
            # (and docs) expect it
            request.row_level_sink.report = getattr(
                result, "row_level_egress", None
            )
        self.plans.record_run(getattr(result, "telemetry", None))
        return result

    # -- coalesced (superset-scan) execution -----------------------------

    def _execute_group(self, tickets: List[RunTicket]) -> List[Any]:
        """Execute a coalesced group: ONE superset scan over the shared
        dataset, each member's ``VerificationResult`` sliced back out.
        Returns one outcome per ticket in order (a result, or an
        exception instance for a member that failed individually). A
        superset-scan failure degrades to independent per-member
        execution; a crash-looped isolated superset floors EVERY member
        with the crash provenance."""
        tm = get_telemetry()
        host = tickets[0]
        run_ids = [t.handle.run_id for t in tickets]
        if self.journal is not None and epoch_fence_check(self.fleet):
            for ticket in tickets:
                self.journal.record_started(
                    ticket.handle.run_id, tenant=ticket.payload.tenant
                )
        tm.counter("service.coalesced_scans").inc()
        tm.counter("service.runs_coalesced").inc(len(tickets))
        # the whole point, as a counter: K runs, K-1 traversals NOT made
        tm.counter("service.scan_passes_saved").inc(len(tickets) - 1)
        waits = [
            max(0.0, (t.handle.started_at or 0.0) - t.submitted_at)
            for t in tickets
        ]
        tm.event(
            "runs_coalesced",
            dataset_key=host.dataset_key,
            members=len(tickets),
            run_ids=",".join(run_ids),
            tenants=",".join(
                sorted({t.payload.tenant for t in tickets})
            ),
            queue_wait_s_max=round(max(waits), 6) if waits else 0.0,
        )
        if self.isolated:
            payload = self._group_isolation_payload(tickets)
            if payload is not None:
                return self._execute_group_isolated(tickets, payload)
            tm.counter("service.isolation_inline_fallbacks").inc()
            tm.event(
                "service_isolation_fallback",
                run_id=",".join(run_ids),
                reason="coalesced group does not pickle; executing "
                "in-process",
            )
        return self._execute_group_inline(tickets)

    def _execute_group_inline(self, tickets: List[RunTicket]) -> List[Any]:
        from deequ_tpu.verification.suite import VerificationSuite

        host = tickets[0]
        request: RunRequest = host.payload
        dataset, hit = self.datasets.lease(
            request.dataset_key, request.dataset_factory
        )
        get_telemetry().event(
            "service_dataset_leased",
            run_id=host.handle.run_id,
            dataset_key=request.dataset_key,
            cache_hit=hit,
            coalesced_members=len(tickets),
        )
        engine = self._build_engine(
            host.lease, run_id=host.handle.run_id
        )
        try:
            # the superset scan runs under the HOST's envelope (best
            # priority, earliest seq). Member deadlines governed queue
            # wait (resolved at pop); a member cancel landing after
            # the scan began does NOT stop the group — the member
            # still receives its complete sliced result
            results = VerificationSuite.do_coalesced_verification_run(
                dataset,
                [
                    (
                        list(t.payload.checks),
                        list(t.payload.required_analyzers),
                    )
                    for t in tickets
                ],
                engine=engine,
                deadline=host.budget,
            )
        # lint-ok: interrupt-swallow: degradation to independent
        # per-member execution — each member's own path re-raises into
        # its outcome slot, nothing is lost
        except BaseException as exc:  # noqa: BLE001
            return self._execute_members_independently(tickets, exc)
        finally:
            self.datasets.release(request.dataset_key)
        for ticket, result in zip(tickets, results):
            _scope_member_telemetry(ticket, result)
            member: RunRequest = ticket.payload
            if (
                member.metrics_repository is not None
                and member.result_key is not None
            ):
                _persist_member_result(
                    member.metrics_repository,
                    member.result_key,
                    result,
                    slo=self.slo,
                    fleet=self.fleet,
                )
        self.plans.record_run(getattr(results[0], "telemetry", None))
        return list(results)

    def _execute_members_independently(
        self, tickets: List[RunTicket], cause: BaseException
    ) -> List[Any]:
        """Superset-scan failure fan-out: re-run every member solo so
        one bad union never fails N tenants. Per-member outcomes are
        results or that member's OWN exception."""
        tm = get_telemetry()
        tm.counter("service.coalesce_fallbacks").inc()
        tm.event(
            "coalesce_fallback",
            dataset_key=tickets[0].dataset_key,
            members=len(tickets),
            error=repr(cause)[:500],
        )
        outcomes: List[Any] = []
        for ticket in tickets:
            try:
                outcomes.append(self._execute_solo(ticket))
            # lint-ok: interrupt-swallow: the outcome slot is the error
            # channel — the scheduler fans it into the member's handle
            except BaseException as exc:  # noqa: BLE001
                outcomes.append(exc)
        return outcomes

    def _group_isolation_payload(
        self, tickets: List[RunTicket]
    ) -> Optional[Dict[str, Any]]:
        host: RunRequest = tickets[0].payload
        payload = {
            "run_ids": [t.handle.run_id for t in tickets],
            "dataset_key": host.dataset_key,
            "dataset_factory": host.dataset_factory,
            "members": [
                {
                    "checks": list(t.payload.checks),
                    "required_analyzers": list(
                        t.payload.required_analyzers
                    ),
                }
                for t in tickets
            ],
            "checkpoint_path": self._checkpoint_path,
            "deadline_s": (
                tickets[0].budget.remaining()
                if tickets[0].budget is not None
                else None
            ),
            "placement_ndev": (
                tickets[0].lease.ndev
                if tickets[0].lease is not None
                else None
            ),
        }
        try:
            pickle.dumps(payload)
        except Exception:  # noqa: BLE001 — any closure anywhere inside
            return None
        return payload

    def _execute_group_isolated(
        self, tickets: List[RunTicket], payload: Dict[str, Any]
    ) -> List[Any]:
        from deequ_tpu.engine.subproc import checkpoint_progress_probe

        host = tickets[0]
        request: RunRequest = host.payload
        probe = (
            checkpoint_progress_probe(self._checkpoint_path)
            if self._checkpoint_path is not None
            else None
        )
        runner = IsolatedRunner(
            key=f"dataset:{request.dataset_key}",
            progress_probe=probe,
            timeout_s=(
                host.budget.remaining()
                if host.budget is not None
                else None
            ),
            clock=self.clock,
            epoch_guard=(
                self.fleet.child_guard() if self.fleet is not None else None
            ),
        )
        try:
            results = runner.run(_isolated_execute_coalesced, payload)
        except CrashLoopError as exc:
            self._note_crash()
            if self.fleet is not None:
                self.fleet.note_crash_loop(
                    f"dataset:{request.dataset_key}"
                )
            from deequ_tpu import config

            policy = config.options().degradation_policy
            # crash-loop flooring lands on EVERY member with the same
            # provenance: under "fail" each handle fails with the
            # CrashLoopError; under warn/tolerate each member gets its
            # own floored empty result carrying the crash record
            if policy == "fail":
                return [exc for _ in tickets]
            return [_crash_loop_result(exc, policy) for _ in tickets]
        # lint-ok: interrupt-swallow: degradation to independent
        # per-member execution; member paths re-raise into outcome slots
        except BaseException as exc:  # noqa: BLE001
            return self._execute_members_independently(tickets, exc)
        for ticket, result in zip(tickets, results):
            if isinstance(result, Exception):
                continue
            _scope_member_telemetry(ticket, result)
            member: RunRequest = ticket.payload
            if (
                member.metrics_repository is None
                or member.result_key is None
            ):
                continue
            _persist_member_result(
                member.metrics_repository,
                member.result_key,
                result,
                slo=self.slo,
                fleet=self.fleet,
            )
        if results and not isinstance(results[0], Exception):
            self.plans.record_run(getattr(results[0], "telemetry", None))
        return list(results)

    # -- introspection --------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        snap = {
            "queue": self.queue.snapshot(),
            "datasets": self.datasets.snapshot(),
            "plans": self.plans.snapshot(),
        }
        if self.placer is not None:
            snap["placement"] = self.placer.snapshot()
        if self.fleet is not None:
            snap["fleet"] = self.fleet.snapshot()
        return snap

    def health(self) -> Dict[str, Any]:
        """The ``/healthz`` payload of the live plane: queue depths,
        active slices, breaker states, shed counts — everything the
        future autoscaler (ROADMAP item 2) reads, in one place."""
        from deequ_tpu.engine.subproc import breaker_states

        tm = get_telemetry()
        queue_snap = self.queue.snapshot()
        counters = tm.metrics.counters_snapshot()
        payload: Dict[str, Any] = {
            "status": "ok" if self.scheduler.running else "stopped",
            "queue": queue_snap,
            "workers": self.scheduler.workers,
            "breakers": breaker_states(),
            "shed": {
                "submissions_shed": counters.get(
                    "service.submissions_shed", 0
                ),
                "drained_queued": counters.get(
                    "service.drained_queued", 0
                ),
                "quota_rejections": counters.get(
                    "service.quota_rejections", 0
                ),
            },
        }
        if self.placer is not None:
            placement = self.placer.snapshot()
            payload["placement"] = placement
            payload["slices_active"] = placement.get("active_slices")
        if self.preemption is not None:
            preempt = self.preemption.snapshot()
            preempt["preemptions"] = counters.get(
                "service.preemptions", 0
            )
            preempt["requeues"] = counters.get(
                "service.preempt_requeues", 0
            )
            preempt["resumes"] = counters.get(
                "service.preempt_resumes", 0
            )
            preempt["batches_conserved"] = counters.get(
                "service.preempted_batches_conserved", 0
            )
            payload["preemption"] = preempt
        if self.autoscaler is not None:
            payload["autoscale"] = self.autoscaler.snapshot()
        if self.slo is not None:
            payload["slo"] = self.slo.snapshot()
        if self.fleet is not None:
            fleet = self.fleet.snapshot()
            fleet["fenced_writes"] = counters.get(
                "service.fleet.fenced_writes", 0
            )
            fleet["runs_adopted"] = counters.get(
                "service.fleet.runs_adopted", 0
            )
            fleet["poisoned_runs"] = counters.get(
                "service.fleet.poisoned_runs", 0
            )
            payload["fleet"] = fleet
        return payload


class _JournalingCheckpointer(ScanCheckpointer):
    """A ``ScanCheckpointer`` that also appends a journal ``checkpoint``
    record per save, so replay knows how far a dead run had progressed
    (the cursor itself lives in the checkpoint blob — the journal only
    records THAT progress happened, and where)."""

    def __init__(
        self,
        path: str,
        journal: Optional[RunJournal],
        run_id: str,
        every_batches: Optional[int] = None,
        fleet: Optional[Any] = None,
    ):
        super().__init__(path, every_batches)
        self._journal = journal
        self._run_id = run_id
        self._fleet = fleet

    def save(self, cursor, plan_token, states, host_accs, degradation):
        if not epoch_fence_check(self._fleet):
            # fenced mid-run: the adopter's resumed copy owns the
            # cursor now — a zombie save here could rewind it
            return
        super().save(cursor, plan_token, states, host_accs, degradation)
        if self._journal is not None:
            self._journal.record_checkpoint(
                self._run_id,
                batch_index=int(cursor.batch_index),
                row_offset=int(cursor.row_offset),
                plan_token=plan_token,
            )


class _EpochFencedCheckpointer(ScanCheckpointer):
    """Child-side checkpointer: before every save, re-read the lease
    chain named by the shipped epoch guard (``CHILD_EPOCH_ENV``,
    engine/subproc.py) — a child whose PARENT was fenced while the
    child kept scanning must also stop persisting cursors, or the
    zombie pair would rewind the adopter's progress. The guard check
    is a couple of small reads per checkpoint interval, not per
    batch."""

    def save(self, cursor, plan_token, states, host_accs, degradation):
        from deequ_tpu.engine.subproc import child_epoch_fenced

        if child_epoch_fenced():
            get_telemetry().counter(
                "service.fleet.child_checkpoint_drops"
            ).inc()
            return
        super().save(cursor, plan_token, states, host_accs, degradation)


def _child_engine(payload: Dict[str, Any]):
    """Rebuild the child-side ``AnalysisEngine`` from a spawn payload:
    a checkpointer over the durable path when journaling, and — for a
    leased run — a mesh over the child's own first ``placement_ndev``
    local devices (a lease object cannot cross a spawn boundary; the
    SIZE reproduces the parent's placement shape, so the child hits the
    same shape-keyed plan entry its warmup compiled)."""
    kwargs: Dict[str, Any] = {}
    if payload.get("checkpoint_path"):
        kwargs["checkpointer"] = _EpochFencedCheckpointer(
            payload["checkpoint_path"]
        )
    ndev = payload.get("placement_ndev")
    if ndev:
        import jax
        import numpy as np
        from jax.sharding import Mesh

        devices = jax.devices()
        if len(devices) >= int(ndev):
            kwargs["mesh"] = Mesh(
                np.array(devices[: int(ndev)]), ("dp",)
            )
    if not kwargs:
        return None
    from deequ_tpu.engine.scan import AnalysisEngine

    return AnalysisEngine(**kwargs)


def _isolated_execute(payload: Dict[str, Any]):
    """Child-process entry for one isolated verification run (module
    level: spawn pickles it by reference). Rebuilds the dataset from
    its factory, attaches a checkpointer over the service's durable
    checkpoint path — so a relaunched child resumes mid-scan (a
    row-level sink resumes mid-ARTIFACT via its durable span cursor) —
    and strips ``_data`` from the result (device buffers do not cross
    the pipe). The run listens on
    the child-side cancel token: a parent-sent preemption (or client
    cancel) exits the scan cleanly at the next batch boundary, final
    cursor persisted."""
    from deequ_tpu.engine.subproc import child_cancel_token
    from deequ_tpu.verification.suite import VerificationSuite

    engine = _child_engine(payload)
    dataset = payload["dataset_factory"]()
    result = VerificationSuite.do_verification_run(
        dataset,
        payload["checks"],
        required_analyzers=payload["required_analyzers"],
        engine=engine,
        deadline=payload.get("deadline_s"),
        cancel=child_cancel_token(),
        # the sink writes the artifact dir directly from this child;
        # durable span segments + the checkpoint's egress cursor let a
        # relaunched child resume the artifact mid-write
        row_level_sink=payload.get("row_level_sink"),
    )
    result._data = None
    return result


def _isolated_execute_coalesced(payload: Dict[str, Any]) -> List[Any]:
    """Child-process entry for one coalesced superset scan (module
    level: spawn pickles it by reference). Rebuilds the shared dataset
    ONCE, runs the single superset traversal, and returns the member
    results in order — each stripped of ``_data`` (device buffers do
    not cross the pipe)."""
    from deequ_tpu.verification.suite import VerificationSuite

    engine = _child_engine(payload)
    dataset = payload["dataset_factory"]()
    results = VerificationSuite.do_coalesced_verification_run(
        dataset,
        [
            (member["checks"], member["required_analyzers"])
            for member in payload["members"]
        ],
        engine=engine,
        deadline=payload.get("deadline_s"),
    )
    for result in results:
        result._data = None
    return results


def _persist_member_result(
    repository, key, result, slo=None, fleet=None
) -> None:
    """Append one coalesced member's sliced result to its metrics
    repository — the same load/combine/save (with operational records)
    that ``do_analysis_run`` performs for a solo run. The coalesced
    path cannot delegate persistence to the superset run: each member
    owns a DIFFERENT repository/key pair and only its own slice. When
    the service tracks SLOs, the current attainment snapshot rides
    along as ``slo.*`` operational records under the same key."""
    if not epoch_fence_check(fleet):
        return  # fenced: the adopter persists this member's result
    from deequ_tpu.analyzers.runner import AnalyzerContext
    from deequ_tpu.repository.base import AnalysisResult

    context = AnalyzerContext(
        dict(result.metrics),
        run_metadata=result.run_metadata,
        telemetry=result.telemetry,
        degradation=result.degradation,
        interruption=result.interruption,
    )
    current = repository.load_by_key(key)
    combined = (
        current.analyzer_context + context
        if current is not None
        else context
    )
    summary = result.telemetry
    if summary is not None:
        from deequ_tpu.telemetry.oprecords import operational_metrics

        op = operational_metrics(summary)
        if op:
            combined = combined + AnalyzerContext(op)
    if slo is not None:
        from deequ_tpu.telemetry.oprecords import slo_metrics

        sm = slo_metrics(slo.snapshot())
        if sm:
            combined = combined + AnalyzerContext(sm)
    repository.save(AnalysisResult(key, combined))


def _persist_slo_records(repository, key, slo, fleet=None) -> None:
    """Append the service's current SLO attainment snapshot as
    operational records under a run's ``ResultKey`` — error-budget
    burn becomes one more metric series the existing anomaly
    strategies can trend, with zero new query machinery."""
    if not epoch_fence_check(fleet):
        return
    from deequ_tpu.analyzers.runner import AnalyzerContext
    from deequ_tpu.repository.base import AnalysisResult
    from deequ_tpu.telemetry.oprecords import slo_metrics

    records = slo_metrics(slo.snapshot())
    if not records:
        return
    context = AnalyzerContext(records)
    current = repository.load_by_key(key)
    combined = (
        current.analyzer_context + context
        if current is not None
        else context
    )
    repository.save(AnalysisResult(key, combined))


def _scope_member_telemetry(ticket, result) -> None:
    """Re-scope a coalesced member's telemetry provenance: the
    superset scan executed ONCE under the host ticket's trace, but
    each member's sliced result must carry spans attributed to its OWN
    trace_id — otherwise every member's persisted summary points at
    the host run and a fleet timeline double-attributes the work."""
    trace = getattr(ticket, "trace", None)
    summary = getattr(result, "telemetry", None)
    if trace is None or not isinstance(summary, dict):
        return
    scoped = dict(summary)
    scoped["trace_id"] = trace.trace_id
    scoped["spans"] = [
        dict(sp, trace_id=trace.trace_id)
        for sp in (summary.get("spans") or [])
    ]
    result.telemetry = scoped


def _crash_loop_result(exc: CrashLoopError, policy: str):
    """The floored result of a crash-looped run under a non-"fail"
    degradation policy: empty metrics, status WARNING ("warn") or
    SUCCESS ("tolerate"), with the crash provenance riding the
    degradation record."""
    from deequ_tpu.checks import CheckStatus
    from deequ_tpu.engine.resilience import BatchFailure, ScanDegradation
    from deequ_tpu.verification.suite import VerificationResult

    status = (
        CheckStatus.WARNING if policy == "warn" else CheckStatus.SUCCESS
    )
    result = VerificationResult(status, {}, {})
    degradation = ScanDegradation()
    degradation.failures.append(
        BatchFailure(
            batch_index=-1,
            rows=0,
            error_class=type(exc).__name__,
            message=str(exc)[:500],
            attempts=int(exc.launches),
        )
    )
    result.degradation = degradation
    return result


def _load_warm_plans():
    """Resolve ``tools.warmup.warm_plans`` without requiring ``tools``
    to be an installed package: try the repo-layout import first, then
    load the module straight off the file next to this package."""
    try:
        from tools.warmup import warm_plans  # type: ignore

        return warm_plans
    except ImportError:
        pass
    import importlib.util
    import os

    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)
        ))),
        "tools",
        "warmup.py",
    )
    spec = importlib.util.spec_from_file_location(
        "deequ_tpu_tools_warmup", path
    )
    if spec is None or spec.loader is None:
        raise ImportError(f"cannot load warmup module from {path}")
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return module.warm_plans
