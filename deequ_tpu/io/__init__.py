from deequ_tpu.io.state_provider import (
    FileSystemStateProvider,
    InMemoryStateProvider,
    StateLoader,
    StatePersister,
)

__all__ = [
    "FileSystemStateProvider",
    "InMemoryStateProvider",
    "StateLoader",
    "StatePersister",
]
