"""Differential tests for the r6 fast paths (ISSUE 6): the Pallas
scatter-max kernel behind ``config.pallas_scatter`` and the widened
runtime-gated sorted-dedup HLL pool behind ``config.hll_dedup_widening``.

Ground truth in both cases is the path the flag replaces — the XLA
``.at[].max()`` scatter and the static-probe-only pool — and the
contract is BIT identity, not tolerance: both forms feed the same
``_index_and_rank`` outputs into a max-reduction over the same
register file, so any divergence is a real bug (the v1/v2 max-merge
hazard in analyzers/states.py).

The Pallas kernel runs here in interpret mode
(``DEEQU_TPU_PALLAS_INTERPRET=1``), which executes the same kernel
logic on CPU — the Mosaic-compiled variant is exercised on TPU hosts
by tools/scatter_probe.py and the same differentials there.

Engine-level equality is checked across the three execution shapes
(resident, streaming, mesh) like tests/test_one_pass_spill.py, because
the flags change the compiled plan (plan-cache fingerprint) and each
shape traces its own program.
"""

import numpy as np
import pytest

from deequ_tpu import config
from deequ_tpu.analyzers import (
    AnalysisRunner,
    ApproxCountDistinct,
    ApproxQuantile,
    Mean,
)
from deequ_tpu.data import Dataset
from deequ_tpu.sketches import hll, pallas_scatter


@pytest.fixture
def pallas_interpret(monkeypatch):
    """Force the Pallas kernel's interpret mode and re-probe; restore
    the real probe verdict afterwards so other tests see this host's
    actual availability."""
    monkeypatch.setenv("DEEQU_TPU_PALLAS_INTERPRET", "1")
    pallas_scatter._reset_probe_for_tests()
    yield
    monkeypatch.delenv("DEEQU_TPU_PALLAS_INTERPRET", raising=False)
    pallas_scatter._reset_probe_for_tests()


def _values(dataset, analyzers, engine=None, **options):
    with config.configure(**options):
        ctx = AnalysisRunner.do_analysis_run(
            dataset, analyzers, **({"engine": engine} if engine else {})
        )
    out = {}
    for a in analyzers:
        value = ctx.metric(a).value
        assert value.is_success, (a, value)
        out[a] = value.get()
    return out


class TestPallasScatterUnit:
    """registers_from_hash_pair(_stacked) bit-identity, kernel vs XLA."""

    def _hash_inputs(self, shape, seed):
        rng = np.random.default_rng(seed)
        h1 = rng.integers(0, 1 << 32, shape, dtype=np.uint64).astype(
            np.uint32
        )
        h2 = rng.integers(0, 1 << 32, shape, dtype=np.uint64).astype(
            np.uint32
        )
        mask = rng.random(shape) < 0.9
        return h1, h2, mask

    def _both(self, fn, pallas_on):
        with config.configure(pallas_scatter=pallas_on):
            if pallas_on:
                assert pallas_scatter.available(), (
                    "interpret-mode probe must succeed on CPU"
                )
                assert pallas_scatter.impl_token() == "pallas"
            return np.asarray(fn())

    def test_single_column_bit_identical(self, pallas_interpret):
        h1, h2, mask = self._hash_inputs(8192, 0)
        fn = lambda: hll.registers_from_hash_pair(h1, h2, mask)  # noqa: E731
        np.testing.assert_array_equal(
            self._both(fn, True), self._both(fn, False)
        )

    def test_stacked_bit_identical(self, pallas_interpret):
        h1, h2, mask = self._hash_inputs((6, 4096), 1)
        fn = lambda: hll.registers_from_hash_pair_stacked(h1, h2, mask)  # noqa: E731
        np.testing.assert_array_equal(
            self._both(fn, True), self._both(fn, False)
        )

    def test_all_collision_adversarial(self, pallas_interpret):
        """Every row targets the SAME register: the unroll-16 inner
        loop must still take the running max, not the last write."""
        n = 4096
        h1 = np.full((3, n), 7 << (32 - hll.P), dtype=np.uint32)
        rng = np.random.default_rng(2)
        h2 = rng.integers(0, 1 << 32, (3, n), dtype=np.uint64).astype(
            np.uint32
        )
        mask = np.ones((3, n), bool)
        fn = lambda: hll.registers_from_hash_pair_stacked(h1, h2, mask)  # noqa: E731
        got, want = self._both(fn, True), self._both(fn, False)
        np.testing.assert_array_equal(got, want)
        # sanity: exactly one live register per column
        assert (np.count_nonzero(got, axis=1) == 1).all()

    def test_disabled_without_probe(self):
        """On a host with no TPU and no interpret override the flag is
        inert: scatter_max returns None and XLA runs — never an error."""
        pallas_scatter._reset_probe_for_tests()
        try:
            import jax

            if jax.default_backend() == "tpu":
                pytest.skip("TPU host: kernel genuinely available")
            with config.configure(pallas_scatter=True):
                assert pallas_scatter.impl_token() == "xla"
                assert (
                    pallas_scatter.scatter_max(
                        np.zeros((1, 8), np.int32),
                        np.ones((1, 8), np.int32),
                        hll.M,
                    )
                    is None
                )
        finally:
            pallas_scatter._reset_probe_for_tests()


def _profile_like_data(n=8192, seed=3):
    rng = np.random.default_rng(seed)
    return {
        "x": rng.normal(size=n).astype(np.float32),
        "y": rng.normal(size=n).astype(np.float32),
        "id": rng.integers(0, 1 << 30, n),
    }


PALLAS_ANALYZERS = [
    ApproxCountDistinct("x"),
    ApproxCountDistinct("y"),
    ApproxCountDistinct("id"),
    ApproxQuantile("x", 0.5),
    ApproxQuantile("y", 0.5),
    Mean("x"),
]


class TestPallasScatterEngine:
    """Full-run metric equality with the kernel wired into the fused
    scan (the plan-cache key carries the resolved impl token, so the
    flag flip really recompiles)."""

    def test_resident(self, pallas_interpret):
        data = _profile_like_data()
        on = _values(
            Dataset.from_pydict(data), PALLAS_ANALYZERS,
            pallas_scatter=True,
        )
        off = _values(
            Dataset.from_pydict(data), PALLAS_ANALYZERS,
            pallas_scatter=False,
        )
        assert on == off

    def test_streaming(self, pallas_interpret):
        data = _profile_like_data(seed=4)
        opts = {"batch_size": 1024, "device_cache_bytes": 0}
        on = _values(
            Dataset.from_pydict(data), PALLAS_ANALYZERS,
            pallas_scatter=True, **opts,
        )
        off = _values(
            Dataset.from_pydict(data), PALLAS_ANALYZERS,
            pallas_scatter=False, **opts,
        )
        assert on == off

    def test_mesh(self, pallas_interpret, cpu_mesh):
        from deequ_tpu.engine.scan import AnalysisEngine

        data = _profile_like_data(seed=5)
        on = _values(
            Dataset.from_pydict(data), PALLAS_ANALYZERS,
            engine=AnalysisEngine(mesh=cpu_mesh), pallas_scatter=True,
        )
        off = _values(
            Dataset.from_pydict(data), PALLAS_ANALYZERS,
            engine=AnalysisEngine(mesh=cpu_mesh), pallas_scatter=False,
        )
        assert on == off


def _widened_gate_data(n=65536, seed=6, mispredict=True):
    """Two i32 columns the STATIC probe cannot pool (span > 4*D) but
    the runtime gate can: batch 1 is mid-cardinality (~1000 distinct,
    seeding a low-cardinality register estimate), batch 2 is either
    mid-cardinality again (gate predicted right, dict path wins) or,
    with ``mispredict``, >16384 distinct — the gate says dict but the
    in-kernel U<=D probe must catch it and fall back to the scatter.
    All values sit inside the f32 24-bit mantissa so the pooled f32
    cast is exact."""
    rng = np.random.default_rng(seed)
    half = n // 2
    lo = rng.choice(np.arange(0, 200_000, 7), 1000, replace=False)
    batch1 = lo[rng.integers(0, 1000, half)]
    if mispredict:
        batch2 = np.arange(half) * 7 + rng.integers(0, 3, half)
    else:
        batch2 = lo[rng.integers(0, 1000, half)]
    cols = {}
    for i, rot in enumerate((0, half // 3)):
        cols[f"c{i}"] = np.concatenate(
            [batch1, np.roll(batch2, rot)]
        ).astype(np.int32)
    assert all(
        int(v.max()) < (1 << 24) and int(v.min()) >= 0
        for v in cols.values()
    )
    assert all(
        int(v.max()) - int(v.min()) > 4 * hll.DEDUP_DICT_CAP
        for v in cols.values()
    )
    return cols


GATE_ANALYZERS = [
    ApproxCountDistinct("c0"),
    ApproxCountDistinct("c1"),
    ApproxQuantile("c0", 0.5),
    ApproxQuantile("c1", 0.5),
]


class TestWidenedDedupGate:
    """Widening on vs off: identical metrics (the gate only changes
    WHICH program computes the registers, never the registers)."""

    @pytest.mark.parametrize("mispredict", [False, True])
    def test_resident(self, mispredict):
        data = _widened_gate_data(mispredict=mispredict)
        opts = {"batch_size": 32768}
        on = _values(
            Dataset.from_pydict(data), GATE_ANALYZERS,
            hll_dedup_widening=True, **opts,
        )
        off = _values(
            Dataset.from_pydict(data), GATE_ANALYZERS,
            hll_dedup_widening=False, **opts,
        )
        assert on == off

    @pytest.mark.parametrize("mispredict", [False, True])
    def test_streaming(self, mispredict):
        data = _widened_gate_data(seed=7, mispredict=mispredict)
        opts = {"batch_size": 32768, "device_cache_bytes": 0}
        on = _values(
            Dataset.from_pydict(data), GATE_ANALYZERS,
            hll_dedup_widening=True, **opts,
        )
        off = _values(
            Dataset.from_pydict(data), GATE_ANALYZERS,
            hll_dedup_widening=False, **opts,
        )
        assert on == off

    def test_mesh(self, cpu_mesh):
        from deequ_tpu.engine.scan import AnalysisEngine

        data = _widened_gate_data(seed=8)
        on = _values(
            Dataset.from_pydict(data), GATE_ANALYZERS,
            engine=AnalysisEngine(mesh=cpu_mesh),
            hll_dedup_widening=True, batch_size=32768,
        )
        off = _values(
            Dataset.from_pydict(data), GATE_ANALYZERS,
            engine=AnalysisEngine(mesh=cpu_mesh),
            hll_dedup_widening=False, batch_size=32768,
        )
        assert on == off

    def test_planner_gates_only_qualifying_columns(self, monkeypatch):
        """Structural: the runtime gate set contains exactly the
        KLL-covered integer columns the static probe could NOT pool —
        statically-poolable columns stay unconditional, columns with
        no KLL coverage stay on the plain scatter (zero added cost)."""
        from deequ_tpu.engine import vectorize

        rng = np.random.default_rng(9)
        n = 4096
        data = {
            # span < 4*D and inside the mantissa: statically pooled
            "narrow": rng.integers(0, 1000, n).astype(np.int32),
            # wide span, KLL-covered: runtime gated
            "wide": rng.integers(0, 1 << 20, n).astype(np.int32),
            # wide span, NO KLL analyzer: not in the candidate pool
            "nokll": rng.integers(0, 1 << 20, n).astype(np.int32),
        }
        analyzers = [
            ApproxCountDistinct("narrow"),
            ApproxCountDistinct("wide"),
            ApproxCountDistinct("nokll"),
            ApproxQuantile("narrow", 0.5),
            ApproxQuantile("wide", 0.5),
        ]
        captured = []
        real = vectorize._build_hll_group

        def spy(dataset, members, value_repr, where, **kwargs):
            captured.append(kwargs.get("runtime_gate_columns"))
            return real(dataset, members, value_repr, where, **kwargs)

        monkeypatch.setattr(vectorize, "_build_hll_group", spy)
        with config.configure(hll_dedup_widening=True):
            units, failures = vectorize.plan_scan_units(
                Dataset.from_pydict(data), analyzers
            )
        assert not failures
        gated = [g for g in captured if g]
        assert gated == [("wide",)], captured

        captured.clear()
        with config.configure(hll_dedup_widening=False):
            vectorize.plan_scan_units(
                Dataset.from_pydict(data), analyzers
            )
        assert [g for g in captured if g] == [], captured
