"""A small SQL-expression compiler for predicates over device columns.

The reference's ``Compliance`` analyzer and ``.where(...)`` filters take
arbitrary Spark SQL expression strings (reference:
``src/main/scala/com/amazon/deequ/analyzers/Compliance.scala``,
``checks/Check.scala``; SURVEY.md §2.2). deequ_tpu keeps that surface but
compiles the expression to pure JAX ops at plan time:

- numeric columns evaluate on their device ``values``;
- string comparisons become *dictionary-code* operations — equality/IN
  become host-side dictionary lookups producing code sets, LIKE/RLIKE
  become a host-side regex sweep over the (small) dictionary producing a
  device bool lookup table gathered by code. Strings never reach the TPU
  (SURVEY.md §7 hard part #3).

Three-valued logic follows SQL: comparisons involving NULL are NULL; a
row "complies" iff the predicate is TRUE (not NULL, not FALSE).

Supported grammar (r4 extends toward the reference's Spark SQL surface;
SURVEY.md §2.2 Compliance = "arbitrary SQL predicate"):

| form | notes |
|---|---|
| OR / AND / NOT | SQL three-valued logic |
| = == != <> < <= > >= | string orderings via shared lexicographic ranks |
| + - * / % , unary - | / and % by zero -> NULL |
| IS [NOT] NULL | |
| [NOT] IN (...) | string or numeric item lists |
| BETWEEN x AND y | |
| [NOT] LIKE 'pat%' / RLIKE 're' | host regex over the dictionary |
| CASE WHEN c THEN v ... [ELSE v] END | numeric/bool OR string branch values (homogeneous) |
| COALESCE(a, b, ...) | numeric/bool OR string arguments (homogeneous) |
| ABS(x) | |
| LENGTH(s) | also over TRIM/UPPER/... and CASE/CONCAT results |
| TRIM/LTRIM/RTRIM(s) | host transform over the dictionary |
| UPPER(s) / LOWER(s) | compose freely, e.g. UPPER(TRIM(s)) |
| SUBSTR/SUBSTRING(s, pos[, len]) | Spark 1-based semantics |
| CONCAT(...) | any mix of string columns/expressions and literals (cross-dictionary product bounded by a 65536-entry plan budget) |
| CAST(x AS INT/BIGINT/DOUBLE/...) | string operands parse per dictionary entry, unparseable -> NULL; timestamp columns -> epoch SECONDS (floor for integral targets) |
| CAST(x AS STRING) | string operands (identity) and boolean columns ('true'/'false') |
| ts_col <op> 'YYYY-MM-DD[ HH:MM:SS]' | date literal in the column's unit |
| DATE_ADD(ts_col, n) / DATE_SUB | shifts by whole days in the column's unit |
| DATEDIFF(a, b) | UTC-day difference; timestamp columns and/or date literals |
| literals | numbers, 'strings', TRUE/FALSE/NULL |

String functions never reach the device: they evaluate host-side over
the (small) column dictionary, composing into per-code lookup tables —
string-valued CASE/COALESCE, multi-column CONCAT and CAST(bool AS
STRING) build SYNTHETIC dictionaries (union / cross-product /
'true'-'false') whose codes the device selects with the same gathers
(SURVEY.md §7 hard part #3). Unsupported syntax fails at PLANNING time
(PredicateParseError), which the runner degrades to that analyzer's
failure metric — never a crash mid-scan.

Known not-yet-implemented vs full Spark SQL (documented, degrade
cleanly): timezone-aware date semantics (DATEDIFF counts UTC days),
and CAST of numeric/timestamp VALUES to STRING (Java number/timestamp
formatting; compare numerically instead).
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from deequ_tpu.data.table import ColumnRequest, Dataset, Kind

# --------------------------------------------------------------------------
# Tokenizer
# --------------------------------------------------------------------------

_TOKEN_RE = re.compile(
    r"""
    (?P<ws>\s+)
  | (?P<number>\d+\.\d*(?:[eE][+-]?\d+)?|\.\d+(?:[eE][+-]?\d+)?|\d+(?:[eE][+-]?\d+)?)
  | (?P<string>'(?:[^'\\]|\\.)*'|"(?:[^"\\]|\\.)*")
  | (?P<bq_ident>`[^`]+`)
  | (?P<op><=|>=|!=|<>|==|=|<|>|\+|-|\*|/|%|\(|\)|,)
  | (?P<ident>[A-Za-z_][A-Za-z_0-9.]*)
    """,
    re.VERBOSE,
)

_KEYWORDS = {
    "AND", "OR", "NOT", "IS", "NULL", "IN", "BETWEEN", "LIKE", "RLIKE",
    "TRUE", "FALSE", "CASE", "WHEN", "THEN", "ELSE", "END", "CAST", "AS",
}


@dataclass(frozen=True)
class Token:
    kind: str  # 'number' | 'string' | 'ident' | 'op' | 'kw'
    text: str


def tokenize(expression: str) -> List[Token]:
    tokens: List[Token] = []
    pos = 0
    while pos < len(expression):
        m = _TOKEN_RE.match(expression, pos)
        if not m:
            raise PredicateParseError(
                f"cannot tokenize {expression[pos:pos + 20]!r} in predicate"
            )
        pos = m.end()
        if m.lastgroup == "ws":
            continue
        text = m.group()
        kind = m.lastgroup
        if kind == "bq_ident":
            tokens.append(Token("ident", text[1:-1]))
        elif kind == "ident" and text.upper() in _KEYWORDS:
            tokens.append(Token("kw", text.upper()))
        else:
            tokens.append(Token(kind, text))
    return tokens


class PredicateParseError(ValueError):
    pass


# --------------------------------------------------------------------------
# AST
# --------------------------------------------------------------------------


@dataclass(frozen=True)
class Node:
    pass


@dataclass(frozen=True)
class ColumnRef(Node):
    name: str


@dataclass(frozen=True)
class NumberLit(Node):
    value: float


@dataclass(frozen=True)
class StringLit(Node):
    value: str


@dataclass(frozen=True)
class BoolLit(Node):
    value: bool


@dataclass(frozen=True)
class NullLit(Node):
    pass


@dataclass(frozen=True)
class UnaryOp(Node):
    op: str  # 'NOT' | 'NEG'
    operand: Node


@dataclass(frozen=True)
class BinOp(Node):
    op: str  # 'AND','OR','=','!=','<','<=','>','>=','+','-','*','/','%'
    left: Node
    right: Node


@dataclass(frozen=True)
class IsNull(Node):
    operand: Node
    negate: bool


@dataclass(frozen=True)
class InList(Node):
    operand: Node
    items: Tuple[Node, ...]
    negate: bool


@dataclass(frozen=True)
class Between(Node):
    operand: Node
    low: Node
    high: Node


@dataclass(frozen=True)
class Like(Node):
    operand: Node
    pattern: str
    regex: bool
    negate: bool


@dataclass(frozen=True)
class CaseWhen(Node):
    """CASE WHEN c1 THEN v1 [WHEN c2 THEN v2 ...] [ELSE v] END."""

    whens: Tuple[Tuple[Node, Node], ...]
    else_: Optional[Node]


@dataclass(frozen=True)
class Cast(Node):
    """CAST(expr AS type); numeric targets only (INT truncates toward
    zero; string operands parse per dictionary entry, unparseable ->
    NULL, Spark's cast semantics)."""

    operand: Node
    type_name: str  # 'INT' | 'BIGINT' | 'LONG' | 'FLOAT' | 'DOUBLE'


@dataclass(frozen=True)
class StarLit(Node):
    """The `*` inside COUNT(*) (aggregate expressions only)."""


@dataclass(frozen=True)
class FuncCall(Node):
    name: str
    args: Tuple[Node, ...]


class _Parser:
    def __init__(self, tokens: List[Token]):
        self.tokens = tokens
        self.pos = 0

    def peek(self) -> Optional[Token]:
        return self.tokens[self.pos] if self.pos < len(self.tokens) else None

    def next(self) -> Token:
        tok = self.peek()
        if tok is None:
            raise PredicateParseError("unexpected end of predicate")
        self.pos += 1
        return tok

    def accept(self, kind: str, text: Optional[str] = None) -> Optional[Token]:
        tok = self.peek()
        if tok and tok.kind == kind and (text is None or tok.text == text):
            return self.next()
        return None

    def expect(self, kind: str, text: Optional[str] = None) -> Token:
        tok = self.accept(kind, text)
        if tok is None:
            got = self.peek()
            raise PredicateParseError(
                f"expected {text or kind}, got {got.text if got else 'EOF'!r}"
            )
        return tok

    def parse(self) -> Node:
        node = self.or_expr()
        if self.peek() is not None:
            raise PredicateParseError(
                f"trailing tokens starting at {self.peek().text!r}"
            )
        return node

    def or_expr(self) -> Node:
        node = self.and_expr()
        while self.accept("kw", "OR"):
            node = BinOp("OR", node, self.and_expr())
        return node

    def and_expr(self) -> Node:
        node = self.not_expr()
        while self.accept("kw", "AND"):
            node = BinOp("AND", node, self.not_expr())
        return node

    def not_expr(self) -> Node:
        if self.accept("kw", "NOT"):
            return UnaryOp("NOT", self.not_expr())
        return self.comparison()

    def comparison(self) -> Node:
        node = self.additive()
        tok = self.peek()
        if tok is None:
            return node
        if tok.kind == "op" and tok.text in ("=", "==", "!=", "<>", "<", "<=", ">", ">="):
            self.next()
            op = {"==": "=", "<>": "!="}.get(tok.text, tok.text)
            return BinOp(op, node, self.additive())
        if tok.kind == "kw" and tok.text == "IS":
            self.next()
            negate = self.accept("kw", "NOT") is not None
            self.expect("kw", "NULL")
            return IsNull(node, negate)
        negate = False
        if tok.kind == "kw" and tok.text == "NOT":
            nxt = (
                self.tokens[self.pos + 1]
                if self.pos + 1 < len(self.tokens)
                else None
            )
            if nxt and nxt.kind == "kw" and nxt.text in ("IN", "LIKE", "RLIKE"):
                self.next()
                negate = True
                tok = self.peek()
        if tok and tok.kind == "kw" and tok.text == "IN":
            self.next()
            self.expect("op", "(")
            items = [self.additive()]
            while self.accept("op", ","):
                items.append(self.additive())
            self.expect("op", ")")
            return InList(node, tuple(items), negate)
        if tok and tok.kind == "kw" and tok.text == "BETWEEN":
            self.next()
            low = self.additive()
            self.expect("kw", "AND")
            high = self.additive()
            return Between(node, low, high)
        if tok and tok.kind == "kw" and tok.text in ("LIKE", "RLIKE"):
            self.next()
            pat = self.next()
            if pat.kind != "string":
                raise PredicateParseError(
                    f"{tok.text} expects a string pattern"
                )
            return Like(
                node,
                _unquote(pat.text),
                regex=tok.text == "RLIKE",
                negate=negate,
            )
        return node

    def additive(self) -> Node:
        node = self.multiplicative()
        while True:
            tok = self.peek()
            if tok and tok.kind == "op" and tok.text in ("+", "-"):
                self.next()
                node = BinOp(tok.text, node, self.multiplicative())
            else:
                return node

    def multiplicative(self) -> Node:
        node = self.unary()
        while True:
            tok = self.peek()
            if tok and tok.kind == "op" and tok.text in ("*", "/", "%"):
                self.next()
                node = BinOp(tok.text, node, self.unary())
            else:
                return node

    def unary(self) -> Node:
        if self.accept("op", "-"):
            return UnaryOp("NEG", self.unary())
        return self.primary()

    def primary(self) -> Node:
        tok = self.next()
        if tok.kind == "kw" and tok.text == "CAST":
            self.expect("op", "(")
            operand = self.or_expr()
            self.expect("kw", "AS")
            type_tok = self.next()
            if type_tok.kind != "ident":
                raise PredicateParseError(
                    f"CAST expects a type name, got {type_tok.text!r}"
                )
            self.expect("op", ")")
            return Cast(operand, type_tok.text.upper())
        if tok.kind == "kw" and tok.text == "CASE":
            whens: List[Tuple[Node, Node]] = []
            while self.accept("kw", "WHEN"):
                cond = self.or_expr()
                self.expect("kw", "THEN")
                whens.append((cond, self.or_expr()))
            if not whens:
                raise PredicateParseError(
                    "CASE requires at least one WHEN ... THEN branch"
                )
            else_ = self.or_expr() if self.accept("kw", "ELSE") else None
            self.expect("kw", "END")
            return CaseWhen(tuple(whens), else_)
        if tok.kind == "number":
            return NumberLit(float(tok.text))
        if tok.kind == "string":
            return StringLit(_unquote(tok.text))
        if tok.kind == "kw" and tok.text == "TRUE":
            return BoolLit(True)
        if tok.kind == "kw" and tok.text == "FALSE":
            return BoolLit(False)
        if tok.kind == "kw" and tok.text == "NULL":
            return NullLit()
        if tok.kind == "op" and tok.text == "(":
            node = self.or_expr()
            self.expect("op", ")")
            return node
        if tok.kind == "ident":
            if self.accept("op", "("):
                args: List[Node] = []
                if tok.text.upper() == "COUNT" and self.accept("op", "*"):
                    args.append(StarLit())  # COUNT(*) only
                    self.expect("op", ")")
                elif not self.accept("op", ")"):
                    args.append(self.or_expr())
                    while self.accept("op", ","):
                        args.append(self.or_expr())
                    self.expect("op", ")")
                return FuncCall(tok.text.upper(), tuple(args))
            return ColumnRef(tok.text)
        raise PredicateParseError(f"unexpected token {tok.text!r}")


def _unquote(s: str) -> str:
    body = s[1:-1]
    return re.sub(r"\\(.)", r"\1", body)


def parse_predicate(expression: str) -> Node:
    return _Parser(tokenize(expression)).parse()


def _validate_date_literal(text: str) -> None:
    """The ONE date-literal validation (plan time); comparison and
    DATEDIFF literals must accept/reject identically."""
    import datetime as _dt

    try:
        _dt.datetime.fromisoformat(text)
    except ValueError as exc:
        raise PredicateParseError(
            f"{text!r} is not a date/timestamp literal "
            "(YYYY-MM-DD[ HH:MM:SS])"
        ) from exc


def _sql_like_to_regex(pattern: str) -> str:
    out = []
    for ch in pattern:
        if ch == "%":
            out.append(".*")
        elif ch == "_":
            out.append(".")
        else:
            out.append(re.escape(ch))
    return "^" + "".join(out) + "$"


# --------------------------------------------------------------------------
# Compiler: AST -> (requests, traced eval over batch)
# --------------------------------------------------------------------------

# An evaluated expression: (values, valid) with SQL null semantics, or for
# booleans (truth, valid). `values` may be numeric or int32 codes tagged
# with the column whose dictionary they index.


@dataclass
class _Val:
    values: jnp.ndarray
    valid: jnp.ndarray
    is_bool: bool = False
    codes_of: Optional[str] = None  # column name whose dictionary applies
    # host-side string transform composed over the dictionary (TRIM/
    # UPPER/LOWER/SUBSTR chains): consumers build per-code LUTs from
    # transform(dict[i]) instead of dict[i]; None = raw values
    transform: Optional[Callable[[str], str]] = None
    # SYNTHETIC string lane (string-valued CASE/COALESCE, multi-column
    # CONCAT, CAST(bool AS STRING)): ``values`` are codes into this
    # tuple instead of a column dictionary; entries may be None for
    # never-selected slots (row validity governs). codes_of stays None.
    entries: Optional[Tuple[Optional[str], ...]] = None
    # timestamp/date lane: ``ts_per_day`` = how many epoch units make
    # one UTC day (set for TIMESTAMP/date columns and DATE_ADD results;
    # 1 = day-valued). Comparisons convert string literals into this
    # unit, and mixed-unit lanes normalize to the finer unit.
    # ``ts_col`` names the source column when the values are its RAW
    # storage epochs (literal conversion then goes through the exact
    # Arrow cast); None for derived day-valued lanes.
    ts_col: Optional[str] = None
    ts_per_day: Optional[int] = None

    def view(self, value: str) -> str:
        return self.transform(value) if self.transform else value


class _PredicateData:
    """What predicate evaluation may touch: the schema (strong) and the
    dictionaries (weak — only string predicates dereference them, and
    only at trace time while the owning run holds the dataset)."""

    __slots__ = ("schema", "_ref")

    def __init__(self, schema, ref):
        self.schema = schema
        self._ref = ref

    def dictionary(self, column: str):
        dataset = self._ref()
        if dataset is None:  # pragma: no cover — contract violation
            raise RuntimeError(
                "string predicate outlived its dataset; string "
                "predicates are only traced while the owning run holds "
                "the data"
            )
        return dataset.dictionary(column)

    def arrow_type(self, column: str):
        """Storage type (timestamp predicates need the epoch unit)."""
        dataset = self._ref()
        if dataset is None:  # pragma: no cover — contract violation
            raise RuntimeError(
                "timestamp predicate outlived its dataset; it is only "
                "traced while the owning run holds the data"
            )
        return dataset._column_arrow_type(column)


class CompiledPredicate:
    """A predicate compiled against a dataset's schema + dictionaries.

    ``requests`` lists the device columns needed; ``evaluate(batch)`` is
    traceable and returns (truth: bool array, valid: bool array). A row
    complies iff truth & valid.
    """

    def __init__(
        self,
        node: Node,
        dataset: Dataset,
        columns_used: Sequence[str],
        requests: Sequence[ColumnRequest],
    ):
        import weakref

        self._node = node
        # WEAK reference: compiled predicates end up inside jitted
        # closures that the cross-run plan cache retains — a strong ref
        # would pin the whole Arrow table for the cache's lifetime. The
        # dataset is only dereferenced at TRACE time (schema lookups,
        # dictionary lookups for string predicates), which happens while
        # the owning run still holds the dataset.
        self._dataset_ref = weakref.ref(dataset)
        self._schema = dataset.schema
        self.columns_used = tuple(columns_used)
        self.requests = tuple(requests)
        # a predicate touching NO string and NO timestamp column
        # evaluates identically on any dataset with the same schema
        # kinds (no dictionary-derived constants and no unit-dependent
        # epoch literals get baked into its closure) — the engine's
        # plan cache may reuse compiled scans across datasets only then
        self.dataset_independent = all(
            dataset.schema.kind_of(c) not in (Kind.STRING, Kind.TIMESTAMP)
            for c in self.columns_used
        )

    @property
    def _dataset(self) -> "_PredicateData":
        # shim: schema strongly held (all a NUMERIC predicate touches,
        # incl. on re-trace after the origin dataset is gone);
        # dictionaries resolve through the weakref (string predicates
        # only — those are never in cached cross-dataset plans)
        return _PredicateData(self._schema, self._dataset_ref)

    def evaluate(self, batch: Dict[str, jnp.ndarray]) -> Tuple[jnp.ndarray, jnp.ndarray]:
        val = _eval(self._node, batch, self._dataset)
        truth, valid = _as_bool(val)
        return truth, valid

    def complies(self, batch: Dict[str, jnp.ndarray]) -> jnp.ndarray:
        truth, valid = self.evaluate(batch)
        return truth & valid


def compile_predicate(expression: str, dataset: Dataset) -> CompiledPredicate:
    # per-dataset compile cache: device_requests() and make_ops() both
    # compile the same expressions during planning
    cache = getattr(dataset, "_predicate_cache", None)
    if cache is None:
        cache = {}
        setattr(dataset, "_predicate_cache", cache)
    if expression in cache:
        return cache[expression]
    node = parse_predicate(expression)
    cols = sorted(_columns_of(node))
    schema = dataset.schema
    requests: List[ColumnRequest] = []
    for c in cols:
        if not schema.has_column(c):
            raise KeyError(f"predicate references unknown column '{c}'")
        kind = schema.kind_of(c)
        if kind == Kind.STRING:
            requests.append(ColumnRequest(c, "codes"))
        else:
            requests.append(ColumnRequest(c, "values"))
        requests.append(ColumnRequest(c, "mask"))
    for col in _length_columns_of(node):
        requests.append(ColumnRequest(col, "lengths"))
    # static type check NOW (make_ops/planning time) so a bad predicate
    # degrades to THAT analyzer's failure metric — a raise later, inside
    # the shared fused-scan trace, would poison every co-scheduled
    # analyzer in the pass
    _check_types(node, schema)
    _check_plan_budgets(node, dataset)
    compiled = CompiledPredicate(node, dataset, cols, requests)
    cache[expression] = compiled
    return compiled


def _check_types(node: Node, schema) -> str:
    """Static kind inference: returns 'string' | 'stringlit' | 'value' |
    'null'; raises PredicateParseError on string/numeric mixes that the
    runtime would otherwise hit mid-trace."""

    def kind_of(n: Node) -> str:
        if isinstance(n, ColumnRef):
            k = schema.kind_of(n.name)
            if k == Kind.STRING:
                return "string"
            if k == Kind.TIMESTAMP:
                return "timestamp"
            return "value"
        if isinstance(n, StringLit):
            return "stringlit"
        if isinstance(n, NullLit):
            return "null"
        if isinstance(n, (NumberLit, BoolLit)):
            return "value"
        if isinstance(n, UnaryOp):
            k = kind_of(n.operand)
            if k in ("string", "stringlit"):
                raise PredicateParseError(
                    f"{'negation' if n.op == 'NEG' else 'NOT'} is "
                    "undefined for string operands"
                )
            return "value"
        if isinstance(n, IsNull):
            kind_of(n.operand)
            return "value"
        if isinstance(n, Between):
            check_cmp(n.operand, n.low)
            check_cmp(n.operand, n.high)
            return "value"
        if isinstance(n, CaseWhen):
            results = [r for _, r in n.whens]
            if n.else_ is not None:
                results.append(n.else_)
            for cond, _ in n.whens:
                if kind_of(cond) in ("string", "stringlit"):
                    raise PredicateParseError(
                        "a CASE condition must be boolean, not a bare "
                        "string operand"
                    )
            return _homogeneous_branches(
                [kind_of(r) for r in results], "CASE"
            )
        if isinstance(n, InList):
            base = kind_of(n.operand)
            for item in n.items:
                if isinstance(item, NullLit):
                    continue
                item_kind = kind_of(item)
                if base == "string" and item_kind != "stringlit":
                    raise PredicateParseError(
                        "IN on a string column requires string literals"
                    )
                if base != "string" and item_kind == "stringlit":
                    raise PredicateParseError(
                        "IN with string literals requires a string column"
                    )
            return "value"
        if isinstance(n, Like):
            if kind_of(n.operand) != "string":
                raise PredicateParseError("LIKE requires a string column")
            return "value"
        if isinstance(n, Cast):
            if (
                n.type_name not in _CAST_TYPES
                and n.type_name not in _STRING_CASTS
            ):
                raise PredicateParseError(
                    f"CAST to {n.type_name} is not supported "
                    "(numeric or STRING targets)"
                )
            k = kind_of(n.operand)
            if k == "stringlit":
                raise PredicateParseError(
                    "CAST of a string literal is constant"
                )
            if n.type_name in _STRING_CASTS:
                if k == "string":
                    return "string"
                if (
                    isinstance(n.operand, ColumnRef)
                    and schema.kind_of(n.operand.name) == Kind.BOOLEAN
                ):
                    return "string"
                raise PredicateParseError(
                    "CAST to STRING supports string and boolean "
                    "operands only (numeric/timestamp formatting is "
                    "not supported)"
                )
            if k == "timestamp" and not isinstance(n.operand, ColumnRef):
                # day-valued DATE_ADD/DATE_SUB results are DATEs;
                # Spark refuses date -> numeric casts
                raise PredicateParseError(
                    "CAST of a date value to a number is not "
                    "supported (Spark refuses date -> numeric)"
                )
            # timestamp COLUMNS cast to epoch seconds (Spark); the
            # date-typed-column refusal needs the arrow type and lives
            # in _check_plan_budgets
            return "value"
        if isinstance(n, FuncCall):
            # the predicate evaluator supports only these functions;
            # aggregates (SUM/COUNT/...) belong to CustomSql expressions
            # and must fail HERE (planning time), not mid-trace where
            # they would poison every co-scheduled analyzer
            if n.name not in (
                "ABS", "LENGTH", "COALESCE", "CONCAT",
                "DATE_ADD", "DATE_SUB", "DATEDIFF",
            ) + _STRING_FNS:
                raise PredicateParseError(
                    f"unsupported function {n.name} in a predicate"
                )
            if n.name in ("DATE_ADD", "DATE_SUB"):
                if len(n.args) != 2:
                    raise PredicateParseError(
                        f"{n.name} takes (timestamp column, days)"
                    )
                if kind_of(n.args[0]) != "timestamp":
                    raise PredicateParseError(
                        f"{n.name} requires a timestamp/date column"
                    )
                _static_int(n.args[1], f"{n.name} day count")
                return "timestamp"
            if n.name == "DATEDIFF":
                if len(n.args) != 2:
                    raise PredicateParseError(
                        "DATEDIFF takes (end, start)"
                    )
                kinds_ = []
                for a in n.args:
                    k = kind_of(a)
                    if k == "stringlit":
                        assert isinstance(a, StringLit)
                        _validate_date_literal(a.value)
                    elif k != "timestamp":
                        raise PredicateParseError(
                            "DATEDIFF arguments must be timestamp "
                            "columns or date literals"
                        )
                    kinds_.append(k)
                if all(k == "stringlit" for k in kinds_):
                    raise PredicateParseError(
                        "DATEDIFF of two literals is constant"
                    )
                return "value"
            if n.name == "CONCAT":
                if not n.args:
                    raise PredicateParseError("CONCAT needs arguments")
                col_args = 0
                for a in n.args:
                    k = kind_of(a)
                    if k == "string":
                        col_args += 1
                    elif k != "stringlit":
                        raise PredicateParseError(
                            "CONCAT arguments must be strings"
                        )
                if col_args == 0:
                    raise PredicateParseError(
                        "CONCAT of only literals is constant"
                    )
                # multi-column CONCAT builds a cross-product synthetic
                # dictionary; its SIZE is validated against the plan
                # budget in _check_plan_budgets (needs dictionaries)
                return "string"
            for a in n.args:
                if isinstance(a, StarLit):
                    raise PredicateParseError(
                        f"* is not a valid argument to {n.name}"
                    )
            if n.name in _STRING_FNS:
                # FULL static validation here: a raise later, inside
                # the shared fused-scan trace, would poison every
                # co-scheduled analyzer (this module's core invariant)
                if n.name in ("SUBSTR", "SUBSTRING"):
                    if len(n.args) not in (2, 3):
                        raise PredicateParseError(
                            f"{n.name} takes (string, pos[, length])"
                        )
                    _static_int(n.args[1], f"{n.name} position")
                    if len(n.args) == 3:
                        _static_int(n.args[2], f"{n.name} length")
                elif len(n.args) != 1:
                    raise PredicateParseError(
                        f"{n.name} takes exactly one argument"
                    )
                if kind_of(n.args[0]) != "string":
                    raise PredicateParseError(
                        f"{n.name} requires a string column operand"
                    )
                return "string"
            if n.name == "COALESCE":
                if not n.args:
                    raise PredicateParseError(
                        "COALESCE needs arguments"
                    )
                return _homogeneous_branches(
                    [kind_of(a) for a in n.args], "COALESCE"
                )
            if n.name == "LENGTH":
                for a in n.args:
                    kind_of(a)
                return "value"
            for a in n.args:
                kind_of(a)
            return "value"
        if isinstance(n, BinOp):
            if n.op in ("AND", "OR"):
                for side in (n.left, n.right):
                    if kind_of(side) in ("string", "stringlit"):
                        raise PredicateParseError(
                            "a bare string operand is not a boolean "
                            f"(in {n.op})"
                        )
                return "value"
            lk, rk = kind_of(n.left), kind_of(n.right)
            if n.op in _CMP:
                check_kinds(lk, rk, n.op)
                check_ts_literal(n.left, lk, n.right, rk)
                return "value"
            # arithmetic
            for k in (lk, rk):
                if k in ("string", "stringlit"):
                    raise PredicateParseError(
                        f"arithmetic {n.op!r} is undefined for string "
                        "operands"
                    )
            return "value"
        return "value"

    def check_kinds(lk: str, rk: str, op: str) -> None:
        stringish = ("string", "stringlit")
        if "null" in (lk, rk):
            return
        # timestamp vs string literal: the literal is a date — valid
        if {"timestamp", "stringlit"} == {lk, rk}:
            return
        if lk == "timestamp":
            lk = "value"
        if rk == "timestamp":
            rk = "value"
        if (lk in stringish) != (rk in stringish):
            raise PredicateParseError(
                "cannot compare a string operand with a non-string "
                "operand (dictionary codes are not values)"
            )
        if lk == "stringlit" and rk == "stringlit":
            raise PredicateParseError(
                f"comparison {op!r} of two string literals is constant"
            )

    def check_ts_literal(a: Node, ak: str, b: Node, bk: str) -> None:
        """A timestamp-vs-string-literal compare carries a STATIC date
        literal — validate it NOW (plan time), not mid-trace."""
        import datetime as _dt

        for node_, kind_, other in ((a, ak, bk), (b, bk, ak)):
            if kind_ == "stringlit" and other == "timestamp":
                assert isinstance(node_, StringLit)
                _validate_date_literal(node_.value)

    def check_cmp(a: Node, b: Node) -> None:
        check_kinds(kind_of(a), kind_of(b), "BETWEEN")
        check_ts_literal(a, kind_of(a), b, kind_of(b))

    return kind_of(node)


def _homogeneous_branches(kinds: List[str], what: str) -> str:
    """CASE/COALESCE result branches must all be stringish or all
    value-ish (NULLs are neutral); returns the result kind."""
    stringish = [k for k in kinds if k in ("string", "stringlit")]
    valueish = [k for k in kinds if k in ("value", "timestamp")]
    if stringish and valueish:
        raise PredicateParseError(
            f"{what} branches mix string and non-string results"
        )
    return "string" if stringish else "value"


def _estimated_entries(node: Node, dataset: Dataset) -> int:
    """Upper bound on a string expression's dictionary size (plan
    time): column lanes count their dictionary, CONCAT multiplies,
    CASE/COALESCE unions sum, literals are 1."""
    if isinstance(node, StringLit):
        return 1
    if isinstance(node, ColumnRef):
        return len(dataset.dictionary(node.name))
    if isinstance(node, FuncCall):
        if node.name == "CONCAT":
            total = 1
            for a in node.args:
                e = _estimated_entries(a, dataset)
                if e > 1:  # literals fold into neighbors
                    total *= e
            return total
        if node.name == "COALESCE":
            return sum(
                _estimated_entries(a, dataset) for a in node.args
            )
        if node.name in _STRING_FNS:
            return _estimated_entries(node.args[0], dataset)
    if isinstance(node, CaseWhen):
        total = sum(
            _estimated_entries(r, dataset) for _, r in node.whens
        )
        if node.else_ is not None:
            total += _estimated_entries(node.else_, dataset)
        return total
    if isinstance(node, Cast):  # CAST(s AS STRING) is identity
        return _estimated_entries(node.operand, dataset)
    return 2  # bool lanes etc.


def _check_plan_budgets(node: Node, dataset: Dataset) -> None:
    """Dictionary-dependent plan-time validation (runs after the
    static type check, with the dataset in hand): CONCAT cross-product
    budgets and the date-typed-column CAST refusal."""
    if isinstance(node, FuncCall) and node.name == "CONCAT":
        est = _estimated_entries(node, dataset)
        if est > _CONCAT_DICT_BUDGET:
            raise PredicateParseError(
                f"CONCAT cross-dictionary size ~{est} exceeds the "
                f"{_CONCAT_DICT_BUDGET}-entry plan budget"
            )
    if (
        isinstance(node, Cast)
        and node.type_name in _CAST_TYPES
        and isinstance(node.operand, ColumnRef)
        and dataset.schema.kind_of(node.operand.name) == Kind.TIMESTAMP
    ):
        import pyarrow as pa

        if pa.types.is_date(dataset._column_arrow_type(node.operand.name)):
            raise PredicateParseError(
                "CAST of a DATE column to a number is not supported "
                "(Spark refuses date -> numeric)"
            )
    for child in _children_of(node):
        _check_plan_budgets(child, dataset)


def _children_of(node: Node):
    """Every child Node, uniformly across node shapes (incl. CASE)."""
    for attr in ("operand", "left", "right", "low", "high", "else_"):
        child = getattr(node, attr, None)
        if isinstance(child, Node):
            yield child
    for attr in ("items", "args"):
        for child in getattr(node, attr, ()):
            if isinstance(child, Node):
                yield child
    for pair in getattr(node, "whens", ()):
        yield pair[0]
        yield pair[1]


def _length_columns_of(node: Node) -> set:
    """Columns appearing as LENGTH(col) — they need the 'lengths' repr."""
    out: set = set()
    if isinstance(node, FuncCall) and node.name == "LENGTH":
        for arg in node.args:
            if isinstance(arg, ColumnRef):
                out.add(arg.name)
    for child in _children_of(node):
        out |= _length_columns_of(child)
    return out


def _columns_of(node: Node) -> set:
    if isinstance(node, ColumnRef):
        return {node.name}
    out: set = set()
    for child in _children_of(node):
        out |= _columns_of(child)
    return out


def _as_bool(v: _Val) -> Tuple[jnp.ndarray, jnp.ndarray]:
    if v.is_bool:
        return v.values.astype(bool), v.valid
    return v.values != 0, v.valid


_CMP = ("=", "!=", "<", "<=", ">", ">=")
_CMP_FNS = {
    "=": jnp.equal,
    "!=": jnp.not_equal,
    "<": jnp.less,
    "<=": jnp.less_equal,
    ">": jnp.greater,
    ">=": jnp.greater_equal,
}


def _is_string_lane(v: "_Val") -> bool:
    """Column-backed (codes_of) OR synthetic (entries) string lane."""
    return v.codes_of is not None or v.entries is not None


def _lane_entries(ds, v: "_Val") -> "list[Optional[str]]":
    """The lane's dictionary as the EXPRESSION sees it: synthetic
    entries verbatim (transforms were folded in at construction);
    column-backed entries through the composed view."""
    if v.entries is not None:
        return list(v.entries)
    return [
        None if x is None else v.view(str(x))
        for x in ds.dictionary(v.codes_of)
    ]


def _dict_lookup(dataset: Dataset, column: str, value: str) -> int:
    dictionary = dataset.dictionary(column)
    matches = np.nonzero(dictionary == value)[0]
    return int(matches[0]) if len(matches) else -2  # -2: matches nothing


def _string_eq_lut(ds: Dataset, base: "_Val", literal: str) -> jnp.ndarray:
    """Per-code bool LUT for ``view(entry[i]) == literal`` — required
    when a transform applies (several raw entries may map to the same
    transformed value, so a single-code lookup can't represent it) and
    for synthetic lanes."""
    view = _lane_entries(ds, base)
    table = np.zeros(len(view) + 1, dtype=bool)
    for i, s in enumerate(view):
        if s is not None and s == literal:
            table[i] = True
    lut = jnp.asarray(table)
    idx = jnp.where(base.values < 0, len(view), base.values)
    return lut[jnp.clip(idx, 0, len(view))]


def _rank_table(
    views: "list[list[str]]", extra: "list[str]"
) -> "dict[str, int]":
    """Lexicographic rank of every distinct string across the given
    (already-transformed) dictionary views (+ literals): the shared
    value domain that makes codes from unrelated dictionaries — or
    transformed views of them — comparable."""
    values = set(extra)
    for view in views:
        values.update(v for v in view if v is not None)
    return {v: i for i, v in enumerate(sorted(values))}


def _dict_view(ds: Dataset, val: "_Val") -> "list[Optional[str]]":
    """The dictionary as the expression sees it: transform applied
    (synthetic lanes included)."""
    return _lane_entries(ds, val)


def _ranks_for(
    view: "list[Optional[str]]", rank: "dict[str, int]"
) -> np.ndarray:
    """int32 LUT code -> shared rank; one trailing slot (-1) for null
    codes so a single clipped gather covers every code."""
    out = np.full(len(view) + 1, -1, dtype=np.int32)
    for i, v in enumerate(view):
        if v is not None:
            out[i] = rank[v]
    return out


def _gather_ranks(lut: np.ndarray, codes: jnp.ndarray) -> jnp.ndarray:
    table = jnp.asarray(lut)
    idx = jnp.where(codes < 0, table.shape[0] - 1, codes)
    return table[jnp.clip(idx, 0, table.shape[0] - 1)]


def _shared_rank_luts(dataset: Dataset, a: "_Val", b: "_Val"):
    va, vb = _dict_view(dataset, a), _dict_view(dataset, b)
    rank = _rank_table(
        [[x for x in va if x is not None], [x for x in vb if x is not None]],
        [],
    )
    return _ranks_for(va, rank), _ranks_for(vb, rank)


def _rank_lut_with_literal(dataset: Dataset, base: "_Val", literal: str):
    view = _dict_view(dataset, base)
    rank = _rank_table([[x for x in view if x is not None]], [literal])
    return _ranks_for(view, rank), rank[literal]


_STRING_FNS = ("TRIM", "LTRIM", "RTRIM", "UPPER", "LOWER", "SUBSTR",
               "SUBSTRING")
_CAST_TYPES = (
    "INT", "INTEGER", "BIGINT", "LONG", "SMALLINT", "TINYINT",
    "FLOAT", "DOUBLE", "REAL",
)
_INT_CASTS = ("INT", "INTEGER", "BIGINT", "LONG", "SMALLINT", "TINYINT")
_STRING_CASTS = ("STRING", "VARCHAR", "TEXT")
# cap on a synthetic cross-product dictionary (multi-column CONCAT):
# host-side string materialization + per-code LUT sizes stay bounded
_CONCAT_DICT_BUDGET = 1 << 16
# JVM d2i-style saturation bounds per integral target (f64 lane: the
# i64 bounds round to the nearest representable double)
_INT_CAST_BOUNDS = {
    "INT": (-2147483648.0, 2147483647.0),
    "INTEGER": (-2147483648.0, 2147483647.0),
    "BIGINT": (-9.223372036854776e18, 9.223372036854776e18),
    "LONG": (-9.223372036854776e18, 9.223372036854776e18),
    "SMALLINT": (-32768.0, 32767.0),
    "TINYINT": (-128.0, 127.0),
}


def _static_int(node: Node, what: str) -> int:
    """A SUBSTR position/length argument must be a static integer."""
    if isinstance(node, UnaryOp) and node.op == "NEG":
        return -_static_int(node.operand, what)
    if isinstance(node, NumberLit) and float(node.value).is_integer():
        return int(node.value)
    raise PredicateParseError(f"{what} must be an integer literal")


def _substr(s: str, pos: int, length: Optional[int]) -> str:
    """Spark substring semantics: 1-based; pos 0 behaves like 1;
    negative pos counts from the end; negative length -> empty."""
    if pos > 0:
        start = pos - 1
    elif pos < 0:
        start = max(len(s) + pos, 0)
    else:
        start = 0
    if length is None:
        return s[start:]
    if length <= 0:
        return ""
    return s[start:start + length]


def _eval_string_fn(
    node: "FuncCall", batch: Dict[str, jnp.ndarray], ds: Dataset
) -> "_Val":
    """TRIM/LTRIM/RTRIM/UPPER/LOWER/SUBSTR compose a host-side
    transform over the operand's dictionary view; codes/validity pass
    through untouched (the device never sees strings)."""
    if node.name in ("SUBSTR", "SUBSTRING"):
        if len(node.args) not in (2, 3):
            raise PredicateParseError(
                f"{node.name} takes (string, pos[, length])"
            )
        base = _eval(node.args[0], batch, ds)
        pos = _static_int(node.args[1], f"{node.name} position")
        length = (
            _static_int(node.args[2], f"{node.name} length")
            if len(node.args) == 3
            else None
        )
        inner = base.view

        def transform(s: str, _pos=pos, _len=length, _inner=inner):
            return _substr(_inner(s), _pos, _len)

    else:
        if len(node.args) != 1:
            raise PredicateParseError(
                f"{node.name} takes exactly one argument"
            )
        base = _eval(node.args[0], batch, ds)
        inner = base.view
        fn = {
            "TRIM": str.strip,
            "LTRIM": str.lstrip,
            "RTRIM": str.rstrip,
            "UPPER": str.upper,
            "LOWER": str.lower,
        }[node.name]

        def transform(s: str, _fn=fn, _inner=inner):
            return _fn(_inner(s))

    if base.entries is not None:
        # synthetic lane: entries are final strings — apply the
        # function eagerly instead of composing a lazy transform
        return _Val(
            base.values,
            base.valid,
            entries=tuple(
                None if e is None else transform(e)
                for e in base.entries
            ),
        )
    if base.codes_of is None:
        raise PredicateParseError(
            f"{node.name} requires a string column operand"
        )
    return _Val(
        base.values, base.valid, codes_of=base.codes_of,
        transform=transform,
    )


def _units_per_day(arrow_type) -> int:
    """How many of the column's int64 epoch units make one UTC day
    (mirrors the values-repr cast in data.table.convert_basic_repr)."""
    import pyarrow as pa

    if pa.types.is_date32(arrow_type):
        return 1
    if pa.types.is_date64(arrow_type):
        return 86_400_000
    unit = getattr(arrow_type, "unit", "us")
    return 86_400 * {
        "s": 1, "ms": 1_000, "us": 1_000_000, "ns": 1_000_000_000
    }[unit]


def _epoch_days_of_literal(literal: str) -> int:
    import datetime as _dt

    d = _dt.datetime.fromisoformat(literal).date()
    return (d - _dt.date(1970, 1, 1)).days


def _date_literal_epoch(ds, column: str, literal: str) -> int:
    """'YYYY-MM-DD[ HH:MM:SS[.ffffff]]' -> the column's int64 epoch
    value (same cast the values repr uses: pc.cast(col, int64) keeps
    the storage unit, so converting the LITERAL through the same arrow
    type makes the numeric compare exact)."""
    import datetime as _dt

    import pyarrow as pa
    import pyarrow.compute as pc

    try:
        dt = _dt.datetime.fromisoformat(literal)
    except ValueError as exc:
        raise PredicateParseError(
            f"{literal!r} is not a date/timestamp literal "
            "(YYYY-MM-DD[ HH:MM:SS])"
        ) from exc
    arrow_type = ds.arrow_type(column)
    value = dt.date() if pa.types.is_date(arrow_type) else dt
    arr = pa.array([value], type=arrow_type)
    if pa.types.is_date32(arrow_type):
        # Arrow has no date32->int64 kernel; hop through int32 — the
        # SAME two-step the values repr uses (convert_basic_repr), so
        # literal and column land in identical units (days)
        arr = pc.cast(arr, pa.int32())
    return int(pc.cast(arr, pa.int64())[0].as_py())


def _eval_stringish(node: Node, batch, ds):
    """Branch evaluation for CASE/COALESCE, where a bare string
    literal (or NULL) is a legal RESULT: literals become ('lit', s)
    markers instead of erroring, everything else evaluates."""
    if isinstance(node, StringLit):
        return ("lit", node.value)
    if isinstance(node, NullLit):
        return ("null",)
    return _eval(node, batch, ds)


def _any_stringish(branches) -> bool:
    return any(
        (isinstance(b, tuple) and b[0] == "lit")
        or (isinstance(b, _Val) and _is_string_lane(b))
        for b in branches
    )


def _string_union(ds, branches):
    """Union synthetic dictionary over string branches + each branch
    as (union codes, valid). Branches: ('lit', s) | ('null',) | string
    _Val lanes (homogeneity is enforced at plan time; a numeric _Val
    here means the checker missed a case — refuse loudly)."""
    values: set = set()
    views: List[Optional[List[Optional[str]]]] = []
    for b in branches:
        if isinstance(b, tuple):
            views.append(None)
            if b[0] == "lit":
                values.add(b[1])
        elif _is_string_lane(b):
            view = _lane_entries(ds, b)
            views.append(view)
            values.update(v for v in view if v is not None)
        else:
            raise PredicateParseError(
                "CASE/COALESCE branches mix string and non-string "
                "results"
            )
    union = sorted(values)
    index = {v: i for i, v in enumerate(union)}
    out = []
    for b, view in zip(branches, views):
        if isinstance(b, tuple):
            if b[0] == "lit":
                out.append(
                    (jnp.int32(index[b[1]]), jnp.asarray(True))
                )
            else:
                out.append((jnp.int32(0), jnp.asarray(False)))
        else:
            lut = np.zeros(len(view) + 1, dtype=np.int32)
            for i, v in enumerate(view):
                if v is not None:
                    lut[i] = index[v]
            table = jnp.asarray(lut)
            idx = jnp.clip(
                jnp.where(b.values < 0, len(view), b.values),
                0,
                len(view),
            )
            out.append((table[idx], b.valid))
    return union, out


def _eval(node: Node, batch: Dict[str, jnp.ndarray], ds: Dataset) -> _Val:
    if isinstance(node, ColumnRef):
        kind = ds.schema.kind_of(node.name)
        mask = batch[f"{node.name}::mask"]
        if kind == Kind.STRING:
            return _Val(batch[f"{node.name}::codes"], mask, codes_of=node.name)
        vals = batch[f"{node.name}::values"]
        is_ts = kind == Kind.TIMESTAMP
        return _Val(
            vals,
            mask,
            is_bool=kind == Kind.BOOLEAN,
            ts_col=node.name if is_ts else None,
            ts_per_day=(
                _units_per_day(ds.arrow_type(node.name)) if is_ts else None
            ),
        )
    if isinstance(node, NumberLit):
        return _Val(jnp.asarray(node.value), jnp.asarray(True))
    if isinstance(node, BoolLit):
        return _Val(jnp.asarray(node.value), jnp.asarray(True), is_bool=True)
    if isinstance(node, NullLit):
        return _Val(jnp.asarray(0.0), jnp.asarray(False))
    if isinstance(node, StringLit):
        # bare string literal only makes sense inside comparisons, which
        # special-case it; standing alone it is an error
        raise PredicateParseError(
            f"string literal {node.value!r} outside comparison"
        )
    if isinstance(node, UnaryOp):
        if node.op == "NEG":
            v = _eval(node.operand, batch, ds)
            return _Val(-v.values, v.valid)
        truth, valid = _as_bool(_eval(node.operand, batch, ds))
        return _Val(~truth, valid, is_bool=True)
    if isinstance(node, IsNull):
        v = _eval(node.operand, batch, ds)
        res = v.valid if node.negate else ~v.valid
        return _Val(res, jnp.ones_like(res, dtype=bool), is_bool=True)
    if isinstance(node, Between):
        return _eval(
            BinOp(
                "AND",
                BinOp(">=", node.operand, node.low),
                BinOp("<=", node.operand, node.high),
            ),
            batch,
            ds,
        )
    if isinstance(node, Cast):
        v = _eval(node.operand, batch, ds)
        if node.type_name in _STRING_CASTS:
            if _is_string_lane(v):
                return v  # identity (transform/entries preserved)
            if v.is_bool:
                # Spark: cast(true AS STRING) = 'true'
                return _Val(
                    v.values.astype(jnp.int32),
                    v.valid,
                    entries=("false", "true"),
                )
            raise PredicateParseError(
                "CAST to STRING supports string and boolean operands "
                "only (numeric/timestamp formatting is not supported)"
            )
        integral = node.type_name in _INT_CASTS
        if v.ts_per_day is not None:
            # Spark: cast(timestamp AS BIGINT/DOUBLE) = epoch SECONDS
            # (floor for integral targets, then the same saturation
            # bounds every integral cast applies); date operands are
            # refused at plan time like Spark's analyzer does
            upd = v.ts_per_day // 86_400  # units per second
            raw = v.values.astype(jnp.int64)
            if integral:
                lo, hi = _INT_CAST_BOUNDS[node.type_name]
                vals = jnp.clip(
                    jnp.floor_divide(raw, jnp.int64(upd)).astype(
                        jnp.float64
                    ),
                    lo,
                    hi,
                )
            else:
                vals = raw.astype(jnp.float64) / float(upd)
            return _Val(vals, v.valid)
        if _is_string_lane(v):
            # string lane: parse each dictionary entry ONCE
            # (Spark cast semantics: unparseable -> NULL). Validity
            # lives in its OWN table — overloading NaN as the invalid
            # sentinel would misreport an entry 'NaN' (which Spark
            # casts to the VALUE NaN) as NULL (r4 advisory).
            view = _lane_entries(ds, v)
            table = np.zeros(len(view) + 1)
            ok = np.zeros(len(view) + 1, dtype=bool)
            for i, s in enumerate(view):
                if s is not None:
                    text = s.strip()
                    if "_" in text:  # Python-only numeric syntax
                        continue  # ('1_0'); Spark casts it to NULL
                    try:
                        table[i] = float(text)
                        ok[i] = True
                    except ValueError:
                        pass
            lut = jnp.asarray(table)
            ok_lut = jnp.asarray(ok)
            idx = jnp.clip(
                jnp.where(v.values < 0, len(view), v.values),
                0,
                len(view),
            )
            vals = lut[idx]
            valid = v.valid & ok_lut[idx]
            vals = jnp.where(valid, vals, 0.0)
            if integral:
                # a string with no finite numeric value has no
                # integral parse -> NULL (Spark's string-to-int cast
                # rejects 'NaN'/'Infinity'; review finding on the r4
                # validity-table fix); finite parses saturate at the
                # target bounds like the numeric-source path — one
                # consistent JVM-d2i cast model (review finding r5)
                finite = jnp.isfinite(vals)
                valid = valid & finite
                lo, hi = _INT_CAST_BOUNDS[node.type_name]
                vals = jnp.clip(
                    jnp.trunc(jnp.where(finite, vals, 0.0)), lo, hi
                )
            return _Val(vals, valid)
        vals = v.values.astype(jnp.float64)
        valid = v.valid
        if integral:
            # numeric source follows JVM double-to-int conversion like
            # non-ANSI Spark: truncate toward zero, SATURATE at the
            # target bounds, NaN -> 0 (NOT NULL — review finding)
            lo, hi = _INT_CAST_BOUNDS[node.type_name]
            vals = jnp.clip(jnp.trunc(vals), lo, hi)
            vals = jnp.where(jnp.isnan(vals), 0.0, vals)
        return _Val(vals, valid)
    if isinstance(node, CaseWhen):
        # SQL: first branch whose condition is TRUE wins (NULL
        # conditions skip); no match and no ELSE -> NULL. Folded in
        # reverse so earlier branches override later ones. String-
        # valued results (homogeneous, enforced at plan time) fold the
        # same way over codes into a UNION synthetic dictionary.
        branches = [
            (cond, _eval_stringish(r, batch, ds))
            for cond, r in node.whens
        ]
        else_b = (
            _eval_stringish(node.else_, batch, ds)
            if node.else_ is not None
            else ("null",)
        )
        if _any_stringish([b for _, b in branches] + [else_b]):
            union, codes_of_branch = _string_union(
                ds, [b for _, b in branches] + [else_b]
            )
            vals, valid = codes_of_branch[-1]
            for (cond, _), (bc, bv) in zip(
                reversed(branches), reversed(codes_of_branch[:-1])
            ):
                ct, cv = _as_bool(_eval(cond, batch, ds))
                hit = ct & cv
                vals = jnp.where(hit, bc, vals)
                valid = jnp.where(hit, bv, valid)
            return _Val(vals, valid, entries=tuple(union))

        # numeric fold, REUSING the already-evaluated branches (a
        # ('null',) marker is an invalid slot); branch values coerce
        # to f64 (SQL promotes mixed numeric/bool CASE branches)
        def as_num(b):
            if isinstance(b, tuple):  # ('null',)
                return jnp.asarray(0.0), jnp.asarray(False)
            return jnp.asarray(b.values, dtype=jnp.float64), b.valid

        vals, valid = as_num(else_b)
        for (cond, _), b in zip(reversed(node.whens), reversed(branches)):
            ct, cv = _as_bool(_eval(cond, batch, ds))
            hit = ct & cv
            bv, bok = as_num(b[1])
            vals = jnp.where(hit, bv, vals)
            valid = jnp.where(hit, bok, valid)
        return _Val(vals, valid)
    if isinstance(node, InList):
        base = _eval(node.operand, batch, ds)
        truth = jnp.zeros_like(base.values, dtype=bool)
        has_null_item = False
        for item in node.items:
            if isinstance(item, NullLit):
                # SQL: x IN (..., NULL) is TRUE on a match, else NULL
                has_null_item = True
            elif isinstance(item, StringLit):
                if not _is_string_lane(base):
                    raise PredicateParseError(
                        "IN with string literals requires a string column"
                    )
                if base.transform is not None or base.entries is not None:
                    truth = truth | _string_eq_lut(ds, base, item.value)
                else:
                    code = _dict_lookup(ds, base.codes_of, item.value)
                    truth = truth | (base.values == code)
            else:
                rhs = _eval(item, batch, ds)
                truth = truth | ((base.values == rhs.values) & rhs.valid)
        valid = base.valid
        if has_null_item:
            valid = valid & truth  # non-matches become NULL
        if node.negate:
            truth = ~truth
        return _Val(truth, valid, is_bool=True)
    if isinstance(node, Like):
        base = _eval(node.operand, batch, ds)
        if not _is_string_lane(base):
            raise PredicateParseError("LIKE requires a string column")
        view = _lane_entries(ds, base)
        pattern = (
            node.pattern if node.regex else _sql_like_to_regex(node.pattern)
        )
        prog = re.compile(pattern)
        table = np.zeros(len(view) + 1, dtype=bool)
        for i, s in enumerate(view):
            if s is not None and prog.search(s):
                table[i] = True
        lut = jnp.asarray(table)
        truth = lut[jnp.clip(base.values, -1, len(view) - 1)]
        truth = jnp.where(base.values < 0, False, truth)
        if node.negate:
            truth = ~truth
        return _Val(truth, base.valid, is_bool=True)
    if isinstance(node, FuncCall):
        if node.name == "ABS" and len(node.args) == 1:
            v = _eval(node.args[0], batch, ds)
            return _Val(jnp.abs(v.values), v.valid)
        if node.name == "COALESCE":
            if not node.args:
                raise PredicateParseError("COALESCE needs arguments")
            branches = [
                _eval_stringish(a, batch, ds) for a in node.args
            ]
            if _any_stringish(branches):
                union, pairs = _string_union(ds, branches)
                vals, valid = pairs[0]
                for code, ok in pairs[1:]:
                    vals = jnp.where(valid, vals, code)
                    valid = valid | ok
                return _Val(vals, valid, entries=tuple(union))
            parts = [
                b if isinstance(b, _Val)
                else _Val(jnp.asarray(0.0), jnp.asarray(False))
                for b in branches
            ]
            vals = parts[0].values
            valid = parts[0].valid
            for p in parts[1:]:
                vals = jnp.where(valid, vals, p.values)
                valid = valid | p.valid
            return _Val(
                vals, valid, is_bool=all(p.is_bool for p in parts)
            )
        if node.name == "LENGTH" and len(node.args) == 1:
            arg = node.args[0]
            if isinstance(arg, ColumnRef):
                mask = batch[f"{arg.name}::mask"]
                return _Val(batch[f"{arg.name}::lengths"], mask)
            # LENGTH over a transformed string expression: per-code
            # i32 LUT of len(view(dict[i])), gathered by code
            v = _eval(arg, batch, ds)
            if not _is_string_lane(v):
                raise PredicateParseError(
                    "LENGTH expects a string column or string function"
                )
            view = _lane_entries(ds, v)
            table = np.zeros(len(view) + 1, dtype=np.int32)
            for i, s in enumerate(view):
                if s is not None:
                    table[i] = len(s)
            lut = jnp.asarray(table)
            idx = jnp.where(v.values < 0, len(view), v.values)
            return _Val(
                lut[jnp.clip(idx, 0, len(view))], v.valid
            )
        if node.name in ("DATE_ADD", "DATE_SUB"):
            v = _eval(node.args[0], batch, ds)
            if v.ts_per_day is None:
                raise PredicateParseError(
                    f"{node.name} requires a timestamp/date column"
                )
            n_days = _static_int(node.args[1], f"{node.name} day count")
            if node.name == "DATE_SUB":
                n_days = -n_days
            # Spark's date_add casts to DATE first: the result is
            # DAY-valued (time-of-day truncates), so equality against
            # date literals behaves like Spark's
            days = jnp.floor_divide(
                v.values.astype(jnp.int64), jnp.int64(v.ts_per_day)
            )
            return _Val(
                days + jnp.int64(n_days), v.valid, ts_per_day=1
            )
        if node.name == "DATEDIFF":
            def days_of(arg):
                if isinstance(arg, StringLit):
                    return (
                        jnp.int64(_epoch_days_of_literal(arg.value)),
                        jnp.asarray(True),
                    )
                v = _eval(arg, batch, ds)
                if v.ts_per_day is None:
                    raise PredicateParseError(
                        "DATEDIFF arguments must be timestamp columns "
                        "or date literals"
                    )
                return (
                    jnp.floor_divide(
                        v.values.astype(jnp.int64),
                        jnp.int64(v.ts_per_day),
                    ),
                    v.valid,
                )

            end_days, end_valid = days_of(node.args[0])
            start_days, start_valid = days_of(node.args[1])
            return _Val(end_days - start_days, end_valid & start_valid)
        if node.name == "CONCAT":
            lanes: List[Tuple[str, object]] = []
            for a in node.args:
                if isinstance(a, StringLit):
                    lanes.append(("lit", a.value))
                else:
                    v = _eval(a, batch, ds)
                    if not _is_string_lane(v):
                        raise PredicateParseError(
                            "CONCAT arguments must be strings"
                        )
                    lanes.append(("lane", v))
            n_lanes = sum(1 for k, _ in lanes if k == "lane")
            if n_lanes == 0:
                raise PredicateParseError(
                    "CONCAT of only literals is constant"
                )
            if n_lanes == 1 and all(
                k == "lit" or v.codes_of is not None for k, v in lanes
            ):
                # one COLUMN-BACKED lane: literals fold into its lazy
                # transform — no synthetic dictionary needed
                col_val = next(v for k, v in lanes if k == "lane")
                inner = col_val.view
                parts = tuple(
                    v if k == "lit" else None for k, v in lanes
                )

                def transform(s, _parts=parts, _inner=inner):
                    return "".join(
                        _inner(s) if p is None else p for p in _parts
                    )

                return _Val(
                    col_val.values,
                    col_val.valid,
                    codes_of=col_val.codes_of,
                    transform=transform,
                )
            # MULTI-column (or synthetic-lane) CONCAT: fold lanes into
            # a cross-product synthetic dictionary (size bounded at
            # plan time by _check_plan_budgets); row code = left_code
            # * |right| + right_code; NULL if ANY operand is null
            # (Spark's concat)
            acc_entries: Optional[List[Optional[str]]] = None
            acc_codes = None
            acc_valid = None
            pending = ""
            for k, v in lanes:
                if k == "lit":
                    if acc_entries is None:
                        pending += v
                    else:
                        acc_entries = [
                            None if e is None else e + v
                            for e in acc_entries
                        ]
                    continue
                view = _lane_entries(ds, v)
                L = len(view)
                codes = jnp.clip(
                    jnp.where(v.values < 0, 0, v.values), 0, L - 1
                ).astype(jnp.int32)
                if acc_entries is None:
                    acc_entries = [
                        None if e is None else pending + e
                        for e in view
                    ]
                    pending = ""
                    acc_codes = codes
                    acc_valid = v.valid
                else:
                    acc_entries = [
                        (
                            None
                            if ea is None or eb is None
                            else ea + eb
                        )
                        for ea in acc_entries
                        for eb in view
                    ]
                    acc_codes = acc_codes * jnp.int32(L) + codes
                    acc_valid = acc_valid & v.valid
            return _Val(
                acc_codes, acc_valid, entries=tuple(acc_entries)
            )
        if node.name in _STRING_FNS:
            return _eval_string_fn(node, batch, ds)
        raise PredicateParseError(f"unsupported function {node.name}")
    if isinstance(node, BinOp):
        if node.op in ("AND", "OR"):
            lt, lv = _as_bool(_eval(node.left, batch, ds))
            rt, rv = _as_bool(_eval(node.right, batch, ds))
            if node.op == "AND":
                truth = lt & rt
                # SQL 3VL: FALSE AND NULL = FALSE (valid)
                valid = (lv & rv) | (lv & ~lt) | (rv & ~rt)
            else:
                truth = lt | rt
                # TRUE OR NULL = TRUE (valid)
                valid = (lv & rv) | (lv & lt) | (rv & rt)
            return _Val(truth, valid, is_bool=True)
        # comparisons involving string literals: =/!= compare raw codes
        # (one O(n) dictionary lookup, scalar compare); orderings need
        # lexicographic ranks — codes are in order of appearance
        if node.op in _CMP and (
            isinstance(node.left, StringLit) or isinstance(node.right, StringLit)
        ):
            lit_on_right = isinstance(node.right, StringLit)
            col_node, lit = (
                (node.left, node.right)
                if lit_on_right
                else (node.right, node.left)
            )
            base = _eval(col_node, batch, ds)
            if base.ts_per_day is not None:
                # timestamp/date lane vs date literal: the literal
                # converts to the lane's epoch unit at trace time (via
                # the exact Arrow cast for raw columns; as UTC days
                # for day-valued DATE_ADD results); the device compare
                # stays numeric
                if base.ts_col is not None:
                    epoch = _date_literal_epoch(
                        ds, base.ts_col, lit.value
                    )
                else:
                    epoch = _epoch_days_of_literal(lit.value)
                lv, rv = (
                    (base.values, epoch)
                    if lit_on_right
                    else (epoch, base.values)
                )
                return _Val(
                    _CMP_FNS[node.op](lv, rv), base.valid, is_bool=True
                )
            if not _is_string_lane(base):
                raise PredicateParseError(
                    "string comparison requires a string column"
                )
            if node.op in ("=", "!="):
                if base.transform is not None or base.entries is not None:
                    truth = _string_eq_lut(ds, base, lit.value)
                else:
                    code = _dict_lookup(ds, base.codes_of, lit.value)
                    truth = base.values == code
                if node.op == "!=":
                    truth = ~truth
                return _Val(truth, base.valid, is_bool=True)
            ranks, lit_rank = _rank_lut_with_literal(
                ds, base, lit.value
            )
            col_ranks = _gather_ranks(ranks, base.values)
            lv, rv = (
                (col_ranks, lit_rank) if lit_on_right else (lit_rank, col_ranks)
            )
            return _Val(_CMP_FNS[node.op](lv, rv), base.valid, is_bool=True)
        lhs = _eval(node.left, batch, ds)
        rhs = _eval(node.right, batch, ds)
        valid = lhs.valid & rhs.valid
        lv, rv = lhs.values, rhs.values
        if (
            node.op in _CMP
            and lhs.ts_per_day is not None
            and rhs.ts_per_day is not None
            and lhs.ts_per_day != rhs.ts_per_day
        ):
            # mixed-unit timestamp lanes (timestamp[us] vs date32, or
            # a day-valued DATE_ADD vs a raw column): scale the coarser
            # side up to the finer unit so epochs compare as instants
            # (comparing raw epochs across units would be silently
            # wrong — r4 review finding)
            if lhs.ts_per_day < rhs.ts_per_day:
                lv = lv.astype(jnp.int64) * jnp.int64(
                    rhs.ts_per_day // lhs.ts_per_day
                )
            else:
                rv = rv.astype(jnp.int64) * jnp.int64(
                    lhs.ts_per_day // rhs.ts_per_day
                )
        if node.op in _CMP:
            if _is_string_lane(lhs) and _is_string_lane(rhs):
                # two string columns: dictionary codes come from
                # UNRELATED dictionaries (and even one dictionary is in
                # order of appearance, not sorted) — remap both sides to
                # ranks in a shared sorted value domain so =/!= and
                # lexicographic ordering are exact
                lut_l, lut_r = _shared_rank_luts(ds, lhs, rhs)
                lv = _gather_ranks(lut_l, lv)
                rv = _gather_ranks(lut_r, rv)
            elif _is_string_lane(lhs) != _is_string_lane(rhs):
                raise PredicateParseError(
                    "cannot compare a string column with a non-string "
                    "operand (dictionary codes are not values)"
                )
            return _Val(_CMP_FNS[node.op](lv, rv), valid, is_bool=True)
        if _is_string_lane(lhs) or _is_string_lane(rhs):
            raise PredicateParseError(
                f"arithmetic {node.op!r} is undefined for string columns"
            )
        if node.op == "+":
            return _Val(lv + rv, valid)
        if node.op == "-":
            return _Val(lv - rv, valid)
        if node.op == "*":
            return _Val(lv * rv, valid)
        if node.op == "/":
            denom_ok = rv != 0
            safe = jnp.where(denom_ok, rv, 1)
            return _Val(lv / safe, valid & denom_ok)
        if node.op == "%":
            denom_ok = rv != 0
            safe = jnp.where(denom_ok, rv, 1)
            return _Val(lv % safe, valid & denom_ok)
    raise PredicateParseError(f"cannot evaluate node {node!r}")
