"""Column profiling pipeline.

Reference: ``src/main/scala/com/amazon/deequ/profiles/`` (SURVEY.md
§2.5, §3.3) runs THREE passes (generic stats; numeric stats; low-card
histograms). Here the structure is tighter:

- PASS 1 — one fused scan over ALL columns: Completeness,
  ApproxCountDistinct, DataType (string columns), AND the numeric stats
  (Mean/Max/Min/Sum/StdDev + optional KLL) for schema-native numeric
  columns — those need nothing from pass 1's outputs, so fusing them
  saves a whole data pass vs the reference (a full re-read on
  streaming sources);
- type inference promotes numeric-looking string columns; an OPTIONAL
  extra scan computes numeric stats for just those promoted columns;
- HISTOGRAM PASS — for columns whose approx distinct count is below
  the low-cardinality threshold (default 120); all histograms share
  ONE scan (compute_many_frequencies), defusing the reference's
  pass-3 job explosion (SURVEY.md §7 hard part #6).

This is the north-star benchmark workload (BASELINE.md).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import numpy as np
import pyarrow as pa

from deequ_tpu.analyzers import (
    AnalysisRunner,
    AnalyzerContext,
    ApproxCountDistinct,
    ApproxQuantiles,
    Completeness,
    DataType,
    Histogram,
    KLLSketch,
    Maximum,
    Mean,
    Minimum,
    Size,
    StandardDeviation,
    Sum,
)
from deequ_tpu.analyzers.datatype import inferred_kind
from deequ_tpu.data.table import ColumnRequest, Dataset, Kind
from deequ_tpu.engine.scan import AnalysisEngine
from deequ_tpu.metrics.distribution import Distribution
from deequ_tpu.metrics.kll import BucketDistribution
from deequ_tpu.sketches.kll import KLLParameters

DEFAULT_LOW_CARDINALITY_THRESHOLD = 120
_PERCENTILES = tuple(round(q / 100.0, 2) for q in range(1, 100))


@dataclass
class StandardColumnProfile:
    column: str
    completeness: float
    approximate_num_distinct_values: float
    data_type: Kind
    is_data_type_inferred: bool
    type_counts: Dict[str, int] = field(default_factory=dict)
    histogram: Optional[Distribution] = None


@dataclass
class NumericColumnProfile(StandardColumnProfile):
    mean: Optional[float] = None
    maximum: Optional[float] = None
    minimum: Optional[float] = None
    sum: Optional[float] = None
    std_dev: Optional[float] = None
    approx_percentiles: Optional[List[float]] = None
    kll: Optional[BucketDistribution] = None


@dataclass
class ColumnProfiles:
    profiles: Dict[str, StandardColumnProfile]
    num_records: int
    run_metadata: Optional["object"] = None  # utils.observe.RunMetadata
    telemetry: Optional[dict] = None  # merged telemetry run summary
    # engine.deadline.ScanInterruption when profiling was cancelled or
    # ran out of deadline — the passes after the interrupt were skipped
    # and their profile fields are None; None = profiled to completion
    interruption: Optional[object] = None

    def __getitem__(self, column: str) -> StandardColumnProfile:
        return self.profiles[column]


class ColumnProfiler:
    @staticmethod
    def profile(
        data: Dataset,
        restrict_to_columns: Optional[Sequence[str]] = None,
        low_cardinality_histogram_threshold: int = DEFAULT_LOW_CARDINALITY_THRESHOLD,
        kll_profiling: bool = False,
        kll_parameters: Optional[KLLParameters] = None,
        engine: Optional[AnalysisEngine] = None,
        deadline=None,
        cancel=None,
    ) -> ColumnProfiles:
        """Profile all columns. ``deadline`` (seconds or a
        ``RunBudget``) and ``cancel`` (a ``CancelToken``) bound the
        WHOLE profile — the multi-pass loop shares ONE envelope
        (``RunBudget.start()`` pins the epoch on first use and is
        idempotent), so pass 2/3 inherit whatever pass 1 left; a pass
        interrupted mid-scan ends the loop and the remaining passes are
        skipped, with the provenance on ``profiles.interruption``."""
        engine = engine or AnalysisEngine()
        if deadline is not None:
            from deequ_tpu import config
            from deequ_tpu.engine.deadline import RunBudget

            if not isinstance(deadline, RunBudget):
                deadline = RunBudget(
                    deadline_s=float(deadline),
                    stall_s=config.options().batch_stall_seconds or None,
                )
        columns = list(restrict_to_columns or data.schema.column_names)
        for c in columns:
            if not data.schema.has_column(c):
                raise KeyError(f"unknown column {c!r}")

        # ---- PASS 1: generic stats, one fused scan -------------------
        # numeric stats for SCHEMA-native numeric columns ride the same
        # scan (they need nothing from pass 1's outputs); the separate
        # pass 2 below only handles promoted string columns, so a
        # streaming source is read once less than the reference's
        # 3-pass structure (SURVEY.md §3.3)
        params = kll_parameters or KLLParameters()

        def numeric_analyzers(cols: Sequence[str]) -> List:
            out: List = []
            for c in cols:
                out += [
                    Mean(c), Maximum(c), Minimum(c), Sum(c),
                    StandardDeviation(c),
                    # approx percentiles are part of the DEFAULT numeric
                    # profile (reference pass 2 computes ApproxQuantiles
                    # unconditionally, SURVEY.md §3.3); the full KLL
                    # bucket distribution stays opt-in. Same params =>
                    # the vectorized KLL group computes ONE sketch per
                    # column serving both analyzers.
                    ApproxQuantiles(c, _PERCENTILES, params=params),
                ]
                if kll_profiling:
                    out.append(KLLSketch(c, params))
            return out

        numeric_native = [
            c for c in columns if data.schema.kind_of(c).is_numeric
        ]
        pass1: List = [Size()]
        # string/bool columns whose dictionary is provably small get
        # their histogram SPECULATIVELY in pass 1: the dictionary is
        # built for codes anyway (HLL/DataType request them), the dense
        # frequency counts fuse into the same scan, and the histogram
        # pass below then usually has nothing left — ONE streamed read
        # of the source instead of two (the 1B-row workload can only
        # ever run streamed). The probe bails early for big
        # dictionaries, and the ATTACH gate below stays the reference's
        # approx-distinct test, so which histograms ship is unchanged.
        pass1_histograms: List[str] = []
        for c in columns:
            kind_c = data.schema.kind_of(c)
            if kind_c in (Kind.STRING, Kind.BOOLEAN):
                try:
                    size = data.dictionary_size_within(
                        c, low_cardinality_histogram_threshold
                    )
                except Exception:  # noqa: BLE001 — odd column: pass 3
                    size = None
                if size is not None:
                    pass1_histograms.append(c)
            elif kind_c == Kind.INTEGRAL:
                # r5: a bounded VALUE RANGE (one O(1) min/max probe,
                # free from parquet statistics) bounds the distinct
                # count, so quantity-style integer histograms ride
                # pass 1's fused scan too — a streamed 1B-row profile
                # then reads its source once less (pass 3 previously
                # re-scanned for exactly these columns)
                try:
                    rng_c = data.integral_range(c)
                except Exception:  # noqa: BLE001 — odd column: pass 3
                    rng_c = None
                if rng_c is not None and (
                    rng_c[1] - rng_c[0]
                ) < low_cardinality_histogram_threshold:
                    pass1_histograms.append(c)
        for c in columns:
            pass1.append(Completeness(c))
            pass1.append(ApproxCountDistinct(c))
            if data.schema.kind_of(c) == Kind.STRING:
                pass1.append(DataType(c))
        pass1 += [Histogram(c) for c in pass1_histograms]
        pass1 += numeric_analyzers(numeric_native)
        ctx1 = AnalysisRunner.do_analysis_run(
            data, pass1, engine=engine, deadline=deadline, cancel=cancel
        )
        interruption = ctx1.interruption

        num_records = int(ctx1.metric(Size()).value.get_or_else(0.0))
        completeness: Dict[str, float] = {}
        approx_distinct: Dict[str, float] = {}
        kinds: Dict[str, Kind] = {}
        inferred: Dict[str, bool] = {}
        type_counts: Dict[str, Dict[str, int]] = {}
        for c in columns:
            completeness[c] = float(
                ctx1.metric(Completeness(c)).value.get_or_else(0.0)
            )
            approx_distinct[c] = float(
                ctx1.metric(ApproxCountDistinct(c)).value.get_or_else(0.0)
            )
            schema_kind = data.schema.kind_of(c)
            if schema_kind == Kind.STRING:
                metric = ctx1.metric(DataType(c))
                if metric is not None and metric.value.is_success:
                    kinds[c] = inferred_kind(metric)
                    inferred[c] = True
                    type_counts[c] = {
                        k: v.absolute
                        for k, v in metric.value.get().values.items()
                    }
                else:
                    kinds[c] = Kind.STRING
                    inferred[c] = False
                    type_counts[c] = {}
            else:
                kinds[c] = schema_kind
                inferred[c] = False
                type_counts[c] = {}

        # ---- PASS 2: promoted string columns only --------------------
        numeric_promoted = [
            c
            for c in columns
            if data.schema.kind_of(c) == Kind.STRING
            and kinds[c] in (Kind.INTEGRAL, Kind.FRACTIONAL)
        ]
        promoted_ctx = None
        ctx2 = ctx1
        # an interrupted pass ends the loop: later passes never start
        # (their scans would each burn a batch discovering the dead
        # envelope) — the assembled profiles just lack those fields
        if numeric_promoted and interruption is None:
            promoted_data = _cast_string_columns(data, numeric_promoted)
            promoted_ctx = AnalysisRunner.do_analysis_run(
                promoted_data, numeric_analyzers(numeric_promoted),
                engine=engine, deadline=deadline, cancel=cancel,
            )
            ctx2 = ctx1 + promoted_ctx
            interruption = ctx2.interruption

        # ---- PASS 3: histograms for low-cardinality columns ----------
        # (ALL histograms share one scan via compute_many_frequencies;
        # columns speculatively histogrammed in pass 1 are excluded, so
        # this pass usually only remains for low-cardinality INTEGER
        # columns — the gate itself is unchanged from the reference)
        histogram_columns = [
            c
            for c in columns
            if approx_distinct[c] <= low_cardinality_histogram_threshold
            and kinds[c] in (Kind.STRING, Kind.BOOLEAN, Kind.INTEGRAL)
        ]
        pass3_columns = [
            c for c in histogram_columns if c not in pass1_histograms
        ]
        if pass3_columns and interruption is None:
            ctx3 = AnalysisRunner.do_analysis_run(
                data, [Histogram(c) for c in pass3_columns],
                engine=engine, deadline=deadline, cancel=cancel,
            )
            interruption = ctx3.interruption or interruption
        else:
            ctx3 = AnalyzerContext({})

        # ---- assemble -------------------------------------------------
        profiles: Dict[str, StandardColumnProfile] = {}
        for c in columns:
            histogram = None
            if c in histogram_columns:  # the reference's approx gate
                source = ctx1 if c in pass1_histograms else ctx3
                metric = source.metric(Histogram(c))
                if metric is not None and metric.value.is_success:
                    histogram = metric.value.get()
            base = dict(
                column=c,
                completeness=completeness[c],
                approximate_num_distinct_values=approx_distinct[c],
                data_type=kinds[c],
                is_data_type_inferred=inferred[c],
                type_counts=type_counts[c],
                histogram=histogram,
            )
            if kinds[c].is_numeric:
                def metric_value(analyzer):
                    m = ctx2.metric(analyzer)
                    if m is None or m.value.is_failure:
                        return None
                    return m.value.get()

                target = c
                percentiles = None
                kll_dist = None
                quantiles = metric_value(
                    ApproxQuantiles(target, _PERCENTILES, params=params)
                )
                if quantiles is not None:
                    percentiles = [
                        quantiles[str(q)] for q in _PERCENTILES
                    ]
                if kll_profiling:
                    kll_dist = metric_value(KLLSketch(target, params))
                profiles[c] = NumericColumnProfile(
                    **base,
                    mean=metric_value(Mean(target)),
                    maximum=metric_value(Maximum(target)),
                    minimum=metric_value(Minimum(target)),
                    sum=metric_value(Sum(target)),
                    std_dev=metric_value(StandardDeviation(target)),
                    approx_percentiles=percentiles,
                    kll=kll_dist,
                )
            else:
                profiles[c] = StandardColumnProfile(**base)
        from deequ_tpu.telemetry import merge_summaries
        from deequ_tpu.utils.observe import RunMetadata

        metadata = ctx1.run_metadata
        if promoted_ctx is not None:
            metadata = RunMetadata.merge_optional(
                metadata, promoted_ctx.run_metadata
            )
        metadata = RunMetadata.merge_optional(metadata, ctx3.run_metadata)
        telemetry = merge_summaries(
            [
                ctx1.telemetry,
                None if promoted_ctx is None else promoted_ctx.telemetry,
                getattr(ctx3, "telemetry", None),
            ]
        )
        return ColumnProfiles(
            profiles, num_records, run_metadata=metadata,
            telemetry=telemetry, interruption=interruption,
        )


def _cast_string_columns(data: Dataset, columns: Sequence[str]) -> Dataset:
    """Numeric view of numeric-looking string columns: parse the (small)
    dictionary host-side, then gather by code — the string data itself is
    never re-scanned (SURVEY.md §3.3 'cast a projected copy')."""
    arrays = {}
    for c in columns:
        dictionary = data.dictionary(c)
        parsed = np.full(len(dictionary) + 1, np.nan)
        for i, v in enumerate(dictionary):
            if v is None:
                continue
            try:
                parsed[i] = float(str(v).strip())
            except ValueError:
                parsed[i] = np.nan
        codes = data.materialize(ColumnRequest(c, "codes"))
        values = parsed[np.where(codes < 0, len(dictionary), codes)]
        arrays[c] = pa.array(
            values, pa.float64(), mask=np.isnan(values)
        )  # unparseable/null -> SQL NULL
    table = pa.table(
        {c: arrays[c] for c in columns}
    )
    out = Dataset.from_arrow(table)
    return out
